"""Health-routed replica fleet: the serving resilience tier (ISSUE 12).

PR 11 built the replica health registry (`/healthz?verbose=1`) as "the
interface a load-balancing router and autoscaler will poll" — this
module is the router that polls it. A FleetRouter load-balances
:predict traffic across N model-server replicas and keeps the fleet
available through the failures one replica WILL have:

- **Health-routed picks.** A background poller reads each replica's
  verbose healthz (queue depth, rolling p99, in-flight, `draining`,
  `uptimeSeconds`); requests go to the least-loaded live replica —
  the pick score weighs queue depth and rolling p99 (the two signals
  the ROADMAP names), never a draining or breaker-open replica.
- **Per-replica circuit breakers.** Failure evidence (connect
  failures, timeouts, 5xx, polled burn rates) folds through the SAME
  exponential-decay scoring shape as the node-health quarantine
  (scheduler/health.py fold_event — PR 6's pattern applied per serving
  replica): at the trip threshold the replica is ejected; after a
  cooldown it goes **half-open** and one probe request at a time is
  admitted; consecutive probe successes (with the score decayed below
  the release threshold) close it again, a probe failure re-opens it
  with the cooldown extended. A manual ejection (`eject(manual=True)`,
  the operator's kubectl analog) is never auto-released.
- **Failover retries under a deadline budget.** Connect failures,
  timeouts, and 5xx re-route to a DIFFERENT replica with jittered
  exponential backoff (Retry-After honored — cluster/http_client.py's
  bounded-retry shape), all inside one per-request deadline propagated
  downstream as the ``x-request-deadline`` header: retrying can never
  spend longer than the client asked for. 4xx is meaning, not
  weather — surfaced, never retried.
- **Tail hedging** (optional). When the first attempt outlives a
  p99-derived delay, a duplicate fires at a second replica; the first
  response wins and the loser's duplicated upstream work is ledgered
  as ``hedge_waste`` badput (obs/goodput.py) — named waste, never
  silent residual.
- **Drain awareness.** A replica advertising ``draining`` stops
  receiving new work before its pod dies (http_server.py drain()).

Every retry/hedge/ejection/drain lands a span event on the request
trace (one ``fleet-request`` summary per routed request, carrying the
fleet ledger) and a ``kftpu_fleet_*`` metric; per-replica series are
pruned on remove_replica (the model-unload prune rule). jax-free —
the router runs beside the client, in a gateway pod, or in-process
with the soak (cluster/chaos.py ServingSoak).
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass
from typing import Optional

from ..cluster.http_client import jittered_backoff, retry_after_s
from ..obs import goodput as gp
from ..obs import trace as obstrace
from ..obs.registry import Registry
from ..scheduler.health import fold_event
from .request_trace import (DEADLINE_HEADER, REQUEST_ID_HEADER,
                            mint_request_id)

log = logging.getLogger(__name__)

# breaker evidence kinds and weights: the scheduler/health.py
# EVENT_WEIGHTS shape with the serving failure vocabulary. Hard
# transport evidence (a connection that died, a replica that never
# answered) weighs full; a 5xx is weaker (could be one bad request),
# a shed 429 and a polled burn-rate breach weaker still (load, not
# sickness — the breaker must not eject a merely-busy replica).
EVIDENCE_CONNECT = "connect-failure"
EVIDENCE_TIMEOUT = "timeout"
EVIDENCE_5XX = "5xx"
EVIDENCE_SHED = "shed"
EVIDENCE_BURN = "burn-rate"

FLEET_EVIDENCE_WEIGHTS = {
    EVIDENCE_CONNECT: 1.0,
    EVIDENCE_TIMEOUT: 1.0,
    EVIDENCE_5XX: 0.5,
    EVIDENCE_SHED: 0.25,
    EVIDENCE_BURN: 0.25,
}

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
_BREAKER_STATE_CODE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                       BREAKER_OPEN: 2}


class FleetError(RuntimeError):
    """Base class for fleet routing failures."""


class NoReplicaAvailableError(FleetError):
    """Every replica is draining, ejected, or removed."""


class DeadlineExceededError(FleetError):
    """The request's deadline budget ran out before a success."""


class RetriesExhaustedError(FleetError):
    """The retry budget ran out; carries the last upstream error."""


class RequestRejectedError(FleetError):
    """A 4xx from the replica: meaning, not weather — never retried."""

    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class _Retryable(Exception):
    """Internal: one failed attempt that may re-route."""

    def __init__(self, kind: str, detail: str,
                 retry_after: Optional[float] = None):
        super().__init__(detail)
        self.kind = kind
        self.retry_after = retry_after
        # True when breaker evidence + the retry metric were already
        # charged to the failing replica (the hedged path does its own
        # per-replica accounting)
        self.recorded = False


@dataclass
class BreakerConfig:
    """Per-replica breaker policy (the HealthConfig analog). The
    defaults suit second-scale serving failures — far faster than the
    node quarantine's minutes, same shape."""

    half_life_s: float = 30.0       # evidence decay half-life
    trip_threshold: float = 3.0     # decayed score that ejects
    release_threshold: float = 1.0  # probation: score must decay here
    open_s: float = 5.0             # cooldown before the first probe
    open_max_s: float = 60.0        # cap on the extended cooldown
    probe_successes: int = 2        # consecutive probe oks to close

    KEYS = ("halfLifeSeconds", "tripThreshold", "releaseThreshold",
            "openSeconds", "openMaxSeconds", "probeSuccesses")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "BreakerConfig":
        d = dict(d or {})
        unknown = set(d) - set(cls.KEYS)
        if unknown:
            # a typo'd knob must fail loudly, not silently default
            raise ValueError(
                f"unknown breaker config keys {sorted(unknown)}; "
                f"valid: {list(cls.KEYS)}")
        return cls(
            half_life_s=float(d.get("halfLifeSeconds", 30.0)),
            trip_threshold=float(d.get("tripThreshold", 3.0)),
            release_threshold=float(d.get("releaseThreshold", 1.0)),
            open_s=float(d.get("openSeconds", 5.0)),
            open_max_s=float(d.get("openMaxSeconds", 60.0)),
            probe_successes=int(d.get("probeSuccesses", 2)))

    def to_dict(self) -> dict:
        return {"halfLifeSeconds": self.half_life_s,
                "tripThreshold": self.trip_threshold,
                "releaseThreshold": self.release_threshold,
                "openSeconds": self.open_s,
                "openMaxSeconds": self.open_max_s,
                "probeSuccesses": self.probe_successes}


class CircuitBreaker:
    """One replica's breaker: evidence-decay scoring with probational
    half-open re-admission — PR 6's quarantine state machine per
    serving replica. Thread-safe; the router records evidence from
    request threads and reads state from the pick path."""

    def __init__(self, cfg: Optional[BreakerConfig] = None,
                 clock=time.monotonic):
        self.cfg = cfg or BreakerConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._rec = {"score": 0.0, "time": clock(), "events": 0,
                     "last": ""}
        self._state = BREAKER_CLOSED
        self._opened_at = 0.0
        self._open_for = self.cfg.open_s
        self._probe_inflight = False
        self._probe_oks = 0
        self._manual = False
        self.trips = 0

    # ------------------------------------------------------------ evidence

    def score(self, now: Optional[float] = None) -> float:
        now = self.clock() if now is None else now
        with self._lock:
            rec = dict(self._rec)
        age = max(0.0, now - rec["time"])
        return rec["score"] * 0.5 ** (
            age / max(self.cfg.half_life_s, 1e-9))

    def record_failure(self, kind: str,
                       weight: Optional[float] = None) -> bool:
        """Fold one failure event; returns True when this event TRIPS
        the breaker (closed → open, or a half-open probe failing)."""
        w = FLEET_EVIDENCE_WEIGHTS.get(kind, 1.0) \
            if weight is None else weight
        now = self.clock()
        with self._lock:
            self._rec = fold_event(self._rec, kind, now,
                                   half_life_s=self.cfg.half_life_s,
                                   weight=w)
            if self._state == BREAKER_HALF_OPEN:
                # a failed probe re-opens with the cooldown extended:
                # a still-failing replica earns a longer bench
                self._probe_inflight = False
                self._probe_oks = 0
                self._state = BREAKER_OPEN
                self._opened_at = now
                self._open_for = min(self.cfg.open_max_s,
                                     self._open_for * 2)
                self.trips += 1
                return True
            if self._state == BREAKER_CLOSED and \
                    self._rec["score"] >= self.cfg.trip_threshold:
                self._state = BREAKER_OPEN
                self._opened_at = now
                self._open_for = self.cfg.open_s
                self.trips += 1
                return True
        return False

    def record_success(self) -> bool:
        """One successful request; returns True when this CLOSES a
        half-open breaker (probation served)."""
        now = self.clock()
        with self._lock:
            if self._state != BREAKER_HALF_OPEN:
                return False
            self._probe_inflight = False
            self._probe_oks += 1
            if self._probe_oks < self.cfg.probe_successes:
                return False
        # probation needs BOTH: enough probe successes AND the decayed
        # score back under the release threshold (the node quarantine's
        # expiry-plus-decay rule)
        if self.score(now) > self.cfg.release_threshold:
            return False
        with self._lock:
            # re-check under the lock: a concurrent failure (poll
            # evidence) may have re-opened the breaker between the
            # score read and here — fresh failure evidence wins,
            # closing over it would re-admit a failing replica
            if self._state != BREAKER_HALF_OPEN:
                return False
            self._state = BREAKER_CLOSED
            self._probe_oks = 0
        return True

    # --------------------------------------------------------------- state

    def state(self, now: Optional[float] = None) -> str:
        now = self.clock() if now is None else now
        with self._lock:
            if self._state == BREAKER_OPEN and not self._manual and \
                    now - self._opened_at >= self._open_for:
                self._state = BREAKER_HALF_OPEN
                self._probe_oks = 0
                self._probe_inflight = False
            return self._state

    def allow_request(self, now: Optional[float] = None) -> bool:
        """Whether the pick path may route here NOW. Open: no.
        Half-open: one probe in flight at a time — probational
        re-admission, not a floodgate. Claims the probe slot when it
        grants one (try_probe); callers that merely INSPECT must use
        state()."""
        state = self.state(now)
        if state == BREAKER_CLOSED:
            return True
        if state == BREAKER_HALF_OPEN:
            return self.try_probe()
        return False

    def try_probe(self) -> bool:
        """Atomically claim the half-open probe slot (released by the
        probe's record_success/record_failure)."""
        state = self.state()   # open→half-open transition included
        with self._lock:
            if state != BREAKER_HALF_OPEN or \
                    self._state != BREAKER_HALF_OPEN or \
                    self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def release_probe(self) -> None:
        """Free the probe slot WITHOUT evidence — for a probe attempt
        abandoned unobserved (a hedge winner elsewhere). The next pick
        may probe again; a leaked slot would bench the replica
        forever."""
        with self._lock:
            self._probe_inflight = False

    def eject(self, manual: bool = False,
              reason: str = "ejected") -> None:
        """Force the breaker open. ``manual=True`` is a human's call —
        NEVER auto-released (the MANUAL_REASON rule); release needs
        an explicit release()."""
        now = self.clock()
        with self._lock:
            self._state = BREAKER_OPEN
            self._opened_at = now
            self._manual = self._manual or manual
            self._rec["last"] = reason
            self.trips += 1

    def release(self) -> None:
        """Explicit (human) release: back to closed, evidence cleared."""
        with self._lock:
            self._state = BREAKER_CLOSED
            self._manual = False
            self._probe_oks = 0
            self._probe_inflight = False
            self._rec = {"score": 0.0, "time": self.clock(),
                         "events": 0, "last": ""}

    @property
    def manual(self) -> bool:
        with self._lock:
            return self._manual

    def to_dict(self) -> dict:
        now = self.clock()
        with self._lock:
            rec = dict(self._rec)
            state = self._state
        return {"state": state, "score": round(self.score(now), 4),
                "events": rec["events"], "last": rec["last"],
                "trips": self.trips, "manual": self.manual}


class _Replica:
    """One fleet member: address, breaker, last polled health."""

    __slots__ = ("name", "base_url", "breaker", "health", "draining",
                 "uptime_s", "last_poll", "poll_ok")

    def __init__(self, name: str, base_url: str,
                 breaker: CircuitBreaker):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.breaker = breaker
        self.health: dict = {}
        self.draining = False
        self.uptime_s: Optional[float] = None
        self.last_poll = 0.0
        self.poll_ok = False


@dataclass
class FleetConfig:
    """The router's policy surface. ``hedge_delay_ms=None`` derives the
    hedge trigger from the replica's rolling p99 (fire only into the
    tail); a fixed value pins it."""

    max_retries: int = 2
    backoff_s: float = 0.05
    default_deadline_s: float = 30.0
    attempt_timeout_s: float = 10.0      # per-attempt cap: a wedged
    #                                      replica can't eat the budget
    poll_interval_s: float = 1.0
    poll_timeout_s: float = 2.0
    hedge: bool = False
    hedge_delay_ms: Optional[float] = None
    hedge_min_delay_ms: float = 5.0
    burn_evidence_threshold: float = 2.0  # fold burn evidence past this


class FleetRouter:
    """Load-balancing, health-polling, breaker-guarded request router
    over N model-server replicas (the module docstring's contract)."""

    def __init__(self, replicas: Optional[dict] = None,
                 config: Optional[FleetConfig] = None,
                 breaker_config: Optional[BreakerConfig] = None,
                 registry: Optional[Registry] = None,
                 span_path: Optional[str] = None,
                 clock=time.monotonic, rng: Optional[random.Random] = None):
        self.config = config or FleetConfig()
        self.breaker_config = breaker_config or BreakerConfig()
        self.clock = clock
        self.rng = rng or random.Random()
        self.registry = registry or Registry()
        self._lock = threading.Lock()
        self._replicas: dict[str, _Replica] = {}
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        # hedge attempts run on their own pool; bounded so a storm of
        # wedged hedges can't grow threads without limit
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="fleet-hedge")
        self._poll_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="fleet-poll")
        if span_path:
            self.writer = obstrace.SpanWriter(span_path, "fleet")
            self._own_writer = True
        else:
            self.writer = obstrace.default_tracer("fleet")
            self._own_writer = False
        r = self.registry
        self._m_requests = r.counter(
            "kftpu_fleet_requests_total",
            "routed requests per outcome", labels=("outcome",))
        self._m_attempts = r.counter(
            "kftpu_fleet_attempts_total",
            "upstream attempts per replica", labels=("replica",))
        self._m_retries = r.counter(
            "kftpu_fleet_retries_total",
            "failover retries per replica and evidence kind",
            labels=("replica", "reason"))
        self._m_hedges = r.counter(
            "kftpu_fleet_hedges_total",
            "tail hedges fired, by what the duplicate did",
            labels=("outcome",))
        self._m_hedge_waste = r.counter(
            "kftpu_fleet_hedge_waste_seconds_total",
            "duplicated upstream seconds from lost hedges "
            "(the hedge_waste badput category)")
        self._m_breaker = r.gauge(
            "kftpu_fleet_breaker_state",
            "per-replica breaker state (0 closed, 1 half-open, 2 open)",
            labels=("replica",))
        self._m_breaker_score = r.gauge(
            "kftpu_fleet_breaker_score",
            "per-replica decayed failure-evidence score",
            labels=("replica",))
        self._m_ejections = r.counter(
            "kftpu_fleet_ejections_total",
            "breaker trips per replica", labels=("replica",))
        self._m_admissions = r.counter(
            "kftpu_fleet_admissions_total",
            "probational re-admissions (half-open → closed) per replica",
            labels=("replica",))
        self._m_draining = r.gauge(
            "kftpu_fleet_replica_draining",
            "1 while the replica advertises draining",
            labels=("replica",))
        self._m_drains = r.counter(
            "kftpu_fleet_drains_total",
            "drain transitions observed per replica",
            labels=("replica",))
        self._m_replicas = r.gauge(
            "kftpu_fleet_replicas", "replicas currently registered")
        for name, url in (replicas or {}).items():
            self.add_replica(name, url)

    # ---------------------------------------------------------- membership

    def add_replica(self, name: str, base_url: str) -> None:
        with self._lock:
            self._replicas[name] = _Replica(
                name, base_url,
                CircuitBreaker(self.breaker_config, clock=self.clock))
            self._m_replicas.set(len(self._replicas))
        self._m_breaker.labels(replica=name).set(0)

    def remove_replica(self, name: str) -> None:
        """Drop a replica AND its per-replica series — a dashboard
        reading frozen breaker state for a gone replica would read it
        as live (the model-unload prune rule, replica_state.prune)."""
        with self._lock:
            self._replicas.pop(name, None)
            self._m_replicas.set(len(self._replicas))
        for fam in (self._m_breaker, self._m_breaker_score,
                    self._m_draining, self._m_attempts,
                    self._m_ejections, self._m_admissions,
                    self._m_drains):
            fam.remove(replica=name)
        for reason in FLEET_EVIDENCE_WEIGHTS:
            self._m_retries.remove(replica=name, reason=reason)

    def set_replica_url(self, name: str, base_url: str) -> None:
        """A replica came back at a new address (pod rescheduled):
        same identity, same breaker history."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None:
                rep.base_url = base_url.rstrip("/")

    def replica(self, name: str) -> Optional[_Replica]:
        with self._lock:
            return self._replicas.get(name)

    def replicas(self) -> list:
        with self._lock:
            return list(self._replicas.values())

    # -------------------------------------------------------------- polling

    def poll_once(self) -> dict:
        """One health sweep: GET every replica's verbose healthz
        CONCURRENTLY (one blackholed host must not stall detection for
        the rest of the fleet by poll_timeout_s), update draining/
        uptime/queue state, fold burn-rate evidence. Returns
        {replica: ok} for tests and the soak."""
        reps = self.replicas()
        if len(reps) <= 1:
            results = {rep.name: self._poll_replica(rep)
                       for rep in reps}
        else:
            futures = {rep.name: self._poll_pool.submit(
                self._poll_replica, rep) for rep in reps}
            results = {name: f.result() for name, f in futures.items()}
        self._refresh_breaker_gauges()
        return results

    def _poll_replica(self, rep: _Replica) -> bool:
        url = f"{rep.base_url}/healthz?verbose=1"
        try:
            with urllib.request.urlopen(
                    url, timeout=self.config.poll_timeout_s) as resp:
                snap = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — poll failure is evidence
            rep.poll_ok = False
            rep.last_poll = self.clock()
            # an unpollable replica is suspect, but weigh it lightly —
            # the request path's own failures carry the hard evidence
            if self._record_failure(rep, EVIDENCE_CONNECT, weight=0.25):
                self._on_trip(rep, f"health poll failed: {e}")
            return False
        rep.poll_ok = True
        rep.last_poll = self.clock()
        rep.health = snap
        rep.uptime_s = snap.get("uptimeSeconds")
        draining = bool(snap.get("draining"))
        if draining and not rep.draining:
            self._m_drains.labels(replica=rep.name).inc()
            self._emit_event("fleet-drain", replica=rep.name)
            log.info("fleet: replica %s is draining — routing away",
                     rep.name)
        rep.draining = draining
        self._m_draining.labels(replica=rep.name).set(
            1 if draining else 0)
        # burn-rate evidence: a replica burning its availability budget
        # fast is failing-in-place even when requests still connect
        for model in snap.get("models", []):
            burns = (model.get("burnRates") or {})
            fast = burns.get("60s") or {}
            if float(fast.get("availability", 0.0) or 0.0) >= \
                    self.config.burn_evidence_threshold:
                if self._record_failure(rep, EVIDENCE_BURN):
                    self._on_trip(rep, "availability burn rate")
                break
        return True

    def start_polling(self) -> None:
        if self._poll_thread is not None:
            return
        self._poll_stop.clear()

        def loop():
            while not self._poll_stop.wait(self.config.poll_interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — the poller survives
                    log.exception("fleet poll failed")

        self._poll_thread = threading.Thread(
            target=loop, daemon=True, name="fleet-poll")
        self._poll_thread.start()

    def close(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=2)
            self._poll_thread = None
        self._hedge_pool.shutdown(wait=False)
        self._poll_pool.shutdown(wait=False)
        if self._own_writer and self.writer is not None:
            self.writer.close()

    # ----------------------------------------------------------- the pick

    def _score(self, rep: _Replica, model: str) -> float:
        """Lower is better: queue depth + in-flight (work already
        committed there) weighted with the rolling p99 in ms (how
        slowly that work drains). An unpolled replica scores neutral —
        new members must receive traffic to produce evidence."""
        if not rep.poll_ok or not rep.health:
            return 1.0
        depth = inflight = 0.0
        p99_ms = 0.0
        for m in rep.health.get("models", []):
            if model and m.get("model") not in ("", model):
                continue
            depth += float(m.get("queueDepth", 0) or 0)
            inflight += float(m.get("inFlight", 0) or 0)
            p99_ms = max(p99_ms, float(m.get("p99Ms", 0.0) or 0.0))
        return depth + inflight + p99_ms / 10.0

    def pick(self, model: str = "", exclude: Optional[set] = None,
             probe_ok: bool = True) -> _Replica:
        """The least-loaded routable replica outside ``exclude``.
        A half-open replica with a free probe slot takes priority —
        probation needs traffic to serve, and one probe at a time is
        the bounded risk. ``probe_ok=False`` skips half-open replicas
        entirely (hedge twins: a latency rescue must not go to a
        suspect replica, and an abandoned twin would leak the claimed
        probe slot). Raises NoReplicaAvailableError when every replica
        is draining or breaker-blocked."""
        exclude = exclude or set()
        now = self.clock()
        closed, half = [], []
        for rep in self.replicas():
            if rep.name in exclude or rep.draining:
                continue
            state = rep.breaker.state(now)
            if state == BREAKER_CLOSED:
                closed.append(rep)
            elif state == BREAKER_HALF_OPEN and probe_ok:
                half.append(rep)
        for rep in sorted(half, key=lambda r: r.name):
            if rep.breaker.try_probe():
                return rep
        if not closed:
            raise NoReplicaAvailableError(
                f"no routable replica (of {len(self.replicas())}, "
                f"excluding {sorted(exclude)})")
        # tiny jitter decorrelates equal-score picks across router
        # instances without disturbing a real load signal
        return min(closed,
                   key=lambda r: (self._score(r, model),
                                  self.rng.random()))

    # --------------------------------------------------------- the request

    def request(self, model: str, body: bytes,
                request_id: Optional[str] = None,
                deadline_s: Optional[float] = None,
                hedge: Optional[bool] = None) -> dict:
        """Route one :predict request: pick → attempt → (failover
        retries | tail hedge) → respond, all inside the deadline
        budget. Returns the decoded response dict; raises a FleetError
        subclass otherwise. Emits one ``fleet-request`` summary span
        with the fleet ledger (client wall = upstream + retry + other;
        a lost hedge's duplicated work ledgered as hedge_waste)."""
        rid = request_id or mint_request_id()
        hedge = self.config.hedge if hedge is None else hedge
        budget = self.config.default_deadline_s \
            if deadline_s is None else float(deadline_s)
        t0_wall = time.time()
        t0 = time.monotonic()
        deadline = t0 + budget
        tried: set = set()
        retry_s = 0.0
        hedge_waste_s = 0.0
        hedged = False
        attempts = retries = 0
        delay = self.config.backoff_s
        last_err: Optional[Exception] = None
        outcome = "error"
        winner = ""
        upstream_s = 0.0
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    outcome = "deadline"
                    raise DeadlineExceededError(
                        f"deadline budget ({budget:.2f}s) exhausted "
                        f"after {attempts} attempt(s): {last_err}")
                try:
                    rep = self.pick(model, exclude=tried)
                except NoReplicaAvailableError:
                    outcome = "no_replica"
                    if not tried:
                        raise
                    # every replica tried once: failover prefers a
                    # DIFFERENT replica but a small fleet may have to
                    # come back around rather than fail the client
                    tried = set()
                    rep = self.pick(model)
                    outcome = "error"
                attempts += 1
                self._m_attempts.labels(replica=rep.name).inc()
                t_attempt = time.monotonic()
                try:
                    out, win_rep, waste = self._attempt_maybe_hedged(
                        rep, model, body, rid, deadline, hedge, tried)
                    upstream_s = time.monotonic() - t_attempt
                    if waste > 0:
                        hedged = True
                        hedge_waste_s += waste
                    winner = win_rep.name
                    outcome = "ok"
                    # success evidence goes to the replica that ANSWERED
                    # (a winning hedge twin may be serving its probation)
                    if win_rep.breaker.record_success():
                        self._m_admissions.labels(
                            replica=win_rep.name).inc()
                        self._emit_event("fleet-admit",
                                         replica=win_rep.name)
                        log.info("fleet: replica %s re-admitted "
                                 "(probation served)", win_rep.name)
                    return out
                except RequestRejectedError:
                    # 4xx is MEANING: the replica answered, transport
                    # is healthy — success evidence for the breaker
                    # (frees a probe slot), the error surfaces
                    rep.breaker.record_success()
                    raise
                except _Retryable as e:
                    attempt_s = time.monotonic() - t_attempt
                    retry_s += attempt_s
                    retries += 1
                    last_err = e
                    tried.add(rep.name)
                    # the hedged path already folded evidence + the
                    # retry metric per failing replica — don't double-
                    # charge the primary with (possibly the twin's)
                    # failure kind
                    if not getattr(e, "recorded", False):
                        self._m_retries.labels(replica=rep.name,
                                               reason=e.kind).inc()
                        if rep.breaker.record_failure(e.kind):
                            self._on_trip(rep, str(e))
                    self._emit_event("fleet-retry", trace_id=rid,
                                     replica=rep.name, reason=e.kind,
                                     attempt=attempts)
                    if retries > self.config.max_retries:
                        outcome = "retries_exhausted"
                        raise RetriesExhaustedError(
                            f"{retries - 1} retries exhausted; "
                            f"last: {e}") from e
                    # jittered backoff; a server-sent Retry-After wins;
                    # both bounded by what's left of the budget
                    sleep = max(jittered_backoff(delay, self.rng),
                                e.retry_after or 0.0)
                    sleep = min(sleep,
                                max(0.0, deadline - time.monotonic()))
                    if sleep > 0:
                        time.sleep(sleep)
                        retry_s += sleep
                    delay *= 2
        except RequestRejectedError:
            outcome = "rejected"
            raise
        finally:
            wall = time.monotonic() - t0
            ledger = gp.decompose_fleet_request(
                wall, upstream_s, retry_s, hedge_waste_s)
            self._m_requests.labels(outcome=outcome).inc()
            if self.writer is not None:
                self.writer.emit(
                    gp.FLEET_REQUEST_SPAN, start=t0_wall,
                    end=t0_wall + wall, trace_id=rid, model=model,
                    outcome=outcome, replica=winner,
                    attempts=attempts, retries=retries, hedged=hedged,
                    ledger=ledger)

    def _attempt_maybe_hedged(self, rep: _Replica, model: str,
                              body: bytes, rid: str, deadline: float,
                              hedge: bool, tried: set):
        """One attempt, optionally shadowed by tail hedges. Each time
        every in-flight attempt outlives the hedge delay, one more
        duplicate fires at a replica not yet holding this request —
        bounded by the fleet size; the first response wins. (A single
        twin is not enough when IT lands on a replica just entering
        its own pause — the bounded series guarantees reaching a live
        one.) Returns (response, winning_replica, hedge_waste_s); a
        raised _Retryable from the hedged path carries
        ``recorded=True`` — its breaker evidence and retry metric were
        already charged to the replica that actually failed."""
        remaining = deadline - time.monotonic()
        timeout = min(remaining, self.config.attempt_timeout_s)
        if not hedge:
            return self._send(rep, model, body, rid, timeout), rep, 0.0
        hedge_delay = self._hedge_delay_s(rep, model)
        primary = self._hedge_pool.submit(
            self._send, rep, model, body, rid, timeout)
        fired = {primary: rep}
        fired_at: dict = {}   # hedge future → fire time (waste calc)
        used = set(tried) | {rep.name}
        t_first_hedge: Optional[float] = None
        more_replicas = True
        while fired:
            budget = deadline - time.monotonic()
            if budget <= 0:
                # unrecorded: the outer handler charges the primary's
                # breaker once (the twins' own timeouts fire later,
                # unobserved)
                raise _Retryable(EVIDENCE_TIMEOUT,
                                 "hedged attempts timed out")
            done, _ = wait(list(fired),
                           timeout=min(hedge_delay, budget)
                           if more_replicas else budget,
                           return_when=FIRST_COMPLETED)
            if not done:
                if not more_replicas:
                    raise _Retryable(EVIDENCE_TIMEOUT,
                                     "hedged attempts timed out")
                # everyone in flight outlived the delay: fire one more
                # duplicate at a replica not yet holding this request
                # (probe_ok=False: a hedge may be abandoned unobserved,
                # which would leak a claimed half-open probe slot)
                try:
                    twin_rep = self.pick(model, exclude=used,
                                         probe_ok=False)
                except NoReplicaAvailableError:
                    more_replicas = False
                    continue
                used.add(twin_rep.name)
                if t_first_hedge is None:
                    t_first_hedge = time.monotonic()
                self._m_hedges.labels(outcome="fired").inc()
                self._emit_event("fleet-hedge", trace_id=rid,
                                 replica=twin_rep.name,
                                 primary=rep.name)
                twin = self._hedge_pool.submit(
                    self._send, twin_rep, model, body, rid,
                    min(max(0.001, deadline - time.monotonic()),
                        self.config.attempt_timeout_s))
                fired[twin] = twin_rep
                fired_at[twin] = time.monotonic()
                continue
            fut = done.pop()
            src = fired.pop(fut)
            try:
                out = fut.result()
            except _Retryable as e:
                # one attempt failed; breaker evidence for ITS
                # replica, keep waiting on the rest
                if src.breaker.record_failure(e.kind):
                    self._on_trip(src, str(e))
                self._m_retries.labels(replica=src.name,
                                       reason=e.kind).inc()
                if not fired:
                    e.recorded = True  # outer handler must not
                    raise              # re-charge the primary
                continue
            # winner: every still-running attempt's overlap-with-
            # hedging is duplicated upstream work — "cancelled" by
            # abandonment (urllib has no mid-flight abort; the
            # duplicated seconds are what we ledger either way)
            now = time.monotonic()
            waste = 0.0
            for leftover, leftover_rep in fired.items():
                leftover.cancel()
                # an abandoned attempt completes unobserved: free any
                # probe slot it held so the replica stays probe-able
                leftover_rep.breaker.release_probe()
                if t_first_hedge is not None:
                    # a loser's duplicated stretch starts when IT (or,
                    # for the primary, the first hedge) created the
                    # duplication
                    waste += now - fired_at.get(leftover,
                                                t_first_hedge)
            self._m_hedges.labels(
                outcome="lost" if src is rep else "won").inc()
            if waste > 0:
                self._m_hedge_waste.inc(round(waste, 6))
            return out, src, waste
        raise _Retryable(EVIDENCE_TIMEOUT, "hedge bookkeeping")

    def _hedge_delay_s(self, rep: _Replica, model: str) -> float:
        """The tail-hedge trigger: the replica's rolling p99 (fire only
        into the tail), floored at hedge_min_delay_ms; a configured
        hedge_delay_ms pins it."""
        if self.config.hedge_delay_ms is not None:
            return self.config.hedge_delay_ms / 1e3
        p99_ms = 0.0
        for m in (rep.health or {}).get("models", []):
            if model and m.get("model") not in ("", model):
                continue
            p99_ms = max(p99_ms, float(m.get("p99Ms", 0.0) or 0.0))
        return max(self.config.hedge_min_delay_ms, p99_ms) / 1e3

    # ------------------------------------------------------------ transport

    def _send(self, rep: _Replica, model: str, body: bytes, rid: str,
              timeout_s: float) -> dict:
        """One upstream attempt. Classifies failures: connect/timeout/
        5xx/429/503 raise _Retryable (weather — evidence + failover),
        other 4xx raise RequestRejectedError (meaning — surfaced)."""
        url = f"{rep.base_url}/v1/models/{model}:predict"
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json",
                     REQUEST_ID_HEADER: rid,
                     DEADLINE_HEADER: f"{max(0.001, timeout_s):.3f}"})
        try:
            with urllib.request.urlopen(
                    req, timeout=max(0.001, timeout_s)) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            e.read()
            if e.code == 429:
                raise _Retryable(EVIDENCE_SHED, f"429: {e.reason}",
                                 retry_after=retry_after_s(e.headers))
            if e.code >= 500:
                raise _Retryable(EVIDENCE_5XX, f"{e.code}: {e.reason}",
                                 retry_after=retry_after_s(e.headers))
            raise RequestRejectedError(e.code, str(e.reason))
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, (TimeoutError,)) or \
                    "timed out" in str(reason):
                raise _Retryable(EVIDENCE_TIMEOUT, f"timeout: {reason}")
            raise _Retryable(EVIDENCE_CONNECT,
                             f"connect failure: {reason}")
        except (TimeoutError, ConnectionError, OSError) as e:
            kind = EVIDENCE_TIMEOUT if isinstance(e, TimeoutError) \
                else EVIDENCE_CONNECT
            raise _Retryable(kind, f"{type(e).__name__}: {e}")
        except json.JSONDecodeError as e:
            # a killed replica can tear the response mid-body
            raise _Retryable(EVIDENCE_CONNECT, f"torn response: {e}")

    # ------------------------------------------------------------- plumbing

    def _record_failure(self, rep: _Replica, kind: str,
                        weight: Optional[float] = None) -> bool:
        return rep.breaker.record_failure(kind, weight=weight)

    def _on_trip(self, rep: _Replica, detail: str) -> None:
        self._m_ejections.labels(replica=rep.name).inc()
        self._emit_event("fleet-eject", replica=rep.name,
                         detail=detail[:200])
        log.warning("fleet: replica %s ejected (breaker open): %s",
                    rep.name, detail)
        self._refresh_breaker_gauges()

    def _refresh_breaker_gauges(self) -> None:
        now = self.clock()
        for rep in self.replicas():
            self._m_breaker.labels(replica=rep.name).set(
                _BREAKER_STATE_CODE[rep.breaker.state(now)])
            self._m_breaker_score.labels(replica=rep.name).set(
                round(rep.breaker.score(now), 4))

    def _emit_event(self, name: str, trace_id: Optional[str] = None,
                    **attrs) -> None:
        if self.writer is not None:
            now = time.time()
            self.writer.emit(name, start=now, end=now,
                             trace_id=trace_id or "", **attrs)

    # -------------------------------------------------------------- status

    def snapshot(self) -> dict:
        """The fleet's own health view (dashboard / soak report)."""
        now = self.clock()
        reps = []
        for rep in self.replicas():
            reps.append({
                "name": rep.name, "baseUrl": rep.base_url,
                "draining": rep.draining,
                "uptimeSeconds": rep.uptime_s,
                "pollOk": rep.poll_ok,
                "breaker": rep.breaker.to_dict(),
                "score": round(self._score(rep, ""), 4),
            })
        return {"replicas": sorted(reps, key=lambda r: r["name"]),
                "config": {
                    "maxRetries": self.config.max_retries,
                    "defaultDeadlineSeconds":
                        self.config.default_deadline_s,
                    "hedge": self.config.hedge,
                },
                "breakerConfig": self.breaker_config.to_dict(),
                "time": now}

    def metrics_text(self) -> str:
        self._refresh_breaker_gauges()
        return self.registry.render()
