"""Replica health registry: the signals the router and autoscaler read.

The ROADMAP's serving tier needs "per-replica health/latency from the
obs registry" for load-balancing routing and "queue-depth and latency
histograms" for autoscaling — this module is that registry. Each model
server feeds one ReplicaState with every finished request (via
serving/request_trace.py) and its batchers' queue state; the state
publishes two surfaces:

- **/metrics** (Prometheus, via the server's obs Registry): rolling
  p50/p99 gauges, request/error/shed counters, in-flight + queue-depth
  + oldest-waiting-age gauges, per-category serving badput counters,
  batch-fill gauge, warm/cold start kind, and multi-window SLO
  burn-rate gauges — all labeled per model (shadow traffic labeled
  ``role=shadow`` so a cold shadow JIT never pollutes the primary's
  SLO series).
- **/healthz?verbose=1** (compact JSON): the same numbers as one
  snapshot — the exact interface the future load-balancing router and
  autoscaler reconciler poll.

Series are pruned when a model is unloaded (`prune`): a router reading
frozen last-latency for a gone model would keep routing to it.

SLO burn rate (the SRE multi-window form): a model declares a target
p99 (ms) and/or an availability target. Over each window, the latency
burn is frac(requests over target) / 0.01 (a p99 target budgets 1%
over) and the availability burn is error_rate / (1 - target). Burn 1.0
= exactly consuming budget; >1 = burning faster than the SLO allows.
jax-free, stdlib only.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from ..obs import goodput as gp

# multi-window burn rates (seconds): the fast window pages, the slow
# window confirms — the standard multi-window multi-burn-rate pattern
BURN_WINDOWS = (60.0, 300.0, 3600.0)

# a p99 target budgets 1% of requests over it
_P99_BUDGET = 0.01


@dataclass(frozen=True)
class ModelSLO:
    """Declarative per-model SLO (the serving manifest schema renders
    these as --slo-p99-ms / --slo-availability)."""

    target_p99_ms: Optional[float] = None
    availability: Optional[float] = None   # e.g. 0.999

    def to_dict(self) -> dict:
        return {"targetP99Ms": self.target_p99_ms,
                "availability": self.availability}


class _ModelWindow:
    """Bounded rolling sample window for one (model, role): (t, latency,
    ok, over_slo) tuples, enough for an hour-window burn rate at
    moderate QPS without unbounded growth."""

    __slots__ = ("samples", "fills")

    def __init__(self, max_samples: int):
        self.samples: deque = deque(maxlen=max_samples)
        self.fills: deque = deque(maxlen=256)


class ReplicaState:
    """Per-model rolling health the model server feeds and publishes."""

    def __init__(self, registry, windows: tuple = BURN_WINDOWS,
                 max_samples: int = 4096, clock=time.time):
        self.registry = registry
        self.windows = tuple(float(w) for w in windows)
        self.max_samples = max_samples
        self.clock = clock
        # the fleet-router contract (serving/fleet.py): uptime lets the
        # router spot a freshly-restarted (cold) replica, draining tells
        # it to stop sending BEFORE the pod dies
        self.started_at = self.clock()
        self.draining = False
        self._lock = threading.Lock()
        self._models: dict[tuple, _ModelWindow] = {}   # (model, role)
        self._slos: dict[str, ModelSLO] = {}
        self._start_kind: dict[str, str] = {}
        self._inflight: dict[str, int] = {}
        self._heartbeat: dict[str, float] = {}
        self._queues: dict[str, object] = {}   # model → batcher
        # cumulative goodput/wall seconds per model (primary ledgers)
        # feeding the kftpu_serving_goodput_ratio gauge
        self._goodput_acc: dict[str, list] = {}
        r = registry
        self._m_requests = r.counter(
            "kftpu_serving_requests_total",
            "finished serving requests per model/role/outcome",
            labels=("model", "role", "outcome"))
        self._m_latency = r.histogram(
            "kftpu_serving_request_seconds",
            "end-to-end request latency (accept → respond)",
            labels=("model", "role"))
        self._m_p50 = r.gauge(
            "kftpu_serving_p50_seconds",
            "rolling p50 request latency", labels=("model", "role"))
        self._m_p99 = r.gauge(
            "kftpu_serving_p99_seconds",
            "rolling p99 request latency", labels=("model", "role"))
        self._m_err = r.gauge(
            "kftpu_serving_error_ratio",
            "rolling error fraction", labels=("model", "role"))
        self._m_inflight = r.gauge(
            "kftpu_serving_inflight",
            "requests accepted but not yet responded", labels=("model",))
        self._m_qdepth = r.gauge(
            "kftpu_serving_queue_depth",
            "requests waiting in the micro-batcher queue",
            labels=("model",))
        self._m_oldest = r.gauge(
            "kftpu_serving_oldest_wait_seconds",
            "age of the oldest request waiting in the batcher queue",
            labels=("model",))
        self._m_fill = r.gauge(
            "kftpu_serving_batch_fill_ratio",
            "rolling mean real-rows / padded-bucket fraction",
            labels=("model",))
        self._m_goodput = r.gauge(
            "kftpu_serving_goodput_ratio",
            "rolling device-real-work fraction of request wall-clock "
            "(docs/operations.md 'Serving observability')",
            labels=("model",))
        # cumulative badput per category: a true counter (inc per
        # request), unlike the job ledger's snapshot-set bridge
        self._m_badput = r.counter(
            "kftpu_serving_badput_seconds_total",
            "request wall-clock seconds lost per serving badput "
            "category", labels=("model", "category"))
        self._m_shed = r.counter(
            "kftpu_serving_shed_total",
            "requests rejected by the bounded batcher queue (429)",
            labels=("model",))
        self._m_heartbeat = r.gauge(
            "kftpu_serving_last_request_time_seconds",
            "unix time of the model's last finished request",
            labels=("model",))
        self._m_start_kind = r.gauge(
            "kftpu_serving_start_kind",
            "1 for the warm-start rung that loaded this model "
            "(cold|warm — PR 9 compile-cache evidence)",
            labels=("model", "kind"))
        self._m_burn = r.gauge(
            "kftpu_serving_slo_burn_rate",
            "error-budget burn rate per SLO and window (1.0 = exactly "
            "consuming budget)", labels=("model", "slo", "window"))
        self._m_draining = r.gauge(
            "kftpu_serving_draining",
            "1 while this replica is draining (readiness flipped, new "
            "work rejected, in-flight finishing)")
        self._m_uptime = r.gauge(
            "kftpu_serving_uptime_seconds",
            "seconds since this replica started serving")

    # ------------------------------------------------------------- feeding

    def set_slo(self, model: str, slo: ModelSLO) -> None:
        with self._lock:
            self._slos[model] = slo

    def slo_of(self, model: str) -> Optional[ModelSLO]:
        with self._lock:
            return self._slos.get(model)

    def set_start_kind(self, model: str, kind: str) -> None:
        with self._lock:
            previous = self._start_kind.get(model)
            self._start_kind[model] = kind
        # one-hot: a reloaded model that warms up must not keep
        # exporting its previous kind's 1 beside the new one
        if previous is not None and previous != kind:
            self._m_start_kind.remove(model=model, kind=previous)
        self._m_start_kind.labels(model=model, kind=kind).set(1)

    def register_queue(self, model: str, batcher) -> None:
        """The model's MicroBatcher: polled at refresh()/snapshot()
        time for queue depth + oldest-waiting age (scrape-time pull,
        zero hot-path cost). Under continuous batching (ISSUE 18) the
        batcher removes an item from both gauges the moment it is
        admitted to a forming cohort — the gauges count work the
        DEVICE has not yet claimed, which is exactly the backlog the
        autoscaler reconciler scales on; counting admitted (in-flight)
        work here would double-book it against ``inFlight``."""
        with self._lock:
            self._queues[model] = batcher

    def inflight_inc(self, model: str) -> None:
        with self._lock:
            self._inflight[model] = self._inflight.get(model, 0) + 1

    def inflight_dec(self, model: str) -> None:
        with self._lock:
            self._inflight[model] = max(
                0, self._inflight.get(model, 0) - 1)

    def total_inflight(self) -> int:
        """Accepted-but-unanswered requests across all models — what a
        graceful drain waits on before the process may exit."""
        with self._lock:
            return sum(self._inflight.values())

    def set_draining(self, draining: bool = True) -> None:
        """Flip the replica-wide draining flag: advertised on the
        verbose healthz payload and /metrics so the fleet router stops
        sending BEFORE the pod dies (plain /healthz also flips to 503
        — the kubelet readiness contract; http_server.py)."""
        self.draining = bool(draining)
        self._m_draining.set(1 if self.draining else 0)

    def uptime_seconds(self) -> float:
        return max(0.0, self.clock() - self.started_at)

    def observe_request(self, model: str, latency_s: float,
                        outcome: str = "ok", role: str = "primary",
                        ledger: Optional[dict] = None,
                        fill: Optional[float] = None) -> None:
        """One finished request (called by RequestTrace.finish)."""
        now = self.clock()
        slo = self._slos.get(model)
        over = bool(slo and slo.target_p99_ms is not None
                    and latency_s * 1e3 > slo.target_p99_ms)
        ok = outcome == "ok"
        with self._lock:
            w = self._models.get((model, role))
            if w is None:
                w = self._models[(model, role)] = \
                    _ModelWindow(self.max_samples)
            w.samples.append((now, latency_s, ok, over))
            if fill is not None:
                w.fills.append(float(fill))
            self._heartbeat[model] = now
        self._m_requests.labels(model=model, role=role,
                                outcome=outcome).inc()
        self._m_latency.labels(model=model, role=role).observe(latency_s)
        self._m_heartbeat.labels(model=model).set(now)
        if outcome == "shed":
            self._m_shed.labels(model=model).inc()
        if ledger and role == "primary":
            for cat, secs in ledger.get("badputSeconds", {}).items():
                if secs:
                    self._m_badput.labels(model=model,
                                          category=cat).inc(secs)
            with self._lock:
                acc = self._goodput_acc.setdefault(model, [0.0, 0.0])
                acc[0] += ledger.get("goodputSeconds", 0.0)
                acc[1] += ledger.get("wallSeconds", 0.0)
                ratio = acc[0] / acc[1] if acc[1] else 0.0
            self._m_goodput.labels(model=model).set(round(ratio, 6))

    # ----------------------------------------------------------- publishing

    def _window_stats(self, w: _ModelWindow, now: float,
                      window_s: float) -> dict:
        # copy under the lock: a request thread appending to the deque
        # while the scrape path iterates it would raise (deque
        # mutated-during-iteration) and 500 the /metrics render
        with self._lock:
            samples = list(w.samples)
        cutoff = now - window_s
        lats = []
        errors = over = 0
        for t, lat, ok, ov in samples:
            if t < cutoff:
                continue
            lats.append(lat)
            if not ok:
                errors += 1
            if ov:
                over += 1
        lats.sort()
        n = len(lats)
        return {
            "n": n,
            "p50": gp._percentile(lats, 0.50),
            "p99": gp._percentile(lats, 0.99),
            "errorRatio": errors / n if n else 0.0,
            "overSloRatio": over / n if n else 0.0,
        }

    def _burn_rates(self, model: str, w: _ModelWindow,
                    now: float) -> dict:
        """{window_label: {"latency": burn, "availability": burn}} for
        the configured windows, only for declared SLOs."""
        slo = self._slos.get(model)
        if slo is None:
            return {}
        out = {}
        for win in self.windows:
            stats = self._window_stats(w, now, win)
            burns = {}
            if slo.target_p99_ms is not None:
                burns["latency"] = stats["overSloRatio"] / _P99_BUDGET
            if slo.availability is not None:
                budget = max(1e-9, 1.0 - slo.availability)
                burns["availability"] = stats["errorRatio"] / budget
            if burns:
                out[f"{int(win)}s"] = burns
        return out

    def refresh(self) -> None:
        """Recompute the derived gauges (rolling percentiles, error
        ratio, queue depth/age, burn rates) — called at scrape and
        healthz time, never on the request hot path."""
        now = self.clock()
        with self._lock:
            models = dict(self._models)
            queues = dict(self._queues)
            inflight = dict(self._inflight)
        # the default rolling window for the headline gauges is the
        # middle burn window (5 min): long enough to be stable, short
        # enough that a recovered replica's gauges recover too
        headline = self.windows[min(1, len(self.windows) - 1)]
        for (model, role), w in models.items():
            stats = self._window_stats(w, now, headline)
            self._m_p50.labels(model=model, role=role).set(
                round(stats["p50"], 6))
            self._m_p99.labels(model=model, role=role).set(
                round(stats["p99"], 6))
            self._m_err.labels(model=model, role=role).set(
                round(stats["errorRatio"], 6))
            if role == "primary":
                with self._lock:
                    fills = list(w.fills)
                if fills:
                    self._m_fill.labels(model=model).set(
                        round(sum(fills) / len(fills), 4))
                for win_label, burns in self._burn_rates(
                        model, w, now).items():
                    for slo_name, burn in burns.items():
                        self._m_burn.labels(
                            model=model, slo=slo_name,
                            window=win_label).set(round(burn, 4))
        self._m_uptime.set(round(self.uptime_seconds(), 3))
        self._m_draining.set(1 if self.draining else 0)
        for model, count in inflight.items():
            self._m_inflight.labels(model=model).set(count)
        for model, batcher in queues.items():
            depth = oldest = 0.0
            try:
                depth = batcher.queue_depth()
                oldest = batcher.oldest_wait_s()
            except Exception:  # noqa: BLE001 — a dead batcher must
                pass           # not kill the scrape
            self._m_qdepth.labels(model=model).set(depth)
            self._m_oldest.labels(model=model).set(round(oldest, 4))

    def snapshot(self) -> dict:
        """The /healthz?verbose=1 body: per-model health the router
        and autoscaler poll — compact, one JSON object. Computes its
        own rolling stats; the Prometheus gauges are refreshed on the
        /metrics scrape path (refresh()), not here — a 1 Hz health
        poller must not pay the window recomputation twice."""
        now = self.clock()
        with self._lock:
            models = dict(self._models)
            queues = dict(self._queues)
            inflight = dict(self._inflight)
            heartbeat = dict(self._heartbeat)
            slos = dict(self._slos)
            start_kind = dict(self._start_kind)
            goodput_acc = {m: (a[0] / a[1] if a[1] else 0.0)
                           for m, a in self._goodput_acc.items()}
        headline = self.windows[min(1, len(self.windows) - 1)]
        out: dict = {}
        for (model, role), w in sorted(models.items()):
            stats = self._window_stats(w, now, headline)
            entry = out.setdefault(model, {
                "model": model,
                "startKind": start_kind.get(model, ""),
                "inFlight": inflight.get(model, 0),
                "lastRequestAgeSeconds": round(
                    now - heartbeat[model], 3)
                if model in heartbeat else None,
            })
            block = {
                "requests": stats["n"],
                "p50Ms": round(stats["p50"] * 1e3, 3),
                "p99Ms": round(stats["p99"] * 1e3, 3),
                "errorRatio": round(stats["errorRatio"], 6),
            }
            if role == "primary":
                entry.update(block)
                with self._lock:
                    fills = list(w.fills)
                entry["meanFill"] = round(
                    sum(fills) / len(fills), 4) if fills else None
                entry["goodputRatio"] = round(
                    goodput_acc.get(model, 0.0), 6)
                slo = slos.get(model)
                if slo is not None:
                    entry["slo"] = slo.to_dict()
                    entry["burnRates"] = {
                        win: {k: round(v, 4) for k, v in burns.items()}
                        for win, burns in
                        self._burn_rates(model, w, now).items()}
            else:
                entry.setdefault("roles", {})[role] = block
        for model, batcher in queues.items():
            entry = out.setdefault(model, {"model": model})
            try:
                entry["queueDepth"] = batcher.queue_depth()
                entry["oldestWaitSeconds"] = round(
                    batcher.oldest_wait_s(), 4)
            except Exception:  # noqa: BLE001
                pass
        return {"models": sorted(out.values(),
                                 key=lambda m: m["model"]),
                "windowSeconds": headline,
                # the fleet-router contract: stop routing to a draining
                # replica; spot a freshly-restarted (cold) one
                "draining": self.draining,
                "uptimeSeconds": round(self.uptime_seconds(), 3)}

    def prune(self, live_models) -> None:
        """Drop every series for models no longer loaded — a router
        must never read frozen stats for a gone model (the
        kftpu_job_phase pruning rule)."""
        live = set(live_models)
        with self._lock:
            gone_keys = [k for k in self._models if k[0] not in live]
            gone = {k[0] for k in gone_keys}
            roles = {}
            for model, role in gone_keys:
                roles.setdefault(model, set()).add(role)
                del self._models[(model, role)]
            for model in gone:
                self._slos.pop(model, None)
                self._start_kind.pop(model, None)
                self._inflight.pop(model, None)
                self._heartbeat.pop(model, None)
                self._queues.pop(model, None)
                self._goodput_acc.pop(model, None)
            slo_windows = [f"{int(w)}s" for w in self.windows]
        for model, model_roles in roles.items():
            for role in model_roles:
                for fam in (self._m_p50, self._m_p99, self._m_err):
                    fam.remove(model=model, role=role)
                for outcome in ("ok", "error", "shed", "drained"):
                    self._m_requests.remove(model=model, role=role,
                                            outcome=outcome)
                self._m_latency.remove(model=model, role=role)
            for fam in (self._m_inflight, self._m_qdepth,
                        self._m_oldest, self._m_fill, self._m_goodput,
                        self._m_shed, self._m_heartbeat):
                fam.remove(model=model)
            for cat in gp.SERVING_BADPUT_CATEGORIES:
                self._m_badput.remove(model=model, category=cat)
            for kind in ("cold", "warm", "aot"):
                self._m_start_kind.remove(model=model, kind=kind)
            for slo_name in ("latency", "availability"):
                for win in slo_windows:
                    self._m_burn.remove(model=model, slo=slo_name,
                                        window=win)
