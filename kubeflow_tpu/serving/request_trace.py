"""Per-request tracing: one request id from accept to respond.

The training side reconstructs a TPUJob's whole life from JSONL spans
alone (obs/trace.py); this module gives the request path the same
property. A **request id** is minted at accept (honoring an inbound
``x-request-id`` header, echoed on the response) and used as the span
``trace_id``, so ``reconstruct(sink, request_id)`` rebuilds one
request's timeline: accept → queue → batch-form → h2d → device →
drain → respond.

Cost discipline (the <1%-of-the-hot-path bar, bench.py --mode
serving-obs): every request emits exactly ONE ``serving-request``
summary span carrying its full ledger (obs/goodput.py
decompose_request); the per-stage detail spans are **sampled**
(``sample_every``, plus any request whose inbound id arrives with an
``x-request-sample`` header) — the acceptance criterion is "one
sampled slow request reconstructed stage-by-stage", not a span
firehose. Stage *seconds* are accumulated for every request regardless
(two float adds per stage) so the ledger, the replica registry, and
the SLO burn tracking never depend on sampling. With no span sink
configured the writer is None and nothing is emitted at all.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from ..obs import goodput as gp
from ..obs import trace as obstrace

# inbound/outbound header carrying the request id (lowercase; http
# header lookup is case-insensitive, gRPC metadata keys must be lower)
REQUEST_ID_HEADER = "x-request-id"

# remaining-deadline budget header: seconds the caller will still wait
# for THIS attempt. The fleet router (serving/fleet.py) decrements it
# across failover retries so retrying can never exceed what the client
# asked for; the model server bounds its batcher wait by it (a request
# whose client is gone must not compute for nobody).
DEADLINE_HEADER = "x-request-deadline"

# stage span name → ledger category (device splits goodput/pad_waste
# by fill, handled in RequestTrace.device)
_STAGE_CATEGORY = {
    "queue": gp.SERVING_QUEUE,
    "batch-form": gp.SERVING_BATCH_FORM,
    "h2d": gp.SERVING_H2D,
    "drain": gp.SERVING_RESPOND,
    "respond": gp.SERVING_RESPOND,
}


def mint_request_id() -> str:
    return uuid.uuid4().hex[:16]


class RequestTrace:
    """One request's context: id, stage ledger, sampled span emission.

    Stage methods are called from two threads — the server handler
    (accept/respond) and the batcher loop (queue/batch-form/h2d/
    device/drain) — but never concurrently for the same stage; the
    future hand-off orders them. ``finish`` is idempotent."""

    __slots__ = ("obs", "request_id", "model", "role", "sampled",
                 "t_accept", "t_pipeline_end", "stages", "attrs",
                 "_done")

    def __init__(self, obs: "ServingObs", request_id: str, model: str,
                 role: str = "primary", sampled: bool = False):
        self.obs = obs
        self.request_id = request_id
        self.model = model
        self.role = role
        self.sampled = sampled
        self.t_accept = time.time()
        # the batcher stamps when its pipeline finished (drain end) so
        # the handler's respond stage starts THERE — the future-wakeup
        # gap is response-path time, not unattributed residual
        self.t_pipeline_end: Optional[float] = None
        self.stages: dict = {}
        self.attrs: dict = {}
        self._done = False
        if sampled and obs.writer is not None:
            obs.writer.emit("accept", start=self.t_accept,
                            trace_id=request_id, model=model, role=role)

    # ------------------------------------------------------------- stages

    def stage(self, name: str, start: float, end: float,
              seconds: Optional[float] = None, **attrs) -> None:
        """Record one stage: ``seconds`` (default end-start) lands in
        the ledger under the stage's category; a sampled request also
        emits the span. Shared-cohort stages (batch-form/h2d/drain)
        pass their prorated share as ``seconds`` while the span keeps
        the cohort's real interval."""
        secs = (end - start) if seconds is None else seconds
        cat = _STAGE_CATEGORY.get(name)
        if cat is not None and secs > 0:
            self.stages[cat] = self.stages.get(cat, 0.0) + secs
        if self.sampled and self.obs.writer is not None:
            self.obs.writer.emit(name, start=start, end=end,
                                 trace_id=self.request_id,
                                 model=self.model, role=self.role,
                                 **attrs)

    def device(self, start: float, end: float, goodput_s: float,
               pad_waste_s: float, **attrs) -> None:
        """The device stage: this request's real-work share is serving
        goodput, its share of the cohort's pad rows is pad_waste."""
        if goodput_s > 0:
            self.stages[gp.SERVING_DEVICE] = \
                self.stages.get(gp.SERVING_DEVICE, 0.0) + goodput_s
        if pad_waste_s > 0:
            self.stages[gp.SERVING_PAD_WASTE] = \
                self.stages.get(gp.SERVING_PAD_WASTE, 0.0) + pad_waste_s
        if self.sampled and self.obs.writer is not None:
            self.obs.writer.emit("device", start=start, end=end,
                                 trace_id=self.request_id,
                                 model=self.model, role=self.role,
                                 goodput_s=round(goodput_s, 6),
                                 pad_waste_s=round(pad_waste_s, 6),
                                 **attrs)

    def note(self, **attrs) -> None:
        """Attach attrs (batch id, fill, bucket) to the summary span."""
        self.attrs.update(attrs)

    # -------------------------------------------------------------- finish

    def finish(self, outcome: str = "ok",
               error: Optional[str] = None) -> dict:
        """Close the request: compute the ledger (exact partition of
        accept→now), emit the always-on summary span, and feed the
        replica registry. Returns the ledger. Idempotent — the first
        caller wins (the error path and a finally block may race)."""
        if self._done:
            return {}
        self._done = True
        t_end = time.time()
        wall = max(0.0, t_end - self.t_accept)
        if outcome == "shed":
            # a shed request never reached the batcher's queue-stamp:
            # its whole unattributed stretch IS queue pressure (the
            # bounded queue turned it away) — charge it there, not to
            # the other residual
            attributed = sum(self.stages.values())
            self.stages[gp.SERVING_QUEUE] = \
                self.stages.get(gp.SERVING_QUEUE, 0.0) + \
                max(0.0, wall - attributed)
        ledger = gp.decompose_request(wall, self.stages)
        if self.obs.writer is not None:
            attrs = {"model": self.model, "role": self.role,
                     "outcome": outcome, "ledger": ledger, **self.attrs}
            if error:
                attrs["error"] = error
            slo = self.obs.slo_p99_ms(self.model)
            if slo is not None:
                attrs["slo_p99_ms"] = slo
            self.obs.writer.emit(gp.SERVING_REQUEST_SPAN,
                                 start=self.t_accept, end=t_end,
                                 trace_id=self.request_id, **attrs)
        if self.obs.replica is not None:
            self.obs.replica.observe_request(
                self.model, wall, outcome=outcome, role=self.role,
                ledger=ledger, fill=self.attrs.get("fill"))
        return ledger


class ServingObs:
    """The model server's request-observability facade: mints
    RequestTraces, owns the span writer + replica registry handle, and
    decides sampling. One per ModelServer (batch_predict makes its
    own); routers share the server's via ``RoutedModel.request_obs``
    so shadow copies trace into the same sink."""

    def __init__(self, replica=None, span_path: Optional[str] = None,
                 component: str = "serving", sample_every: int = 16,
                 slos: Optional[dict] = None):
        if span_path:
            self.writer = obstrace.SpanWriter(span_path, component)
            self._own_writer = True
        else:
            # env-driven (KFTPU_SPAN_PATH, the operator-rendered
            # contract); None = tracing off, zero emission cost
            self.writer = obstrace.default_tracer(component)
            self._own_writer = False
        self.replica = replica
        self.sample_every = max(0, int(sample_every))
        # model → target p99 ms (the declarative SLO; availability
        # lives on the replica registry where the burn windows are)
        self._slos = dict(slos or {})
        self._lock = threading.Lock()
        self._accepted = 0

    def slo_p99_ms(self, model: str) -> Optional[float]:
        # the replica registry is the single SLO source when present
        # (the server feeds it from the manifest-declared targets);
        # the local dict covers registry-less uses (batch_predict)
        if self.replica is not None:
            slo = self.replica.slo_of(model)
            if slo is not None and slo.target_p99_ms is not None:
                return float(slo.target_p99_ms)
            if slo is not None:
                return None
        slo = self._slos.get(model)
        return None if slo is None else float(slo)

    def set_slo(self, model: str, p99_ms: Optional[float]) -> None:
        if p99_ms is None:
            self._slos.pop(model, None)
        else:
            self._slos[model] = float(p99_ms)

    def begin(self, model: str, request_id: Optional[str] = None,
              role: str = "primary",
              force_sample: bool = False) -> RequestTrace:
        """Start one request's trace. ``request_id`` is the honored
        inbound ``x-request-id`` (minted otherwise)."""
        with self._lock:
            self._accepted += 1
            sampled = force_sample or (
                self.sample_every > 0
                and (self._accepted - 1) % self.sample_every == 0)
        return RequestTrace(self, request_id or mint_request_id(),
                            model, role=role,
                            sampled=sampled and self.writer is not None)

    def close(self) -> None:
        # default_tracer-owned writers are process-cached and shared;
        # only close a writer this instance constructed itself
        if self._own_writer and self.writer is not None:
            self.writer.close()
