"""Offline batch prediction job — the tf-batch-predict analog.

Reference: kubeflow/tf-batch-predict/tf-batch-predict.libsonnet:17-31
(model path, input file patterns, batch size, GPU count → here a TPU
process). Input is .npy / .npz / .jsonl; output is .jsonl with one
prediction record per input row, plus a summary line.

TPU note: a fixed batch size (one compiled program) streams the file
through the device; the tail batch is padded, never recompiled.
"""

from __future__ import annotations

import glob
import json
import time
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

from .servable import ModelRepository, Servable


def _iter_input(path: str) -> Iterator[np.ndarray]:
    if path.endswith(".npy"):
        yield np.load(path)
    elif path.endswith(".npz"):
        data = np.load(path)
        yield data[list(data.files)[0]]
    elif path.endswith(".jsonl"):
        rows = []
        with open(path) as f:
            for line in f:
                if line.strip():
                    rows.append(json.loads(line)["instance"])
        if rows:
            yield np.asarray(rows)
    else:
        raise ValueError(f"unsupported input format: {path}")


def run_batch_predict(servable: Servable, input_patterns: list[str],
                      output_path: str, batch_size: int = 64,
                      input_dtype: Optional[str] = None) -> dict:
    """Run prediction over all files matching the patterns; returns the
    summary dict that is also appended to the output file."""
    files: list[str] = []
    for pat in input_patterns:
        files.extend(sorted(glob.glob(pat)))
    if not files:
        raise FileNotFoundError(f"no inputs match {input_patterns}")

    out = Path(output_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    n_total, t0 = 0, time.perf_counter()
    with out.open("w") as f:
        for path in files:
            for arr in _iter_input(path):
                if input_dtype:
                    arr = arr.astype(input_dtype)
                for i in range(0, arr.shape[0], batch_size):
                    chunk = arr[i:i + batch_size]
                    n = chunk.shape[0]
                    if n < batch_size:  # pad the tail: same compiled shape
                        pad = np.zeros(
                            (batch_size - n,) + chunk.shape[1:], chunk.dtype)
                        chunk = np.concatenate([chunk, pad])
                    preds = servable.predict(chunk)
                    preds = {k: np.asarray(v)[:n] for k, v in preds.items()} \
                        if isinstance(preds, dict) else \
                        {"output": np.asarray(preds)[:n]}
                    for j in range(n):
                        f.write(json.dumps(
                            {"source": path, "index": n_total + j,
                             "prediction": {k: np.asarray(v[j]).tolist()
                                            for k, v in preds.items()}})
                            + "\n")
                    n_total += n
    summary = {"instances": n_total, "files": len(files),
               "seconds": round(time.perf_counter() - t0, 3),
               "model": servable.name, "version": servable.version}
    with out.open("a") as f:
        f.write(json.dumps({"summary": summary}) + "\n")
    return summary


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser("tpu-batch-predict")
    p.add_argument("--model-name", default="model")
    p.add_argument("--model-type", default="resnet50")
    p.add_argument("--model-path", default="")
    p.add_argument("--input-file-patterns", required=True,
                   help="comma-separated globs")
    p.add_argument("--output-result-file", required=True)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--input-dtype", default=None)
    args = p.parse_args(argv)

    # before the servable's first jit: a batch-predict job over a big
    # input set restarts often (spot nodes) and re-pays the per-bucket
    # compile every time without the persistent cache (no-op when
    # KFTPU_COMPILE_CACHE_DIR is unset — runtime/compile_cache.py)
    from ..runtime.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    repo = ModelRepository()
    servable = repo.load(args.model_name, args.model_type,
                         checkpoint_dir=args.model_path or None)
    summary = run_batch_predict(
        servable, args.input_file_patterns.split(","),
        args.output_result_file, batch_size=args.batch_size,
        input_dtype=args.input_dtype)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
