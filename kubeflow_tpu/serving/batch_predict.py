"""Offline batch prediction job — the tf-batch-predict analog.

Reference: kubeflow/tf-batch-predict/tf-batch-predict.libsonnet:17-31
(model path, input file patterns, batch size, GPU count → here a TPU
process). Input is .npy / .npz / .jsonl; output is .jsonl with one
prediction record per input row, plus a summary line.

TPU note: a fixed batch size (one compiled program) streams the file
through the device; the tail batch is padded, never recompiled.
"""

from __future__ import annotations

import glob
import json
import time
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

from .servable import ModelRepository, Servable


def _iter_input(path: str) -> Iterator[np.ndarray]:
    if path.endswith(".npy"):
        yield np.load(path)
    elif path.endswith(".npz"):
        data = np.load(path)
        yield data[list(data.files)[0]]
    elif path.endswith(".jsonl"):
        rows = []
        with open(path) as f:
            for line in f:
                if line.strip():
                    rows.append(json.loads(line)["instance"])
        if rows:
            yield np.asarray(rows)
    else:
        raise ValueError(f"unsupported input format: {path}")


def run_batch_predict(servable: Servable, input_patterns: list[str],
                      output_path: str, batch_size: int = 64,
                      input_dtype: Optional[str] = None,
                      request_id: Optional[str] = None) -> dict:
    """Run prediction over all files matching the patterns; returns the
    summary dict that is also appended to the output file.

    Observability: the run carries ONE request id (minted unless the
    caller propagates an inbound one) and — when a span sink is
    configured (KFTPU_SPAN_PATH) — emits a sampled request trace per
    input file plus the always-on per-file ledger summaries, so an
    offline job's device/pad/H2D attribution reads exactly like an
    online request's (obs/goodput.py serving vocabulary)."""
    from .request_trace import ServingObs, mint_request_id
    files: list[str] = []
    for pat in input_patterns:
        files.extend(sorted(glob.glob(pat)))
    if not files:
        raise FileNotFoundError(f"no inputs match {input_patterns}")

    request_id = request_id or mint_request_id()
    obs = ServingObs(component="batch-predict", sample_every=1)
    out = Path(output_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    n_total, t0 = 0, time.perf_counter()
    with out.open("w") as f:
        for fi, path in enumerate(files):
            # per-file trace: the run id suffixed per file, so one slow
            # shard is attributable on its own timeline
            ctx = obs.begin(servable.name,
                            request_id=f"{request_id}-f{fi}")
            ctx.note(source=path, run_request_id=request_id)
            file_rows = 0
            try:
                for arr in _iter_input(path):
                    if input_dtype:
                        arr = arr.astype(input_dtype)
                    for i in range(0, arr.shape[0], batch_size):
                        chunk = arr[i:i + batch_size]
                        n = chunk.shape[0]
                        if n < batch_size:  # pad the tail: same shape
                            pad = np.zeros(
                                (batch_size - n,) + chunk.shape[1:],
                                chunk.dtype)
                            chunk = np.concatenate([chunk, pad])
                        tw0 = time.time()
                        preds, stages = \
                            servable.predict_with_stages(chunk)
                        dev_s = stages["device_s"]
                        padded = max(1, batch_size)
                        ctx.stage("h2d", tw0, tw0 + stages["h2d_s"])
                        ctx.device(
                            tw0 + stages["h2d_s"],
                            tw0 + stages["h2d_s"] + dev_s,
                            goodput_s=dev_s * (n / padded),
                            pad_waste_s=dev_s
                            * ((batch_size - n) / padded))
                        preds = {k: np.asarray(v)[:n]
                                 for k, v in preds.items()} \
                            if isinstance(preds, dict) else \
                            {"output": np.asarray(preds)[:n]}
                        tr0 = time.time()
                        for j in range(n):
                            f.write(json.dumps(
                                {"source": path, "index": n_total + j,
                                 "requestId": request_id,
                                 "prediction": {
                                     k: np.asarray(v[j]).tolist()
                                     for k, v in preds.items()}})
                                + "\n")
                        ctx.stage("respond", tr0, time.time())
                        n_total += n
                        file_rows += n
            except Exception as e:
                ctx.note(rows=file_rows)
                ctx.finish("error", error=f"{type(e).__name__}: {e}")
                raise
            ctx.note(rows=file_rows)
            ctx.finish("ok")
    summary = {"instances": n_total, "files": len(files),
               "seconds": round(time.perf_counter() - t0, 3),
               "model": servable.name, "version": servable.version,
               "requestId": request_id}
    with out.open("a") as f:
        f.write(json.dumps({"summary": summary}) + "\n")
    return summary


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser("tpu-batch-predict")
    p.add_argument("--model-name", default="model")
    p.add_argument("--model-type", default="resnet50")
    p.add_argument("--model-path", default="")
    p.add_argument("--input-file-patterns", required=True,
                   help="comma-separated globs")
    p.add_argument("--output-result-file", required=True)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--input-dtype", default=None)
    p.add_argument("--request-id", default=None,
                   help="propagate an inbound request id (the job's "
                        "spans carry it; minted otherwise)")
    args = p.parse_args(argv)

    # before the servable's first jit: a batch-predict job over a big
    # input set restarts often (spot nodes) and re-pays the per-bucket
    # compile every time without the persistent cache (no-op when
    # KFTPU_COMPILE_CACHE_DIR is unset — runtime/compile_cache.py)
    from ..runtime.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    repo = ModelRepository()
    servable = repo.load(args.model_name, args.model_type,
                         checkpoint_dir=args.model_path or None)
    summary = run_batch_predict(
        servable, args.input_file_patterns.split(","),
        args.output_result_file, batch_size=args.batch_size,
        input_dtype=args.input_dtype, request_id=args.request_id)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
