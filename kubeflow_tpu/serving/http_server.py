"""REST model server: the TF-Serving-compatible HTTP surface.

Endpoint shape matches what the reference deploys and its E2E test probes
(tf-serving.libsonnet REST :8500; testing/test_tf_serving.py:110 posts to
``:8500/v1/models/mnist:predict``), merged with the http-proxy handlers
(components/k8s-model-server/http-proxy/server.py:27-40 — predict /
metadata / status):

- ``GET  /v1/models/<name>``            → version status
- ``GET  /v1/models/<name>/metadata``   → signature metadata
- ``POST /v1/models/<name>:predict``    → {"instances": [...]} →
  {"predictions": [...]}
- ``GET  /healthz`` and ``GET /metrics`` (prometheus text) — the
  observability the reference keeps in separate sidecars.

stdlib ThreadingHTTPServer: requests are I/O-light; the device work is
serialized by the per-model MicroBatcher.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..obs.registry import Registry
from .batcher import MicroBatcher, QueueFullError
from .replica_state import ModelSLO, ReplicaState
from .request_trace import (DEADLINE_HEADER, REQUEST_ID_HEADER,
                            ServingObs, mint_request_id)
from .servable import ModelRepository


class ModelServer:
    def __init__(self, repository: Optional[ModelRepository] = None,
                 host: str = "0.0.0.0", port: int = 8500,
                 max_batch: int = 64, max_latency_ms: float = 5.0,
                 max_pending: int = 0, sample_every: int = 16,
                 span_path: Optional[str] = None,
                 slos: Optional[dict] = None,
                 drain_timeout_s: float = 10.0,
                 batching: str = "continuous",
                 max_wait_ms: Optional[float] = None):
        self.repository = repository or ModelRepository()
        self.host, self.port = host, port
        self.max_batch = max_batch
        self.max_latency_ms = max_latency_ms
        self.max_pending = max_pending
        self.drain_timeout_s = drain_timeout_s
        # batcher admission scheduler (ISSUE 18): "continuous" =
        # in-flight batching; "window" = the fixed-window PR 11
        # baseline, kept for the bench A/B arm
        self.batching = batching
        self.max_wait_ms = max_wait_ms
        self._batchers: dict[str, MicroBatcher] = {}
        self._batchers_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # live handler connections, for kill() (simulated SIGKILL:
        # in-flight clients see a reset, not a response)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._killed = False
        # experiment routers (A/B, bandit, shadow — serving/router.py)
        self.routers: dict[str, "object"] = {}
        # per-server registry (obs/registry.py), not the process default:
        # several ModelServers coexist in one test process and must not
        # share counts. The per-servable totals stay owned by the
        # servables (warmup and direct calls count too) and are bridged
        # into the exposition at scrape time; the REST latency histogram
        # is observed per request.
        self.registry = Registry()
        self._m_requests = self.registry.counter(
            "kubeflow_model_request_count", "requests per servable",
            labels=("model",))
        self._m_predict_s = self.registry.counter(
            "kubeflow_model_predict_seconds_total",
            "cumulative device predict seconds per servable",
            labels=("model",))
        self._m_latency = self.registry.histogram(
            "kubeflow_model_request_seconds",
            "end-to-end REST :predict latency", labels=("model",))
        self._m_exported: set = set()
        # replica health registry + per-request tracing (ISSUE 11):
        # every finished request feeds the registry; spans ride the
        # explicit span_path or the KFTPU_SPAN_PATH env contract
        self.replica = ReplicaState(self.registry)
        self.obs = ServingObs(replica=self.replica, span_path=span_path,
                              sample_every=sample_every)
        for model, slo in (slos or {}).items():
            self.set_slo(model, slo)

    def set_slo(self, model: str, slo: ModelSLO) -> None:
        """Declare a model's SLO (manifest --slo-p99-ms /
        --slo-availability): burn-rate gauges start tracking it."""
        self.replica.set_slo(model, slo)

    def add_router(self, routed) -> None:
        """Mount a RoutedModel at /v1/routers/<name>; when it serves this
        server's repository, its arms resolve through the server's
        MicroBatchers so routed and direct traffic batch together. A
        caller-set resolver or foreign repository is left alone. The
        router also adopts this server's request observability so its
        shadow copies trace into the same sink with role=shadow."""
        if routed.predict_resolver is None and \
                routed.repository is self.repository:
            routed.predict_resolver = lambda arm: self.batcher(arm).predict
        if routed.request_obs is None:
            routed.request_obs = self.obs
        self.routers[routed.name] = routed

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        handler = _make_handler(self)
        owner = self

        class _Httpd(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # a killed server's handler threads die on purpose
                # (OSError on send) — no traceback spam; real errors
                # still print
                if not owner._killed:
                    super().handle_error(request, client_address)

        self._httpd = _Httpd((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="model-server")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        for b in self._batchers.values():
            b.shutdown()
        self.obs.close()

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful drain (the SIGTERM / preStop contract, ISSUE 12):
        flip readiness (plain /healthz → 503, ``draining: true`` on
        the verbose payload so the fleet router stops sending), reject
        new :predict work with 503 + Retry-After, flush each batcher's
        pending cohort, and wait for in-flight requests to finish — up
        to ``drainTimeoutSeconds``. Idempotent; does NOT stop the
        listener (the caller decides when the process dies). Returns a
        report the soak asserts zero-loss against."""
        timeout_s = self.drain_timeout_s if timeout_s is None else \
            float(timeout_s)
        already = self.replica.draining
        self.replica.set_draining(True)
        inflight_at_start = self.replica.total_inflight()
        deadline = time.monotonic() + max(0.0, timeout_s)
        flushed = failed = 0
        if not already:
            with self._batchers_lock:
                batchers = list(self._batchers.values())
            for b in batchers:
                r = b.drain(timeout_s=max(0.1,
                                          deadline - time.monotonic()))
                flushed += r["flushed"]
                failed += r["failed"]
        # in-flight = accepted but not yet responded; the batcher flush
        # resolved their futures, this waits out response serialization
        while time.monotonic() < deadline and \
                self.replica.total_inflight() > 0:
            time.sleep(0.005)
        return {"draining": True,
                "inFlightAtStart": inflight_at_start,
                "inFlightRemaining": self.replica.total_inflight(),
                "flushed": flushed, "failed": failed,
                "drainTimeoutSeconds": timeout_s}

    def kill(self) -> None:
        """Simulated SIGKILL (the chaos replica-crash fault,
        cluster/chaos.py): close the listener and every live
        connection with NO drain — in-flight clients see a reset or
        an empty response, queued work is abandoned. Real code never
        calls this; the soak does, to prove the fleet survives it."""
        self._killed = True
        if self._httpd:
            self._httpd.shutdown()
            # don't wait for handler threads — SIGKILL wouldn't
            self._httpd.block_on_close = False
            self._httpd.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # -- dispatch -----------------------------------------------------------

    def batcher(self, name: str) -> MicroBatcher:
        servable = self.repository.get(name)
        # check-then-set under a lock: handler threads race on first
        # request, and a losing MicroBatcher would leak its poll thread
        with self._batchers_lock:
            b = self._batchers.get(name)
            if b is None:
                b = MicroBatcher(servable, max_batch=self.max_batch,
                                 max_latency_ms=self.max_latency_ms,
                                 max_pending=self.max_pending,
                                 batching=self.batching,
                                 max_wait_ms=self.max_wait_ms)
                self._batchers[name] = b
                # queue depth + oldest-age gauges: scrape-time pull
                self.replica.register_queue(name, b)
        return b

    def metrics_text(self) -> str:
        """The standard exposition off the shared registry (names
        wire-compatible with the pre-registry hand-rolled text): the
        servable-owned totals are snapshotted in, the request-latency
        histogram is already live."""
        names = set(self.repository.names())
        # a model unloaded from the repository must stop exporting (its
        # frozen last totals would read as live — and as a counter reset
        # if the name is later re-added from zero)
        for gone in self._m_exported - names:
            self._m_requests.remove(model=gone)
            self._m_predict_s.remove(model=gone)
            self._m_latency.remove(model=gone)
        self._m_exported = names
        for name in names:
            servable = self.repository.get(name)
            meta = servable.metadata()["stats"]
            self._m_requests.labels(model=name).set(meta["request_count"])
            self._m_predict_s.labels(model=name).set(
                round(meta["predict_seconds"], 6))
            self.replica.set_start_kind(
                name, getattr(servable, "start_kind", "cold"))
        # the replica registry prunes its own series for gone models
        # and recomputes the rolling gauges + burn rates at scrape time
        self.replica.prune(names)
        self.replica.refresh()
        return self.registry.render()


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def setup(self):
            super().setup()
            with server._conns_lock:
                server._conns.add(self.connection)

        def finish(self):
            with server._conns_lock:
                server._conns.discard(self.connection)
            try:
                super().finish()
            except OSError:
                pass  # connection already torn down by kill()

        def _send(self, code: int, payload, content_type="application/json",
                  headers: Optional[dict] = None):
            if server._killed:
                # simulated SIGKILL: the response must never leave —
                # the client sees a dead connection, not a late answer
                raise OSError("server killed")
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode())
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, msg: str,
                   headers: Optional[dict] = None):
            try:
                self._send(code, {"error": msg}, headers=headers)
            except OSError:
                # the client gave up (deadline timeout, hedge winner
                # elsewhere) — a late error answer has nobody to read
                # it; the ledger already recorded the outcome
                pass

        def do_GET(self):
            path, _, rawq = self.path.partition("?")
            path = path.rstrip("/")
            if path == "/healthz":
                if "verbose=1" in rawq:
                    # the replica-health contract the router and
                    # autoscaler poll (serving/replica_state.py) —
                    # always 200: a draining replica must still be
                    # pollable (the payload carries `draining`)
                    return self._send(200, server.replica.snapshot())
                if "live=1" in rawq:
                    # liveness: the process is up — stays 200 through a
                    # drain so the kubelet doesn't kill a pod that is
                    # gracefully finishing its in-flight work
                    return self._send(200, {"status": "ok"})
                if server.replica.draining:
                    # readiness flip: endpoints controller pulls this
                    # pod out of the Service before it dies
                    return self._send(503, {"status": "draining"})
                return self._send(200, {"status": "ok"})
            if path == "/drain":
                # the preStop hook (manifests/serving.py renders an
                # httpGet here): synchronous bounded drain, so the
                # kubelet holds SIGTERM until in-flight work finished
                return self._send(200, server.drain())
            if path == "/metrics":
                return self._send(200, server.metrics_text().encode(),
                                  content_type="text/plain")
            if path.startswith("/v1/models/"):
                rest = path[len("/v1/models/"):]
                try:
                    if rest.endswith("/metadata"):
                        name = rest[:-len("/metadata")]
                        return self._send(
                            200, server.repository.get(name).metadata())
                    return self._send(
                        200, server.repository.get(rest).status())
                except KeyError as e:
                    return self._error(404, str(e))
            if path.startswith("/v1/routers/"):
                name = path[len("/v1/routers/"):]
                routed = server.routers.get(name)
                if routed is None:
                    return self._error(404, f"router {name!r} not found")
                return self._send(200, routed.status())
            self._error(404, f"no route {path}")

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length))

        def _parse_instances(self, req: dict) -> np.ndarray:
            if "instances" not in req:
                raise ValueError("missing 'instances' in request")
            instances = np.asarray(req["instances"])
            if "dtype" in req:
                instances = instances.astype(req["dtype"])
            return instances

        def _request_id(self) -> str:
            """Honor an inbound x-request-id (echoed on the response);
            mint otherwise — one id stamps every stage span."""
            return self.headers.get(REQUEST_ID_HEADER) or mint_request_id()

        def _force_sample(self) -> bool:
            """``x-request-sample: 1`` forces stage spans for THIS
            request regardless of the sampling cadence — the debug
            handle for 'reconstruct this exact request'."""
            return self.headers.get("x-request-sample") == "1"

        def _run_predict(self, predict, req: dict, ctx=None,
                         rid: Optional[str] = None):
            """Shared predict body: parse instances, run, serialize —
            one implementation for model and router endpoints. Instance
            decode is charged to batch-form (it IS forming the device
            input); the respond stage runs from the batcher's pipeline
            end (so the future-wakeup gap is respond time, not
            residual) through serialize + send."""
            t_parse = time.time()
            instances = self._parse_instances(req)
            if ctx is not None:
                ctx.stage("batch-form", t_parse, time.time(),
                          decode=True)
            out = predict(instances)
            t_resp = time.time()
            if ctx is not None and ctx.t_pipeline_end is not None:
                t_resp = min(t_resp, max(ctx.t_pipeline_end,
                                         ctx.t_accept))
            predictions = {
                k: np.asarray(v).tolist() for k, v in out.items()
            } if isinstance(out, dict) else np.asarray(out).tolist()
            self._send(200, {"predictions": predictions},
                       headers={REQUEST_ID_HEADER: rid} if rid else None)
            if ctx is not None:
                ctx.stage("respond", t_resp, time.time())

        def _deadline_s(self) -> Optional[float]:
            """The client's remaining deadline budget (the
            ``x-request-deadline`` contract: seconds the caller will
            still wait — serving/request_trace.py). Malformed reads as
            absent."""
            raw = self.headers.get(DEADLINE_HEADER)
            if raw is None:
                return None
            try:
                return max(0.0, float(raw))
            except (TypeError, ValueError):
                return None

        def do_POST(self):
            if self.path.rstrip("/") == "/drain":
                return self._send(200, server.drain())
            if ":" not in self.path:
                return self._error(404, "expected /v1/models/<name>:predict")
            route, verb = self.path.rsplit(":", 1)
            if route.startswith("/v1/routers/"):
                return self._router_post(route[len("/v1/routers/"):], verb)
            if not route.startswith("/v1/models/") or verb != "predict":
                return self._error(404, f"no route {self.path}")
            name = route[len("/v1/models/"):]
            rid = self._request_id()
            hdr = {REQUEST_ID_HEADER: rid}
            if server.replica.draining:
                # draining: refuse new work with an explicit retryable
                # 503 — the fleet router re-routes to a live replica
                return self._error(503, "draining",
                                   headers={**hdr, "Retry-After": "1"})
            ctx = None
            try:
                req = self._read_body()
                try:
                    batcher = server.batcher(name)
                except KeyError as e:  # unknown model only → 404
                    return self._error(404, str(e), headers=hdr)
                # the deadline budget bounds how long this request may
                # wait on the batcher future: past it the client is
                # gone — answer 504 instead of computing for nobody
                deadline_s = self._deadline_s()
                timeout = 30.0 if deadline_s is None \
                    else max(0.001, deadline_s)
                ctx = server.obs.begin(name, request_id=rid,
                                       force_sample=self._force_sample())
                server.replica.inflight_inc(name)
                t0 = time.perf_counter()
                try:
                    self._run_predict(
                        lambda x: batcher.predict(x, timeout=timeout,
                                                  ctx=ctx), req,
                        ctx=ctx, rid=rid)
                    ctx.finish("ok")
                finally:
                    server.replica.inflight_dec(name)
                    # errors are latency too (clients waited for them)
                    server._m_latency.labels(model=name).observe(
                        time.perf_counter() - t0)
            except QueueFullError as e:
                # bounded-queue shed: explicit 429, recorded in the
                # ledger (all-queue badput), never silently dropped.
                # Retry-After carries the drain-rate hint (ISSUE 18):
                # come back when the backlog you were shed behind has
                # drained, not at the client's blind jitter cadence.
                if ctx is not None:
                    ctx.finish("shed", error=str(e))
                self._error(429, f"QueueFullError: {e}", headers={
                    **hdr, "Retry-After":
                        f"{getattr(e, 'retry_after_s', 1.0):.1f}"})
            except FuturesTimeoutError:
                if ctx is not None:
                    ctx.finish("error", error="deadline exceeded")
                self._error(504, "deadline exceeded", headers=hdr)
            except Exception as e:  # noqa: BLE001 — surface to client
                if ctx is not None:
                    ctx.finish("error", error=f"{type(e).__name__}: {e}")
                # an exception may carry its own HTTP status (the chaos
                # 5xx-burst fault rides this; 5xx reads as retryable
                # weather to the fleet router, 400 stays meaning)
                code = int(getattr(e, "http_status", 400))
                self._error(code, f"{type(e).__name__}: {e}", headers=hdr)

        def _router_post(self, name: str, verb: str):
            """/v1/routers/<name>:predict and :feedback (the seldon
            /send-feedback analog)."""
            routed = server.routers.get(name)
            if routed is None:
                return self._error(404, f"router {name!r} not found")
            rid = self._request_id()
            hdr = {REQUEST_ID_HEADER: rid}
            ctx = None
            try:
                req = self._read_body()
                if verb == "feedback":
                    routed.record_feedback(req["arm"], float(req["reward"]))
                    return self._send(200, routed.status())
                if verb != "predict":
                    return self._error(404, f"unknown verb {verb!r}")
                # the router stamps the chosen arm onto the ctx once
                # routed; the span's model is the ARM, attrs carry the
                # router name (serving/router.py)
                ctx = server.obs.begin(f"router:{name}", request_id=rid,
                                       force_sample=self._force_sample())
                self._run_predict(
                    lambda x: routed.predict(x, ctx=ctx), req,
                    ctx=ctx, rid=rid)
                ctx.finish("ok")
            except QueueFullError as e:
                if ctx is not None:
                    ctx.finish("shed", error=str(e))
                self._error(429, f"QueueFullError: {e}", headers={
                    **hdr, "Retry-After":
                        f"{getattr(e, 'retry_after_s', 1.0):.1f}"})
            except Exception as e:  # noqa: BLE001 — surface to client
                if ctx is not None:
                    ctx.finish("error", error=f"{type(e).__name__}: {e}")
                self._error(400, f"{type(e).__name__}: {e}", headers=hdr)

    return Handler


def main(argv: Optional[list[str]] = None) -> int:
    """CLI: the in-pod entry the tpu-serving manifest runs
    (manifests/serving.py tpu_serving args)."""
    import argparse
    p = argparse.ArgumentParser("tpu-model-server")
    p.add_argument("--model-name", default="model")
    p.add_argument("--model-type", default="resnet50")
    p.add_argument("--model-path", default="")
    p.add_argument("--rest-port", type=int, default=8500)
    p.add_argument("--grpc-port", type=int, default=9000,
                   help="TF-Serving-compatible PredictionService port "
                        "(0 disables)")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--reload-interval", type=float, default=30.0,
                   help="poll the model path for new checkpoint versions "
                        "every N seconds (TF-Serving fs monitor; 0 = off)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip compiling the padded-bucket executables at "
                        "load (first request per bucket then pays the "
                        "XLA compile)")
    p.add_argument("--kernel-serving", default=None,
                   choices=["stock", "int8"],
                   help="serving kernel tier (spec.kernels.serving): "
                        "int8 = per-channel absmax quantized weights "
                        "behind the accuracy parity gate (default "
                        "$KFTPU_KERNEL_SERVING or stock)")
    p.add_argument("--int8-max-delta", type=float, default=None,
                   help="parity-gate threshold for --kernel-serving "
                        "int8: refuse to serve when the measured "
                        "argmax-disagreement delta exceeds this "
                        "(default $KFTPU_INT8_MAX_DELTA or 0.02)")
    p.add_argument("--batching", default="continuous",
                   choices=["continuous", "window"],
                   help="batcher admission scheduler: 'continuous' = "
                        "in-flight batching (the next batch forms from "
                        "everything queued the moment the previous "
                        "dispatch returns; ISSUE 18), 'window' = the "
                        "legacy fixed collect window (the PR 11 "
                        "baseline, kept for A/B)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="continuous batching's idle-device coalescing "
                        "bound: how long a lone request may hold for "
                        "co-riders when the device is idle (default: "
                        "the --max-latency window value; under load "
                        "nobody waits)")
    p.add_argument("--max-latency", type=float, default=5.0,
                   help="window mode's collect window in ms (and the "
                        "max-wait default for continuous mode)")
    p.add_argument("--max-pending", type=int, default=0,
                   help="bounded batcher queue: shed with 429 past this "
                        "many waiting requests (0 = unbounded; sheds "
                        "carry a drain-rate Retry-After hint)")
    p.add_argument("--sample-every", type=int, default=16,
                   help="emit per-stage trace spans for every Nth "
                        "request (the ledger summary span is always "
                        "emitted; 0 = summaries only)")
    p.add_argument("--span-path", default=None,
                   help="request-span JSONL sink (default: the "
                        "KFTPU_SPAN_PATH env contract)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="declarative latency SLO: target p99 in ms "
                        "(burn-rate gauges on /metrics)")
    p.add_argument("--slo-availability", type=float, default=None,
                   help="declarative availability SLO target, e.g. "
                        "0.999")
    p.add_argument("--drain-timeout", type=float, default=10.0,
                   help="graceful-drain budget in seconds: on SIGTERM "
                        "(or GET /drain, the preStop hook) readiness "
                        "flips, new work is refused with 503, the "
                        "batcher's pending cohort flushes, and "
                        "in-flight requests get this long to finish "
                        "before the process exits")
    args = p.parse_args(argv)

    # warm server restarts skip the per-bucket XLA compiles: warmup()
    # hits the persistent cache (KFTPU_COMPILE_CACHE_DIR, rendered by the
    # serving manifest onto the model volume)
    from ..runtime.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    repo = ModelRepository()
    # a QuantizationRefused from the int8 parity gate propagates and
    # kills the server at startup — an operator asking for a quantized
    # tier past its accuracy budget must see the refusal, not a
    # silently-float replica
    servable = repo.load(args.model_name, args.model_type,
                         checkpoint_dir=args.model_path or None,
                         kernels=args.kernel_serving,
                         quant_max_delta=args.int8_max_delta)
    servable.max_batch = args.max_batch
    if servable.quant is not None:
        print(f"int8 serving: accuracy delta "
              f"{servable.quant['accuracy_delta']} (gate "
              f"{servable.quant['max_delta']})", flush=True)
    if not args.no_warmup:
        buckets = servable.warmup()
        print(f"warmed buckets {buckets}", flush=True)
    if args.model_path and args.reload_interval:
        repo.start_polling(args.reload_interval)
    slos = {}
    if args.slo_p99_ms is not None or args.slo_availability is not None:
        from .replica_state import ModelSLO as _SLO
        slos[args.model_name] = _SLO(target_p99_ms=args.slo_p99_ms,
                                     availability=args.slo_availability)
    server = ModelServer(repo, port=args.rest_port,
                         max_batch=args.max_batch,
                         max_latency_ms=args.max_latency,
                         max_pending=args.max_pending,
                         sample_every=args.sample_every,
                         span_path=args.span_path, slos=slos,
                         drain_timeout_s=args.drain_timeout,
                         batching=args.batching,
                         max_wait_ms=args.max_wait_ms)
    port = server.start()
    grpc_server = None
    if args.grpc_port:
        from .grpc_server import GrpcPredictServer, HAVE_GRPC
        if HAVE_GRPC:
            grpc_server = GrpcPredictServer(server, port=args.grpc_port)
            gport = grpc_server.start()
            print(f"gRPC PredictionService on :{gport}", flush=True)
    print(f"model server listening on :{port} "
          f"(models: {repo.names()})", flush=True)

    # graceful drain on SIGTERM (the kubelet's pod-stop signal): flip
    # readiness, flush + finish in-flight up to --drain-timeout, THEN
    # die — the fleet router saw `draining` and stopped sending first
    done = threading.Event()

    def _sigterm(signum, frame):
        print("SIGTERM: draining "
              f"(budget {args.drain_timeout:.0f}s)", flush=True)
        report = server.drain()
        print(f"drain: {report}", flush=True)
        if grpc_server:
            grpc_server.stop(grace=args.drain_timeout)
        server.stop()
        done.set()

    import signal
    signal.signal(signal.SIGTERM, _sigterm)
    try:
        done.wait()
    except KeyboardInterrupt:
        if grpc_server:
            grpc_server.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
