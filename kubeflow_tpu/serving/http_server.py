"""REST model server: the TF-Serving-compatible HTTP surface.

Endpoint shape matches what the reference deploys and its E2E test probes
(tf-serving.libsonnet REST :8500; testing/test_tf_serving.py:110 posts to
``:8500/v1/models/mnist:predict``), merged with the http-proxy handlers
(components/k8s-model-server/http-proxy/server.py:27-40 — predict /
metadata / status):

- ``GET  /v1/models/<name>``            → version status
- ``GET  /v1/models/<name>/metadata``   → signature metadata
- ``POST /v1/models/<name>:predict``    → {"instances": [...]} →
  {"predictions": [...]}
- ``GET  /healthz`` and ``GET /metrics`` (prometheus text) — the
  observability the reference keeps in separate sidecars.

stdlib ThreadingHTTPServer: requests are I/O-light; the device work is
serialized by the per-model MicroBatcher.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..obs.registry import Registry
from .batcher import MicroBatcher
from .servable import ModelRepository


class ModelServer:
    def __init__(self, repository: Optional[ModelRepository] = None,
                 host: str = "0.0.0.0", port: int = 8500,
                 max_batch: int = 64, max_latency_ms: float = 5.0):
        self.repository = repository or ModelRepository()
        self.host, self.port = host, port
        self.max_batch = max_batch
        self.max_latency_ms = max_latency_ms
        self._batchers: dict[str, MicroBatcher] = {}
        self._batchers_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # experiment routers (A/B, bandit, shadow — serving/router.py)
        self.routers: dict[str, "object"] = {}
        # per-server registry (obs/registry.py), not the process default:
        # several ModelServers coexist in one test process and must not
        # share counts. The per-servable totals stay owned by the
        # servables (warmup and direct calls count too) and are bridged
        # into the exposition at scrape time; the REST latency histogram
        # is observed per request.
        self.registry = Registry()
        self._m_requests = self.registry.counter(
            "kubeflow_model_request_count", "requests per servable",
            labels=("model",))
        self._m_predict_s = self.registry.counter(
            "kubeflow_model_predict_seconds_total",
            "cumulative device predict seconds per servable",
            labels=("model",))
        self._m_latency = self.registry.histogram(
            "kubeflow_model_request_seconds",
            "end-to-end REST :predict latency", labels=("model",))
        self._m_exported: set = set()

    def add_router(self, routed) -> None:
        """Mount a RoutedModel at /v1/routers/<name>; when it serves this
        server's repository, its arms resolve through the server's
        MicroBatchers so routed and direct traffic batch together. A
        caller-set resolver or foreign repository is left alone."""
        if routed.predict_resolver is None and \
                routed.repository is self.repository:
            routed.predict_resolver = lambda arm: self.batcher(arm).predict
        self.routers[routed.name] = routed

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="model-server")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        for b in self._batchers.values():
            b.shutdown()

    # -- dispatch -----------------------------------------------------------

    def batcher(self, name: str) -> MicroBatcher:
        servable = self.repository.get(name)
        # check-then-set under a lock: handler threads race on first
        # request, and a losing MicroBatcher would leak its poll thread
        with self._batchers_lock:
            b = self._batchers.get(name)
            if b is None:
                b = MicroBatcher(servable, max_batch=self.max_batch,
                                 max_latency_ms=self.max_latency_ms)
                self._batchers[name] = b
        return b

    def metrics_text(self) -> str:
        """The standard exposition off the shared registry (names
        wire-compatible with the pre-registry hand-rolled text): the
        servable-owned totals are snapshotted in, the request-latency
        histogram is already live."""
        names = set(self.repository.names())
        # a model unloaded from the repository must stop exporting (its
        # frozen last totals would read as live — and as a counter reset
        # if the name is later re-added from zero)
        for gone in self._m_exported - names:
            self._m_requests.remove(model=gone)
            self._m_predict_s.remove(model=gone)
            self._m_latency.remove(model=gone)
        self._m_exported = names
        for name in names:
            meta = self.repository.get(name).metadata()["stats"]
            self._m_requests.labels(model=name).set(meta["request_count"])
            self._m_predict_s.labels(model=name).set(
                round(meta["predict_seconds"], 6))
        return self.registry.render()


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload, content_type="application/json"):
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode())
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, msg: str):
            self._send(code, {"error": msg})

        def do_GET(self):
            path = self.path.rstrip("/")
            if path == "/healthz":
                return self._send(200, {"status": "ok"})
            if path == "/metrics":
                return self._send(200, server.metrics_text().encode(),
                                  content_type="text/plain")
            if path.startswith("/v1/models/"):
                rest = path[len("/v1/models/"):]
                try:
                    if rest.endswith("/metadata"):
                        name = rest[:-len("/metadata")]
                        return self._send(
                            200, server.repository.get(name).metadata())
                    return self._send(
                        200, server.repository.get(rest).status())
                except KeyError as e:
                    return self._error(404, str(e))
            if path.startswith("/v1/routers/"):
                name = path[len("/v1/routers/"):]
                routed = server.routers.get(name)
                if routed is None:
                    return self._error(404, f"router {name!r} not found")
                return self._send(200, routed.status())
            self._error(404, f"no route {path}")

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length))

        def _parse_instances(self, req: dict) -> np.ndarray:
            if "instances" not in req:
                raise ValueError("missing 'instances' in request")
            instances = np.asarray(req["instances"])
            if "dtype" in req:
                instances = instances.astype(req["dtype"])
            return instances

        def _run_predict(self, predict, req: dict):
            """Shared predict body: parse instances, run, serialize —
            one implementation for model and router endpoints."""
            out = predict(self._parse_instances(req))
            predictions = {
                k: np.asarray(v).tolist() for k, v in out.items()
            } if isinstance(out, dict) else np.asarray(out).tolist()
            self._send(200, {"predictions": predictions})

        def do_POST(self):
            if ":" not in self.path:
                return self._error(404, "expected /v1/models/<name>:predict")
            route, verb = self.path.rsplit(":", 1)
            if route.startswith("/v1/routers/"):
                return self._router_post(route[len("/v1/routers/"):], verb)
            if not route.startswith("/v1/models/") or verb != "predict":
                return self._error(404, f"no route {self.path}")
            name = route[len("/v1/models/"):]
            try:
                req = self._read_body()
                try:
                    batcher = server.batcher(name)
                except KeyError as e:  # unknown model only → 404
                    return self._error(404, str(e))
                t0 = time.perf_counter()
                try:
                    self._run_predict(batcher.predict, req)
                finally:
                    # errors are latency too (clients waited for them)
                    server._m_latency.labels(model=name).observe(
                        time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 — surface to client
                self._error(400, f"{type(e).__name__}: {e}")

        def _router_post(self, name: str, verb: str):
            """/v1/routers/<name>:predict and :feedback (the seldon
            /send-feedback analog)."""
            routed = server.routers.get(name)
            if routed is None:
                return self._error(404, f"router {name!r} not found")
            try:
                req = self._read_body()
                if verb == "feedback":
                    routed.record_feedback(req["arm"], float(req["reward"]))
                    return self._send(200, routed.status())
                if verb != "predict":
                    return self._error(404, f"unknown verb {verb!r}")
                self._run_predict(routed.predict, req)
            except Exception as e:  # noqa: BLE001 — surface to client
                self._error(400, f"{type(e).__name__}: {e}")

    return Handler


def main(argv: Optional[list[str]] = None) -> int:
    """CLI: the in-pod entry the tpu-serving manifest runs
    (manifests/serving.py tpu_serving args)."""
    import argparse
    p = argparse.ArgumentParser("tpu-model-server")
    p.add_argument("--model-name", default="model")
    p.add_argument("--model-type", default="resnet50")
    p.add_argument("--model-path", default="")
    p.add_argument("--rest-port", type=int, default=8500)
    p.add_argument("--grpc-port", type=int, default=9000,
                   help="TF-Serving-compatible PredictionService port "
                        "(0 disables)")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--reload-interval", type=float, default=30.0,
                   help="poll the model path for new checkpoint versions "
                        "every N seconds (TF-Serving fs monitor; 0 = off)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip compiling the padded-bucket executables at "
                        "load (first request per bucket then pays the "
                        "XLA compile)")
    args = p.parse_args(argv)

    # warm server restarts skip the per-bucket XLA compiles: warmup()
    # hits the persistent cache (KFTPU_COMPILE_CACHE_DIR, rendered by the
    # serving manifest onto the model volume)
    from ..runtime.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    repo = ModelRepository()
    servable = repo.load(args.model_name, args.model_type,
                         checkpoint_dir=args.model_path or None)
    servable.max_batch = args.max_batch
    if not args.no_warmup:
        buckets = servable.warmup()
        print(f"warmed buckets {buckets}", flush=True)
    if args.model_path and args.reload_interval:
        repo.start_polling(args.reload_interval)
    server = ModelServer(repo, port=args.rest_port,
                         max_batch=args.max_batch)
    port = server.start()
    grpc_server = None
    if args.grpc_port:
        from .grpc_server import GrpcPredictServer, HAVE_GRPC
        if HAVE_GRPC:
            grpc_server = GrpcPredictServer(server, port=args.grpc_port)
            gport = grpc_server.start()
            print(f"gRPC PredictionService on :{gport}", flush=True)
    print(f"model server listening on :{port} "
          f"(models: {repo.names()})", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        if grpc_server:
            grpc_server.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
