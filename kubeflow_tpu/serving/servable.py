"""Servables: named, versioned, jit-compiled predict functions.

The TPU answer to TF-Serving's model loading (reference
kubeflow/tf-serving/tf-serving.libsonnet:5-60 — modelPath params from
GCS/S3/PVC): a Servable wraps a predict function + params restored from an
orbax checkpoint directory, compiled once per input bucket.

TPU notes: inputs are padded to power-of-two batch buckets so XLA compiles
a handful of programs, not one per request batch size; params are
device-put once at load; compute dtype follows the model (bf16 on TPU).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.registry import Registry

log = logging.getLogger(__name__)

PyTree = Any
# predict(params, batch_array) -> predictions array/pytree
PredictFn = Callable[[PyTree, jax.Array], Any]

# model-name → builder() -> (predict_fn, init_params_fn, input_signature)
_MODEL_BUILDERS: dict[str, Callable] = {}


def register_model(name: str):
    def deco(fn):
        _MODEL_BUILDERS[name] = fn
        return fn
    return deco


def next_bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n (capped): the static-shape bucket."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


# -- int8 quantized serving (ISSUE 16 kernel tier, serving rung) ----------
#
# Per-channel absmax weight quantization: every float matrix leaf
# (ndim >= 2) is stored as int8 with one f32 scale per OUTPUT channel
# (the last axis — the matmul's N dimension), computed as
# absmax/127 over the remaining axes. At predict the weights dequantize
# to f32 inside the jitted program, so every matmul accumulates in f32
# — XLA fuses the (int8 → f32 · scale) expansion into the matmul
# prologue; HBM holds 1/4 the weight bytes. Rank-0/1 leaves (biases,
# norm scales) stay float: they are bytes-irrelevant and
# precision-critical.
#
# The PARITY GATE is the contract that makes the tier shippable: the
# accuracy delta vs the float model is MEASURED on calibration batches
# at quantize time, ledgered (metadata + registry gauge — never
# hidden), and a delta past the configurable threshold REFUSES to
# serve (QuantizationRefused) rather than silently degrading.

INT8_MAX_DELTA_ENV = "KFTPU_INT8_MAX_DELTA"
DEFAULT_INT8_MAX_DELTA = 0.02  # ≤2% argmax disagreement by default

_Q_KEY = "__int8_q__"
_SCALE_KEY = "__int8_scale__"


class QuantizationRefused(RuntimeError):
    """The measured int8 accuracy delta exceeds the parity-gate
    threshold: the model must keep serving float."""


def quantize_params_int8(params: PyTree) -> tuple[PyTree, dict]:
    """Per-channel absmax int8 quantization of every float leaf with
    ndim >= 2. Returns (qtree, stats); quantized leaves become
    ``{_Q_KEY: int8, _SCALE_KEY: f32[..., 1, channels]}`` sub-dicts the
    pytree machinery carries like any other node."""
    n_q = n_kept = 0
    bytes_f = bytes_q = 0

    def q(p):
        nonlocal n_q, n_kept, bytes_f, bytes_q
        if getattr(p, "ndim", 0) >= 2 and \
                jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
            p32 = jnp.asarray(p, jnp.float32)
            amax = jnp.max(jnp.abs(p32), axis=tuple(range(p32.ndim - 1)),
                           keepdims=True)
            scale = jnp.maximum(amax, 1e-12) / 127.0
            qv = jnp.clip(jnp.round(p32 / scale), -127, 127
                          ).astype(jnp.int8)
            n_q += 1
            bytes_f += p32.size * 4
            bytes_q += qv.size + scale.size * 4
            return {_Q_KEY: qv, _SCALE_KEY: scale.astype(jnp.float32)}
        n_kept += 1
        sz = int(getattr(p, "size", 0)) * 4
        bytes_f += sz
        bytes_q += sz
        return p

    qtree = jax.tree.map(q, params)
    return qtree, {"quantized_leaves": n_q, "float_leaves": n_kept,
                   "weight_bytes_float": bytes_f,
                   "weight_bytes_int8": bytes_q}


def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and _Q_KEY in node


def dequantize_params(qtree: PyTree) -> PyTree:
    """int8 · per-channel f32 scale → f32 weights; runs INSIDE the
    jitted predict so XLA fuses it into each matmul's prologue."""
    return jax.tree.map(
        lambda n: (n[_Q_KEY].astype(jnp.float32) * n[_SCALE_KEY])
        if _is_qleaf(n) else n,
        qtree, is_leaf=_is_qleaf)


def _argmax_fields(out) -> Optional[np.ndarray]:
    """The discrete prediction the accuracy delta is measured on —
    'classes' (image models) or 'next_token' (LMs); None for models
    exposing neither (delta falls back to relative logits error)."""
    if isinstance(out, dict):
        for k in ("classes", "next_token"):
            if k in out:
                return np.asarray(out[k])
    return None


def quantize_servable(
    servable: "Servable",
    calibration: Optional[list] = None,
    *,
    max_delta: Optional[float] = None,
    calib_batches: int = 4,
    calib_batch_size: int = 8,
    seed: int = 0,
) -> "Servable":
    """Build the int8 Servable from a float one, behind the parity gate.

    ``calibration`` is a list of input batches (np arrays); when omitted
    they are synthesized from the input signature with a fixed seed —
    deterministic, so the ledgered delta is reproducible. ``max_delta``
    is the gate threshold (argmax-disagreement fraction); default
    $KFTPU_INT8_MAX_DELTA or 0.02. Raises QuantizationRefused past the
    threshold — the caller keeps serving the float model. The measured
    delta is ledgered either way: Servable.quant, metadata()['quantization'],
    and the kubeflow_model_quant_accuracy_delta gauge."""
    if max_delta is None:
        import os
        max_delta = float(os.environ.get(INT8_MAX_DELTA_ENV, "")
                          or DEFAULT_INT8_MAX_DELTA)
    if calibration is None:
        sig = servable.input_signature.get("inputs") or {}
        shape_tail = list(sig.get("shape") or [])[1:]
        if not shape_tail or any(d is None or d <= 0 for d in shape_tail):
            raise ValueError(
                f"model {servable.name!r} declares no synthesizable "
                f"input shape; pass calibration batches explicitly")
        dtype = np.dtype(sig.get("dtype", "float32"))
        rng = np.random.default_rng(seed)
        if np.issubdtype(dtype, np.integer):
            # token inputs: the transformer signature has no vocab
            # bound, keep ids small and valid for any vocab >= 256
            calibration = [rng.integers(
                0, 256, size=(calib_batch_size, *shape_tail)).astype(dtype)
                for _ in range(calib_batches)]
        else:
            calibration = [rng.standard_normal(
                (calib_batch_size, *shape_tail)).astype(dtype)
                for _ in range(calib_batches)]

    qparams, qstats = quantize_params_int8(servable.params)
    float_predict = servable.predict_fn

    def predict_int8(qtree, x):
        return float_predict(dequantize_params(qtree), x)

    quantized = Servable(
        name=servable.name, predict_fn=predict_int8, params=qparams,
        version=servable.version,
        input_signature=servable.input_signature,
        max_batch=servable.max_batch)

    # -- measure the delta: float vs int8 over the calibration set ------
    n_total = n_flipped = 0
    logits_err = 0.0
    for batch in calibration:
        out_f = servable.predict(np.asarray(batch))
        out_q = quantized.predict(np.asarray(batch))
        af, aq = _argmax_fields(out_f), _argmax_fields(out_q)
        if af is not None and aq is not None:
            n_total += af.size
            n_flipped += int(np.sum(af.reshape(-1) != aq.reshape(-1)))
        lf = out_f.get("logits") if isinstance(out_f, dict) else out_f
        lq = out_q.get("logits") if isinstance(out_q, dict) else out_q
        if lf is not None and lq is not None:
            lf, lq = np.asarray(lf, np.float64), np.asarray(lq, np.float64)
            denom = max(float(np.max(np.abs(lf))), 1e-12)
            logits_err = max(logits_err,
                             float(np.max(np.abs(lf - lq))) / denom)
    delta = (n_flipped / n_total) if n_total else logits_err

    quant_info = {
        "kernel": "int8",
        "accuracy_delta": round(float(delta), 6),
        "max_delta": float(max_delta),
        "logits_rel_err": round(float(logits_err), 6),
        "calibration_examples": int(
            sum(np.asarray(b).shape[0] for b in calibration)),
        **qstats,
    }
    # ledgered, never hidden: the gauge and metadata carry the delta
    # whether the gate passes or refuses
    quantized.quant = quant_info
    # the un-wrapped float predict: ModelRepository.reload rebuilds the
    # quantized servable from a NEW checkpoint version through the same
    # gate, so it needs the original predict_fn back
    quantized._float_predict = float_predict
    quantized.registry.gauge(
        "kubeflow_model_quant_accuracy_delta",
        "measured int8-vs-float accuracy delta (argmax disagreement)",
        labels=("model",)).labels(model=servable.name).set(float(delta))
    log.info("int8 quantization of %s: delta=%.4f (gate %.4f), "
             "logits_rel_err=%.5f, weight bytes %d -> %d",
             servable.name, delta, max_delta, logits_err,
             qstats["weight_bytes_float"], qstats["weight_bytes_int8"])
    if delta > max_delta:
        err = QuantizationRefused(
            f"int8 accuracy delta {delta:.4f} exceeds the parity gate "
            f"{max_delta:.4f} for model {servable.name!r}: refusing to "
            f"serve quantized (measured on "
            f"{quant_info['calibration_examples']} calibration "
            f"examples; delta ledgered)")
        # the measured delta rides the exception so refusal handlers
        # (bench gate drill, reload keep-old path) can ledger it without
        # re-parsing the message
        err.delta = float(delta)
        raise err
    return quantized


@dataclass
class Servable:
    """One loaded model version behind a compiled predict."""

    name: str
    predict_fn: PredictFn
    params: PyTree
    version: int = 1
    input_signature: dict = field(default_factory=dict)
    max_batch: int = 256
    # set by quantize_servable: the ledgered quantization record
    # (kernel, measured accuracy_delta, gate threshold, weight bytes)
    quant: Optional[dict] = None
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self):
        # per-servable stats on the shared obs Registry machinery
        # (they predate it and used to be a hand dict + lock): each
        # Servable owns its OWN Registry — several servers serving the
        # same model name coexist in one test process and must not
        # share counts — with the wire-compatible family names the
        # server exposition bridges (http_server.metrics_text)
        self.registry = Registry()
        self._m_requests = self.registry.counter(
            "kubeflow_model_request_count", "requests served",
            labels=("model",)).labels(model=self.name)
        self._m_predict_s = self.registry.counter(
            "kubeflow_model_predict_seconds_total",
            "cumulative device predict seconds",
            labels=("model",)).labels(model=self.name)
        # warm/cold start kind (PR 9 evidence): set by warmup() from
        # compile-cache stats; "cold" until proven warm
        self.start_kind = "cold"
        # one jit wrapper: jax caches per input shape, so each padded
        # bucket gets its own executable without any bookkeeping here
        self._jit_predict = jax.jit(self.predict_fn)

    @property
    def _stats(self) -> dict:
        """The legacy snapshot shape, now read off the registry
        counters (metadata()['stats'] consumers keep working)."""
        return {"request_count": int(self._m_requests.value),
                "predict_seconds": self._m_predict_s.value}

    def predict(self, instances: np.ndarray) -> np.ndarray:
        """Pad to bucket, run on device, slice back. Thread-safe."""
        out, _ = self.predict_with_stages(instances)
        return out

    def predict_with_stages(self, instances: np.ndarray) -> tuple:
        """predict() plus the per-stage attribution the request tracer
        charges its ledger from: ``(out, {"h2d_s", "device_s",
        "drain_s", "bucket", "rows", "pad_rows"})``. The split is
        host-observed: h2d = the device_put of the padded batch,
        device = dispatch + block_until_ready, drain = device→host
        copy of the results."""
        n = instances.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        if n > self.max_batch:
            # split oversized requests; serving never compiles > max
            # bucket. Stages aggregate across the chunks.
            parts = []
            agg = {"h2d_s": 0.0, "device_s": 0.0, "drain_s": 0.0,
                   "bucket": self.max_batch, "rows": n, "pad_rows": 0}
            for i in range(0, n, self.max_batch):
                out, st = self.predict_with_stages(
                    instances[i:i + self.max_batch])
                parts.append(out)
                for k in ("h2d_s", "device_s", "drain_s", "pad_rows"):
                    agg[k] += st[k]
            return jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0), *parts), agg
        bucket = next_bucket(n, self.max_batch)
        padded = instances
        if bucket != n:
            pad = np.zeros((bucket - n,) + instances.shape[1:],
                           instances.dtype)
            padded = np.concatenate([instances, pad], axis=0)
        t0 = time.perf_counter()
        dev_in = jnp.asarray(padded)
        t1 = time.perf_counter()
        out = jax.block_until_ready(
            self._jit_predict(self.params, dev_in))
        t2 = time.perf_counter()
        out = jax.device_get(out)
        t3 = time.perf_counter()
        self._m_requests.inc()
        self._m_predict_s.inc(t3 - t0)
        stages = {"h2d_s": t1 - t0, "device_s": t2 - t1,
                  "drain_s": t3 - t2, "bucket": bucket, "rows": n,
                  "pad_rows": bucket - n}
        return jax.tree.map(lambda x: np.asarray(x)[:n], out), stages

    def warmup(self, buckets: Optional[list[int]] = None) -> list[int]:
        """Compile the padded-bucket executables BEFORE serving traffic
        (SURVEY §7 hard part e: serving cold-start — jit compiles per
        input shape, so the first request on each bucket otherwise pays
        seconds of XLA compile). Runs a zero batch through each bucket;
        default = every power-of-two bucket up to max_batch. TF-Serving's
        model-warmup records play the same role."""
        sig = self.input_signature.get("inputs") or {}
        shape_tail = list(sig.get("shape") or [])[1:]
        if not shape_tail or any(d is None or d <= 0 for d in shape_tail):
            return []  # no synthesizable input shape declared
        if buckets is None:
            buckets, b = [], 1
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            # the cap bucket itself: oversized requests pad to max_batch,
            # which the doubling loop skips when it is not a power of two
            buckets.append(self.max_batch)
        dtype = np.dtype(sig.get("dtype", "float32"))
        # warm/cold evidence (the PR 9 start_kind rule, serving form):
        # if every bucket compile was served by the persistent cache —
        # hits and zero derived backend compiles — this replica started
        # WARM; the replica registry exports it so the router can
        # attribute a slow replica to a cold start
        from ..runtime.compile_cache import compile_stats
        before = compile_stats()
        # Compile through the jit wrapper directly: warmup must not move
        # serving metrics, and a snapshot/restore of _stats would also
        # discard increments from REAL requests landing concurrently
        # (the re-warm-under-traffic case test_serving exercises).
        for b in buckets:
            out = self._jit_predict(self.params,
                                    jnp.asarray(np.zeros((b, *shape_tail),
                                                         dtype)))
            jax.device_get(out)
        after = compile_stats()
        hits = after["cache_hits"] - before["cache_hits"]
        compiles = (after["xla_backend_compiles"]
                    - before["xla_backend_compiles"])
        if hits > 0 and compiles == 0:
            self.start_kind = "warm"
        return buckets

    def swap(self, params: PyTree, version: int) -> None:
        """Hot-swap to a newer model version. In-flight predicts finish on
        the old params (they captured the reference); the jit cache keys on
        shapes, so no recompile when the new version matches."""
        with self._lock:
            self.params = params
            self.version = version

    def metadata(self) -> dict:
        """TF-Serving /metadata analog (reference http-proxy
        server.py model-metadata handler)."""
        out = {
            "model_spec": {"name": self.name,
                           "version": str(self.version)},
            "signature_def": self.input_signature,
            "stats": dict(self._stats),
        }
        if self.quant is not None:
            # the quantization ledger rides the metadata surface the
            # dashboard's runs panel reads — the measured delta is
            # never hidden
            out["quantization"] = dict(self.quant)
        return out

    def status(self) -> dict:
        return {"model_version_status": [{
            "version": str(self.version),
            "state": "AVAILABLE",
            "status": {"error_code": "OK", "error_message": ""},
        }]}


class ModelRepository:
    """name → Servable registry with checkpoint loading.

    The model-server process's view of the reference's modelPath param:
    ``load(name, path)`` restores params with orbax (runtime/checkpoint)
    using a registered model builder, or accepts params directly.
    """

    def __init__(self):
        self._models: dict[str, Servable] = {}
        self._sources: dict[str, str] = {}  # name → checkpoint dir
        self._lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._poll_thread: Optional[threading.Thread] = None

    def add(self, servable: Servable) -> None:
        with self._lock:
            self._models[servable.name] = servable

    def load(self, name: str, model_type: str,
             checkpoint_dir: Optional[str] = None,
             kernels: Optional[str] = None,
             quant_max_delta: Optional[float] = None, **kw) -> Servable:
        """Load a servable; ``kernels`` selects the serving rung of the
        kernel tier (spec.kernels.serving → KFTPU_KERNEL_SERVING):
        "int8" quantizes behind the parity gate — a QuantizationRefused
        (delta past ``quant_max_delta``) propagates to the caller, it
        is NEVER downgraded silently."""
        if model_type not in _MODEL_BUILDERS:
            raise KeyError(
                f"unknown model type {model_type!r}; "
                f"registered: {sorted(_MODEL_BUILDERS)}")
        if kernels is None:
            import os
            kernels = os.environ.get("KFTPU_KERNEL_SERVING") or "stock"
        if kernels not in ("stock", "int8"):
            raise ValueError(
                f"kernels.serving {kernels!r} not one of "
                f"('stock', 'int8')")
        predict_fn, init_params, signature = _MODEL_BUILDERS[model_type](**kw)
        params = init_params()
        version = 1
        if checkpoint_dir:
            from ..runtime.checkpoint import CheckpointManager
            mgr = CheckpointManager(checkpoint_dir)
            step = mgr.latest_step()
            if step is not None:
                params = mgr.restore_params(step)
                version = step
            else:
                # nothing written yet (server started before the trainer):
                # version 0 so the trainer's FIRST checkpoint — possibly
                # step 1 — is newer and gets picked up by reload
                version = 0
            mgr.close()
        servable = Servable(name=name, predict_fn=predict_fn, params=params,
                            version=version, input_signature=signature)
        if kernels == "int8":
            servable = quantize_servable(servable,
                                         max_delta=quant_max_delta)
        self.add(servable)
        if checkpoint_dir:
            with self._lock:
                self._sources[name] = checkpoint_dir
        return servable

    # -- hot version reload (the TF-Serving file-system monitor behavior:
    # the server watches the model path and serves new versions as the
    # trainer writes them, old version until the new one is ready) --------

    def reload(self, name: str) -> bool:
        """Swap in a newer checkpoint version if one landed; False when
        already current or the model has no checkpoint source."""
        servable = self.get(name)
        with self._lock:
            src = self._sources.get(name)
        if not src:
            return False
        from ..runtime.checkpoint import CheckpointManager
        mgr = CheckpointManager(src)
        try:
            step = mgr.latest_step()
            if step is None or step <= servable.version:
                return False
            # template-free: the trainer writes full TrainState trees, the
            # server only wants the params subtree
            params = mgr.restore_params(step)
        finally:
            mgr.close()
        if servable.quant is not None:
            # a quantized servable can't swap raw float params in — the
            # new version re-quantizes through the SAME parity gate; a
            # refusal keeps the old quantized version serving
            base = Servable(
                name=servable.name,
                predict_fn=servable._float_predict, params=params,
                version=step, input_signature=servable.input_signature,
                max_batch=servable.max_batch)
            try:
                newq = quantize_servable(
                    base, max_delta=servable.quant["max_delta"])
            except QuantizationRefused as e:
                log.warning(
                    "model %s version %d refused by the int8 parity "
                    "gate (%s); keeping version %d", name, step, e,
                    servable.version)
                return False
            self.add(newq)
            log.info("model %s reloaded to version %d (int8, delta "
                     "%.4f)", name, step,
                     newq.quant["accuracy_delta"])
            return True
        servable.swap(params, step)
        log.info("model %s reloaded to version %d", name, step)
        return True

    def start_polling(self, interval_s: float = 30.0) -> None:
        """Background version monitor over every checkpoint-backed model."""
        if self._poll_thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(interval_s):
                for name in self.names():
                    try:
                        self.reload(name)
                    except Exception as e:  # noqa: BLE001 — keep serving
                        log.warning("reload %s failed: %s", name, e)

        self._poll_thread = threading.Thread(target=loop, daemon=True,
                                             name="model-version-poller")
        self._poll_thread.start()

    def stop_polling(self) -> None:
        if self._poll_thread is not None:
            self._stop.set()
            self._poll_thread.join(timeout=5)
            self._poll_thread = None

    def get(self, name: str) -> Servable:
        with self._lock:
            if name not in self._models:
                raise KeyError(f"model {name!r} not found; "
                               f"loaded: {sorted(self._models)}")
            return self._models[name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)


def _build_resnet(depth: int = 50, num_classes: int = 1000,
                  image_size: int = 224):
    from ..models import resnet as R
    model = R.make_resnet(depth, num_classes=num_classes)

    def init_params():
        return jax.jit(lambda rng: model.init(
            rng, jnp.zeros((1, image_size, image_size, 3), jnp.float32),
            train=False))(jax.random.PRNGKey(0))

    def predict(variables, images):
        logits = model.apply(variables, images, train=False)
        return {"logits": logits,
                "classes": jnp.argmax(logits, axis=-1)}

    sig = {"inputs": {"shape": [-1, image_size, image_size, 3],
                      "dtype": "float32"},
           "outputs": {"logits": [-1, num_classes], "classes": [-1]}}
    return predict, init_params, sig


from ..models import RESNET_DEPTHS  # noqa: E402 — light, no flax import

for _depth in RESNET_DEPTHS:
    register_model(f"resnet{_depth}")(partial(_build_resnet, depth=_depth))


@register_model("transformer_lm")
def _build_transformer(vocab_size: int = 32000, **cfg_kw):
    from ..models import transformer as T
    cfg = T.TransformerConfig(vocab_size=vocab_size, **cfg_kw)
    model = T.TransformerLM(cfg)

    def init_params():
        return {"params": T.init_fn(model, cfg.max_seq_len)(
            jax.random.PRNGKey(0))[0]}

    def predict(variables, tokens):
        logits = model.apply(variables, tokens)
        return {"logits": logits,
                "next_token": jnp.argmax(logits[:, -1], axis=-1)}

    sig = {"inputs": {"shape": [-1, cfg.max_seq_len], "dtype": "int32"},
           "outputs": {"logits": [-1, cfg.max_seq_len, vocab_size]}}
    return predict, init_params, sig
