"""Servables: named, versioned, jit-compiled predict functions.

The TPU answer to TF-Serving's model loading (reference
kubeflow/tf-serving/tf-serving.libsonnet:5-60 — modelPath params from
GCS/S3/PVC): a Servable wraps a predict function + params restored from an
orbax checkpoint directory, compiled once per input bucket.

TPU notes: inputs are padded to power-of-two batch buckets so XLA compiles
a handful of programs, not one per request batch size; params are
device-put once at load; compute dtype follows the model (bf16 on TPU).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.registry import Registry

log = logging.getLogger(__name__)

PyTree = Any
# predict(params, batch_array) -> predictions array/pytree
PredictFn = Callable[[PyTree, jax.Array], Any]

# model-name → builder() -> (predict_fn, init_params_fn, input_signature)
_MODEL_BUILDERS: dict[str, Callable] = {}


def register_model(name: str):
    def deco(fn):
        _MODEL_BUILDERS[name] = fn
        return fn
    return deco


def next_bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n (capped): the static-shape bucket."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


@dataclass
class Servable:
    """One loaded model version behind a compiled predict."""

    name: str
    predict_fn: PredictFn
    params: PyTree
    version: int = 1
    input_signature: dict = field(default_factory=dict)
    max_batch: int = 256
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self):
        # per-servable stats on the shared obs Registry machinery
        # (they predate it and used to be a hand dict + lock): each
        # Servable owns its OWN Registry — several servers serving the
        # same model name coexist in one test process and must not
        # share counts — with the wire-compatible family names the
        # server exposition bridges (http_server.metrics_text)
        self.registry = Registry()
        self._m_requests = self.registry.counter(
            "kubeflow_model_request_count", "requests served",
            labels=("model",)).labels(model=self.name)
        self._m_predict_s = self.registry.counter(
            "kubeflow_model_predict_seconds_total",
            "cumulative device predict seconds",
            labels=("model",)).labels(model=self.name)
        # warm/cold start kind (PR 9 evidence): set by warmup() from
        # compile-cache stats; "cold" until proven warm
        self.start_kind = "cold"
        # one jit wrapper: jax caches per input shape, so each padded
        # bucket gets its own executable without any bookkeeping here
        self._jit_predict = jax.jit(self.predict_fn)

    @property
    def _stats(self) -> dict:
        """The legacy snapshot shape, now read off the registry
        counters (metadata()['stats'] consumers keep working)."""
        return {"request_count": int(self._m_requests.value),
                "predict_seconds": self._m_predict_s.value}

    def predict(self, instances: np.ndarray) -> np.ndarray:
        """Pad to bucket, run on device, slice back. Thread-safe."""
        out, _ = self.predict_with_stages(instances)
        return out

    def predict_with_stages(self, instances: np.ndarray) -> tuple:
        """predict() plus the per-stage attribution the request tracer
        charges its ledger from: ``(out, {"h2d_s", "device_s",
        "drain_s", "bucket", "rows", "pad_rows"})``. The split is
        host-observed: h2d = the device_put of the padded batch,
        device = dispatch + block_until_ready, drain = device→host
        copy of the results."""
        n = instances.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        if n > self.max_batch:
            # split oversized requests; serving never compiles > max
            # bucket. Stages aggregate across the chunks.
            parts = []
            agg = {"h2d_s": 0.0, "device_s": 0.0, "drain_s": 0.0,
                   "bucket": self.max_batch, "rows": n, "pad_rows": 0}
            for i in range(0, n, self.max_batch):
                out, st = self.predict_with_stages(
                    instances[i:i + self.max_batch])
                parts.append(out)
                for k in ("h2d_s", "device_s", "drain_s", "pad_rows"):
                    agg[k] += st[k]
            return jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0), *parts), agg
        bucket = next_bucket(n, self.max_batch)
        padded = instances
        if bucket != n:
            pad = np.zeros((bucket - n,) + instances.shape[1:],
                           instances.dtype)
            padded = np.concatenate([instances, pad], axis=0)
        t0 = time.perf_counter()
        dev_in = jnp.asarray(padded)
        t1 = time.perf_counter()
        out = jax.block_until_ready(
            self._jit_predict(self.params, dev_in))
        t2 = time.perf_counter()
        out = jax.device_get(out)
        t3 = time.perf_counter()
        self._m_requests.inc()
        self._m_predict_s.inc(t3 - t0)
        stages = {"h2d_s": t1 - t0, "device_s": t2 - t1,
                  "drain_s": t3 - t2, "bucket": bucket, "rows": n,
                  "pad_rows": bucket - n}
        return jax.tree.map(lambda x: np.asarray(x)[:n], out), stages

    def warmup(self, buckets: Optional[list[int]] = None) -> list[int]:
        """Compile the padded-bucket executables BEFORE serving traffic
        (SURVEY §7 hard part e: serving cold-start — jit compiles per
        input shape, so the first request on each bucket otherwise pays
        seconds of XLA compile). Runs a zero batch through each bucket;
        default = every power-of-two bucket up to max_batch. TF-Serving's
        model-warmup records play the same role."""
        sig = self.input_signature.get("inputs") or {}
        shape_tail = list(sig.get("shape") or [])[1:]
        if not shape_tail or any(d is None or d <= 0 for d in shape_tail):
            return []  # no synthesizable input shape declared
        if buckets is None:
            buckets, b = [], 1
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            # the cap bucket itself: oversized requests pad to max_batch,
            # which the doubling loop skips when it is not a power of two
            buckets.append(self.max_batch)
        dtype = np.dtype(sig.get("dtype", "float32"))
        # warm/cold evidence (the PR 9 start_kind rule, serving form):
        # if every bucket compile was served by the persistent cache —
        # hits and zero derived backend compiles — this replica started
        # WARM; the replica registry exports it so the router can
        # attribute a slow replica to a cold start
        from ..runtime.compile_cache import compile_stats
        before = compile_stats()
        # Compile through the jit wrapper directly: warmup must not move
        # serving metrics, and a snapshot/restore of _stats would also
        # discard increments from REAL requests landing concurrently
        # (the re-warm-under-traffic case test_serving exercises).
        for b in buckets:
            out = self._jit_predict(self.params,
                                    jnp.asarray(np.zeros((b, *shape_tail),
                                                         dtype)))
            jax.device_get(out)
        after = compile_stats()
        hits = after["cache_hits"] - before["cache_hits"]
        compiles = (after["xla_backend_compiles"]
                    - before["xla_backend_compiles"])
        if hits > 0 and compiles == 0:
            self.start_kind = "warm"
        return buckets

    def swap(self, params: PyTree, version: int) -> None:
        """Hot-swap to a newer model version. In-flight predicts finish on
        the old params (they captured the reference); the jit cache keys on
        shapes, so no recompile when the new version matches."""
        with self._lock:
            self.params = params
            self.version = version

    def metadata(self) -> dict:
        """TF-Serving /metadata analog (reference http-proxy
        server.py model-metadata handler)."""
        return {
            "model_spec": {"name": self.name,
                           "version": str(self.version)},
            "signature_def": self.input_signature,
            "stats": dict(self._stats),
        }

    def status(self) -> dict:
        return {"model_version_status": [{
            "version": str(self.version),
            "state": "AVAILABLE",
            "status": {"error_code": "OK", "error_message": ""},
        }]}


class ModelRepository:
    """name → Servable registry with checkpoint loading.

    The model-server process's view of the reference's modelPath param:
    ``load(name, path)`` restores params with orbax (runtime/checkpoint)
    using a registered model builder, or accepts params directly.
    """

    def __init__(self):
        self._models: dict[str, Servable] = {}
        self._sources: dict[str, str] = {}  # name → checkpoint dir
        self._lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._poll_thread: Optional[threading.Thread] = None

    def add(self, servable: Servable) -> None:
        with self._lock:
            self._models[servable.name] = servable

    def load(self, name: str, model_type: str,
             checkpoint_dir: Optional[str] = None, **kw) -> Servable:
        if model_type not in _MODEL_BUILDERS:
            raise KeyError(
                f"unknown model type {model_type!r}; "
                f"registered: {sorted(_MODEL_BUILDERS)}")
        predict_fn, init_params, signature = _MODEL_BUILDERS[model_type](**kw)
        params = init_params()
        version = 1
        if checkpoint_dir:
            from ..runtime.checkpoint import CheckpointManager
            mgr = CheckpointManager(checkpoint_dir)
            step = mgr.latest_step()
            if step is not None:
                params = mgr.restore_params(step)
                version = step
            else:
                # nothing written yet (server started before the trainer):
                # version 0 so the trainer's FIRST checkpoint — possibly
                # step 1 — is newer and gets picked up by reload
                version = 0
            mgr.close()
        servable = Servable(name=name, predict_fn=predict_fn, params=params,
                            version=version, input_signature=signature)
        self.add(servable)
        if checkpoint_dir:
            with self._lock:
                self._sources[name] = checkpoint_dir
        return servable

    # -- hot version reload (the TF-Serving file-system monitor behavior:
    # the server watches the model path and serves new versions as the
    # trainer writes them, old version until the new one is ready) --------

    def reload(self, name: str) -> bool:
        """Swap in a newer checkpoint version if one landed; False when
        already current or the model has no checkpoint source."""
        servable = self.get(name)
        with self._lock:
            src = self._sources.get(name)
        if not src:
            return False
        from ..runtime.checkpoint import CheckpointManager
        mgr = CheckpointManager(src)
        try:
            step = mgr.latest_step()
            if step is None or step <= servable.version:
                return False
            # template-free: the trainer writes full TrainState trees, the
            # server only wants the params subtree
            params = mgr.restore_params(step)
        finally:
            mgr.close()
        servable.swap(params, step)
        log.info("model %s reloaded to version %d", name, step)
        return True

    def start_polling(self, interval_s: float = 30.0) -> None:
        """Background version monitor over every checkpoint-backed model."""
        if self._poll_thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(interval_s):
                for name in self.names():
                    try:
                        self.reload(name)
                    except Exception as e:  # noqa: BLE001 — keep serving
                        log.warning("reload %s failed: %s", name, e)

        self._poll_thread = threading.Thread(target=loop, daemon=True,
                                             name="model-version-poller")
        self._poll_thread.start()

    def stop_polling(self) -> None:
        if self._poll_thread is not None:
            self._stop.set()
            self._poll_thread.join(timeout=5)
            self._poll_thread = None

    def get(self, name: str) -> Servable:
        with self._lock:
            if name not in self._models:
                raise KeyError(f"model {name!r} not found; "
                               f"loaded: {sorted(self._models)}")
            return self._models[name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)


def _build_resnet(depth: int = 50, num_classes: int = 1000,
                  image_size: int = 224):
    from ..models import resnet as R
    model = R.make_resnet(depth, num_classes=num_classes)

    def init_params():
        return jax.jit(lambda rng: model.init(
            rng, jnp.zeros((1, image_size, image_size, 3), jnp.float32),
            train=False))(jax.random.PRNGKey(0))

    def predict(variables, images):
        logits = model.apply(variables, images, train=False)
        return {"logits": logits,
                "classes": jnp.argmax(logits, axis=-1)}

    sig = {"inputs": {"shape": [-1, image_size, image_size, 3],
                      "dtype": "float32"},
           "outputs": {"logits": [-1, num_classes], "classes": [-1]}}
    return predict, init_params, sig


from ..models import RESNET_DEPTHS  # noqa: E402 — light, no flax import

for _depth in RESNET_DEPTHS:
    register_model(f"resnet{_depth}")(partial(_build_resnet, depth=_depth))


@register_model("transformer_lm")
def _build_transformer(vocab_size: int = 32000, **cfg_kw):
    from ..models import transformer as T
    cfg = T.TransformerConfig(vocab_size=vocab_size, **cfg_kw)
    model = T.TransformerLM(cfg)

    def init_params():
        return {"params": T.init_fn(model, cfg.max_seq_len)(
            jax.random.PRNGKey(0))[0]}

    def predict(variables, tokens):
        logits = model.apply(variables, tokens)
        return {"logits": logits,
                "next_token": jnp.argmax(logits[:, -1], axis=-1)}

    sig = {"inputs": {"shape": [-1, cfg.max_seq_len], "dtype": "int32"},
           "outputs": {"logits": [-1, cfg.max_seq_len, vocab_size]}}
    return predict, init_params, sig
