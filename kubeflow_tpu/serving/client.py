"""Serving client: the inception-client / label.py analog.

The reference ships a standalone client that sends an image to the
deployed model server and prints the top-k labels
(components/k8s-model-server/inception-client/label.py). Same tool here
against the TPU model server's TF-Serving-compatible REST surface
(serving/http_server.py `POST /v1/models/<name>:predict`), reading either
a record-shard image (data/imagenet.py format) or a raw .npy array.

    python -m kubeflow_tpu.serving.client --server host:8500 \
        --model resnet50 --npy image.npy --top-k 5
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from ..cluster.http_client import jittered_backoff, retry_after_s
from .request_trace import (DEADLINE_HEADER, REQUEST_ID_HEADER,
                            mint_request_id)


def predict(server: str, model: str, instances, dtype: str = "float32",
            timeout_s: float = 60.0, request_id: Optional[str] = None,
            retries: int = 2, backoff_s: float = 0.1) -> dict:
    """POST :predict with the bounded-retry shape of
    cluster/http_client.py: transient failures (connect errors, 5xx,
    429) retry up to ``retries`` times with jittered backoff, a
    server-sent Retry-After (a throttling 429/503) is honored, and 4xx
    semantics surface immediately — meaning, not weather. One
    ``x-request-id`` is minted up front and propagated across every
    attempt (the server echoes it), and the remaining ``timeout_s``
    budget rides the ``x-request-deadline`` header so the server — and
    any fleet router in between — can never spend longer on retries
    than this caller will wait."""
    url = f"http://{server}/v1/models/{model}:predict"
    payload = json.dumps({"instances": instances, "dtype": dtype}).encode()
    rid = request_id or mint_request_id()
    deadline = time.monotonic() + timeout_s
    delay = backoff_s
    for attempt in range(retries + 1):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"predict {model!r}: deadline budget ({timeout_s:.1f}s) "
                f"exhausted after {attempt} attempt(s)")
        req = urllib.request.Request(
            url, data=payload, method="POST",
            headers={"Content-Type": "application/json",
                     REQUEST_ID_HEADER: rid,
                     DEADLINE_HEADER: f"{remaining:.3f}"})
        try:
            with urllib.request.urlopen(req, timeout=remaining) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            e.read()
            transient = e.code == 429 or e.code >= 500
            if not transient or attempt >= retries:
                raise
            sleep = max(jittered_backoff(delay),
                        retry_after_s(e.headers) or 0.0)
        except (urllib.error.URLError, TimeoutError, OSError):
            if attempt >= retries:
                raise
            sleep = jittered_backoff(delay)
        time.sleep(min(sleep, max(0.0, deadline - time.monotonic())))
        delay *= 2
    raise RuntimeError("unreachable")  # pragma: no cover


def predict_grpc(server: str, model: str, instances,
                 dtype: str = "float32", timeout_s: float = 60.0) -> dict:
    """Predict over the gRPC surface (the reference inception-client's
    wire: PredictionService on :9000 — serving/grpc_server.py here).
    Binary tensors, ~20x less wire than REST JSON floats at 224px."""
    import grpc as grpc_mod

    from . import tpu_serving_pb2 as pb
    from .grpc_server import ndarray_to_tensor, predict_stub, tensor_to_ndarray
    channel = grpc_mod.insecure_channel(server)
    try:
        stub = predict_stub(channel)
        req = pb.PredictRequest()
        req.model_spec.name = model
        req.inputs["instances"].CopyFrom(
            ndarray_to_tensor(np.asarray(instances, np.dtype(dtype))))
        resp = stub["Predict"](req, timeout=timeout_s)
        # REST-shaped result: named outputs become the predictions dict
        # (logits preferred by _first_output), a single unnamed output
        # becomes the bare list
        outs = {k: tensor_to_ndarray(v).tolist()
                for k, v in resp.outputs.items()}
        if list(outs) == ["outputs"]:
            return {"predictions": outs["outputs"]}
        return {"predictions": outs}
    finally:
        channel.close()


def _first_output(predictions) -> list:
    """predictions is either a list (single-output models) or a dict of
    named outputs (the TF-Serving response shape); prefer 'logits'."""
    if isinstance(predictions, dict):
        for key in ("logits", "y", "outputs"):
            if key in predictions:
                return predictions[key]
        predictions = next(iter(predictions.values()))
    return predictions


def top_k(logits, k: int = 5,
          labels: Optional[list[str]] = None) -> list[dict]:
    arr = np.asarray(logits, np.float32)
    idx = np.argsort(arr)[::-1][:k]
    exp = np.exp(arr - arr.max())
    probs = exp / exp.sum()
    return [{"class": int(i),
             "label": labels[i] if labels and i < len(labels) else str(i),
             "score": float(probs[i])} for i in idx]


def load_image(npy: Optional[str], data_dir: Optional[str],
               index: int) -> np.ndarray:
    if npy:
        return np.load(npy)
    if data_dir:
        from ..data.imagenet import ImageNetSource
        with ImageNetSource(data_dir, batch_size=1, augment=False) as src:
            batch = next(src.epoch(0, seed=0, skip=index))
            return batch["images"][0]
    raise SystemExit("one of --npy / --data-dir is required")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="TPU model-server client")
    p.add_argument("--server",
                   help="host:port (default: 127.0.0.1:8500 REST, "
                        "127.0.0.1:9000 with --grpc)")
    p.add_argument("--model", default="resnet50")
    p.add_argument("--npy", help="image array (.npy)")
    p.add_argument("--data-dir", help="record-shard dir; sends record N")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--top-k", type=int, default=5)
    p.add_argument("--labels", help="text file, one label per line")
    p.add_argument("--grpc", action="store_true",
                   help="use the PredictionService gRPC wire (:9000) "
                        "instead of REST")
    args = p.parse_args(argv)

    image = load_image(args.npy, args.data_dir, args.index)
    labels = None
    if args.labels:
        with open(args.labels) as f:
            labels = [line.strip() for line in f]
    server = args.server or \
        ("127.0.0.1:9000" if args.grpc else "127.0.0.1:8500")
    # gRPC carries binary tensor_content: hand it the ndarray directly
    # (tolist() would materialize ~150k Python floats per 224px image)
    result = predict_grpc(server, args.model, image[None]) if args.grpc \
        else predict(server, args.model, [image.tolist()])
    preds = _first_output(result.get("predictions") or [])
    if not len(preds):
        print(json.dumps(result))
        return 1
    for entry in top_k(preds[0], args.top_k, labels):
        print(f"{entry['score']:.4f}  {entry['label']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
