"""Micro-batching queue: concurrent requests → one device dispatch.

TF-Serving batches on-device; the reference's HTTP proxy forwards one
request at a time (http-proxy/server.py). On TPU, per-request dispatch
wastes the MXU — the batcher coalesces requests that arrive within
``max_latency_ms`` into a single padded batch, runs one jit call, and
fans results back out to per-request futures.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

import jax
import numpy as np


@dataclass
class _WorkItem:
    instances: np.ndarray
    future: Future


class MicroBatcher:
    """Collects requests for one servable and dispatches merged batches."""

    def __init__(self, servable, max_batch: int = 64,
                 max_latency_ms: float = 5.0):
        self.servable = servable
        self.max_batch = max_batch
        self.max_latency = max_latency_ms / 1000.0
        self._queue: "queue.Queue[_WorkItem]" = queue.Queue()
        self._stop = threading.Event()
        self._submit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"batcher-{servable.name}")
        self._thread.start()

    def submit(self, instances: np.ndarray) -> Future:
        item = _WorkItem(np.asarray(instances), Future())
        # Lock makes the stop-check + put atomic w.r.t. shutdown()'s
        # stop-set + drain, so no item can land after the final drain and
        # leave its future forever unresolved.
        with self._submit_lock:
            if self._stop.is_set():
                raise RuntimeError("batcher is shut down")
            self._queue.put(item)
        return item.future

    def predict(self, instances: np.ndarray, timeout: float = 30.0):
        return self.submit(instances).result(timeout=timeout)

    def _collect(self) -> list[_WorkItem]:
        """Block for the first item, then drain what arrives within the
        latency window (or until the batch is full)."""
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return []
        items, total = [first], first.instances.shape[0]
        deadline = self.max_latency
        t0 = time.perf_counter()
        while total < self.max_batch:
            remaining = deadline - (time.perf_counter() - t0)
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            items.append(nxt)
            total += nxt.instances.shape[0]
        return items

    def _dispatch(self, items: list[_WorkItem]):
        """One device call for a shape-compatible cohort; errors fan out
        only to that cohort."""
        batch = np.concatenate([it.instances for it in items], axis=0)
        try:
            out = self.servable.predict(batch)
        except Exception as e:  # noqa: BLE001 — fan the error out
            for it in items:
                it.future.set_exception(e)
            return
        ofs = 0
        for it in items:
            n = it.instances.shape[0]
            it.future.set_result(
                jax.tree.map(lambda x: x[ofs:ofs + n], out))
            ofs += n

    def _loop(self):
        while not self._stop.is_set():
            items = self._collect()
            if not items:
                continue
            # Group by trailing shape + dtype: one malformed request must
            # not poison the other requests coalesced into its window.
            groups: dict[tuple, list[_WorkItem]] = {}
            for it in items:
                if it.instances.ndim < 1:
                    it.future.set_exception(ValueError(
                        "instances must have a batch dimension"))
                    continue
                key = (it.instances.shape[1:], str(it.instances.dtype))
                groups.setdefault(key, []).append(it)
            for cohort in groups.values():
                self._dispatch(cohort)

    def shutdown(self):
        with self._submit_lock:
            self._stop.set()
        self._thread.join(timeout=5)
        while True:  # fail any stragglers
            try:
                self._queue.get_nowait().future.set_exception(
                    RuntimeError("batcher shut down"))
            except queue.Empty:
                break
