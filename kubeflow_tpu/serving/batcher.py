"""Micro-batching queue: concurrent requests → one device dispatch.

TF-Serving batches on-device; the reference's HTTP proxy forwards one
request at a time (http-proxy/server.py). On TPU, per-request dispatch
wastes the MXU — the batcher coalesces concurrent requests into a
single padded batch, runs one jit call, and fans results back out to
per-request futures. Two admission schedulers (``batching=``):

- ``continuous`` (default, ISSUE 18): in-flight batching. The moment
  the previous device dispatch returns, the next batch is formed
  greedily — oldest-first, everything already queued, up to
  ``max_batch`` — and dispatched immediately; nobody waits for a
  window edge while the device has work to do. Only when the device
  was IDLE (the queue was empty when the loop came back) does the
  first arrival wait, and then at most ``max_wait_ms``, purely as a
  coalescing bound so a lone request can pick up co-riders.
- ``window`` (legacy, the PR 11 baseline and the bench A/B arm): the
  fixed ``max_latency_ms`` collect window — first arrival opens a
  window, dispatch happens at the window edge or at ``max_batch``.
  Under load this queues bursts behind the window edge: the measured
  p99 knee (102→191 ms at 2× load) continuous batching removes.

Observability (ISSUE 11): each work item may carry a RequestTrace
(serving/request_trace.py) — the batcher stamps its queue wait,
batch-form share, H2D/device/pad-waste/drain shares onto it, so one
request's ledger partitions its wall-clock exactly. A bounded queue
(``max_pending``) sheds load with an explicit QueueFullError (HTTP
429 / gRPC RESOURCE_EXHAUSTED upstream) instead of growing the queue
unbounded — the shed request's wait is recorded as ``queue`` badput,
never dropped from the ledger, and the error carries a ``Retry-After``
hint from the measured drain rate. Queue depth and oldest-waiting age
are polled by the replica registry at scrape time (zero hot-path
cost); an item leaves both gauges the moment it is admitted to a
forming cohort — admitted work is device backlog, not queue backlog,
and the autoscaler scales on the queue gauges (ISSUE 18).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np


class QueueFullError(RuntimeError):
    """The bounded batcher queue is at max_pending: shed this request
    (429 / RESOURCE_EXHAUSTED) rather than queue it unbounded.

    ``retry_after_s`` is the shed hint the HTTP layer surfaces as a
    ``Retry-After`` header: current queue depth over the measured
    dispatch drain rate (EWMA requests/s through the device), clamped
    to [1, 30] s — "come back when the backlog you were shed behind
    has drained", not a bare 429 the client can only guess at."""

    retry_after_s: float = 1.0


class BatcherClosedError(RuntimeError):
    """The batcher is draining or shut down: this replica is going
    away, not misbehaving. ``http_status = 503`` makes the HTTP layer
    answer retryable weather (the fleet router re-routes) instead of a
    non-retryable 400 — a request racing a graceful drain must never
    fail hard while N-1 healthy replicas could serve it."""

    http_status = 503


@dataclass
class _WorkItem:
    instances: np.ndarray
    future: Future
    ctx: Optional[object] = None      # RequestTrace (or None)
    t_enqueue: float = 0.0


class MicroBatcher:
    """Collects requests for one servable and dispatches merged batches."""

    BATCHING_MODES = ("continuous", "window")

    def __init__(self, servable, max_batch: int = 64,
                 max_latency_ms: float = 5.0, max_pending: int = 0,
                 batching: str = "continuous",
                 max_wait_ms: Optional[float] = None):
        if batching not in self.BATCHING_MODES:
            raise ValueError(
                f"batching must be one of {self.BATCHING_MODES}, "
                f"got {batching!r}")
        self.servable = servable
        self.max_batch = max_batch
        self.max_latency = max_latency_ms / 1000.0
        self.batching = batching
        # continuous mode's idle-device coalescing bound; defaults to
        # the window knob so one number tunes either scheduler
        self.max_wait = (max_latency_ms if max_wait_ms is None
                         else max_wait_ms) / 1000.0
        # 0 = unbounded (the legacy behavior); N = shed at N waiting
        self.max_pending = max(0, int(max_pending))
        # EWMA of requests/s through the device: the Retry-After hint's
        # denominator. Written only by the loop thread, read anywhere
        # (float store is atomic under the GIL).
        self._drain_rate = 0.0
        self._queue: "queue.Queue[_WorkItem]" = queue.Queue()
        self._stop = threading.Event()
        self._draining = False
        self._submit_lock = threading.Lock()
        # waiting-item enqueue times for the oldest-age gauge: keyed by
        # item id, removed when the loop collects the item
        self._waiting: dict[int, float] = {}
        self._batch_ids = itertools.count(1)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"batcher-{servable.name}")
        self._thread.start()

    # ------------------------------------------------------ queue telemetry

    def queue_depth(self) -> int:
        """Requests waiting (not yet pulled into a batch)."""
        with self._submit_lock:
            return len(self._waiting)

    def oldest_wait_s(self) -> float:
        """Age of the oldest waiting request; 0 when the queue is empty."""
        with self._submit_lock:
            if not self._waiting:
                return 0.0
            return max(0.0, time.time() - min(self._waiting.values()))

    def retry_after_s(self) -> float:
        """The shed hint: seconds until the current backlog drains at
        the measured dispatch rate, clamped to [1, 30]. 1 s when no
        rate has been measured yet (cold batcher)."""
        with self._submit_lock:
            depth = len(self._waiting)
        return self._retry_hint(depth)

    def _retry_hint(self, depth: int) -> float:
        rate = self._drain_rate
        if rate <= 0.0:
            return 1.0
        return min(30.0, max(1.0, depth / rate))

    # -------------------------------------------------------------- submit

    def submit(self, instances: np.ndarray,
               ctx: Optional[object] = None) -> Future:
        item = _WorkItem(np.asarray(instances), Future(), ctx=ctx)
        # Lock makes the stop-check + put atomic w.r.t. shutdown()'s
        # stop-set + drain, so no item can land after the final drain and
        # leave its future forever unresolved.
        with self._submit_lock:
            if self._stop.is_set():
                raise BatcherClosedError("batcher is shut down")
            if self._draining:
                # drain closed the door: the cohort already queued gets
                # flushed, but no new work may land behind it
                raise BatcherClosedError("batcher is draining")
            if self.max_pending and len(self._waiting) >= self.max_pending:
                err = QueueFullError(
                    f"batcher queue full ({self.max_pending} pending)")
                err.retry_after_s = self._retry_hint(len(self._waiting))
                raise err
            item.t_enqueue = time.time()
            self._waiting[id(item)] = item.t_enqueue
            self._queue.put(item)
        return item.future

    def predict(self, instances: np.ndarray, timeout: float = 30.0,
                ctx: Optional[object] = None):
        return self.submit(instances, ctx=ctx).result(timeout=timeout)

    def _take(self, timeout: Optional[float] = None) -> Optional[_WorkItem]:
        """Pull one queued item into the forming cohort. Admission is
        when it leaves the queue GAUGES (scrape-time depth/oldest-age
        must stop counting it immediately — admitted work is device
        backlog the autoscaler must not double-count as queue backlog),
        so ``_waiting`` is popped here, at pull time, not at dispatch.
        ``timeout=None`` means non-blocking."""
        try:
            item = (self._queue.get_nowait() if timeout is None
                    else self._queue.get(timeout=timeout))
        except queue.Empty:
            return None
        with self._submit_lock:
            self._waiting.pop(id(item), None)
        return item

    def _seal(self, items: list[_WorkItem]) -> None:
        """The cohort is final: close every member's ``queue`` ledger
        stage at one shared seal instant (enqueue → admission-to-cohort;
        dispatch starts immediately after, so the ledger still
        partitions wall-clock exactly — no unattributed gap)."""
        now = time.time()
        for it in items:
            if it.ctx is not None:
                it.ctx.stage("queue", it.t_enqueue, now)

    def _admit(self) -> list[_WorkItem]:
        """Continuous (in-flight) admission: greedily form the next
        batch from whatever is queued RIGHT NOW — the loop re-enters
        the moment the previous dispatch returned, so under load no
        request ever waits on a window edge. Only when the device was
        idle (nothing queued on re-entry) does the first arrival hold
        for co-riders, bounded by ``max_wait_ms``; a drain skips even
        that (flush now, nobody new is coming)."""
        first = self._take()
        was_idle = first is None
        if was_idle:
            first = self._take(timeout=0.1)
            if first is None:
                return []
        items, total = [first], first.instances.shape[0]
        while total < self.max_batch:
            nxt = self._take()
            if nxt is None:
                break
            items.append(nxt)
            total += nxt.instances.shape[0]
        if was_idle and total < self.max_batch and self.max_wait > 0 \
                and not self._draining:
            t0 = time.perf_counter()
            while total < self.max_batch:
                remaining = self.max_wait - (time.perf_counter() - t0)
                if remaining <= 0:
                    break
                nxt = self._take(timeout=remaining)
                if nxt is None:
                    break
                items.append(nxt)
                total += nxt.instances.shape[0]
        self._seal(items)
        return items

    def _collect(self) -> list[_WorkItem]:
        """Fixed-window collect (``batching="window"``, the PR 11
        baseline): block for the first item, then drain what arrives
        within the latency window (or until the batch is full)."""
        first = self._take(timeout=0.1)
        if first is None:
            return []
        items, total = [first], first.instances.shape[0]
        deadline = self.max_latency
        t0 = time.perf_counter()
        while total < self.max_batch:
            remaining = deadline - (time.perf_counter() - t0)
            if remaining <= 0:
                break
            nxt = self._take(timeout=remaining)
            if nxt is None:
                break
            items.append(nxt)
            total += nxt.instances.shape[0]
        self._seal(items)
        return items

    def _dispatch(self, items: list[_WorkItem]):
        """One device call for a shape-compatible cohort; errors fan out
        only to that cohort. Each item's ctx gets the cohort's FULL
        stage intervals (the request lived through the whole shared
        pipeline — its wall-clock partitions exactly), with the device
        interval split by the cohort's fill: the real-row fraction is
        serving goodput (co-riders' rows are useful work the request
        rode along with), the pad fraction is pad_waste."""
        traced = [it for it in items if it.ctx is not None]
        t_form0 = time.perf_counter()
        tw_form0 = time.time()
        batch = np.concatenate([it.instances for it in items], axis=0)
        form_s = time.perf_counter() - t_form0
        try:
            if hasattr(self.servable, "predict_with_stages"):
                out, stages = self.servable.predict_with_stages(batch)
            else:
                out, stages = self.servable.predict(batch), None
        except Exception as e:  # noqa: BLE001 — fan the error out
            for it in items:
                it.future.set_exception(e)
            return
        if traced:
            self._record_stages(items, traced, stages, form_s, tw_form0)
        ofs = 0
        for it in items:
            n = it.instances.shape[0]
            it.future.set_result(
                jax.tree.map(lambda x: x[ofs:ofs + n], out))
            ofs += n

    def _record_stages(self, items, traced, stages, form_s: float,
                       tw_form0: float) -> None:
        rows_total = sum(it.instances.shape[0] for it in items)
        batch_id = next(self._batch_ids)
        if stages is None:
            stages = {"h2d_s": 0.0, "device_s": 0.0, "drain_s": 0.0,
                      "bucket": rows_total, "rows": rows_total,
                      "pad_rows": 0}
        bucket = max(1, int(stages.get("bucket", rows_total)))
        pad_rows = int(stages.get("pad_rows", 0))
        # padded_total covers the oversized-split case too (several
        # chunks, each padded): real + pad rows actually computed
        padded_total = max(1, rows_total + pad_rows)
        fill = rows_total / padded_total
        device_s = float(stages.get("device_s", 0.0))
        pad_waste_total = device_s * (pad_rows / padded_total)
        # wall-clock boundaries for the sampled stage spans (the ledger
        # carries the shares; the spans carry the cohort's intervals)
        tw_form1 = tw_form0 + form_s
        tw_h2d1 = tw_form1 + float(stages.get("h2d_s", 0.0))
        tw_dev1 = tw_h2d1 + device_s
        tw_drain1 = tw_dev1 + float(stages.get("drain_s", 0.0))
        quant = getattr(self.servable, "quant", None)
        for it in traced:
            it.ctx.note(batch_id=batch_id, bucket=bucket,
                        fill=round(fill, 4),
                        batch_requests=len(items))
            if quant:
                # the int8 tier's ledgered accuracy delta rides every
                # sampled span — the dashboard's serving table shows it
                # next to the SLO badge (ISSUE 16)
                it.ctx.note(quant_delta=quant["accuracy_delta"])
            it.ctx.stage("batch-form", tw_form0, tw_form1,
                         batch_id=batch_id, fill=round(fill, 4),
                         pad_rows=pad_rows)
            it.ctx.stage("h2d", tw_form1, tw_h2d1, bucket=bucket)
            it.ctx.device(tw_h2d1, tw_dev1,
                          goodput_s=device_s * fill,
                          pad_waste_s=pad_waste_total,
                          batch_id=batch_id)
            it.ctx.stage("drain", tw_dev1, tw_drain1)
            it.ctx.t_pipeline_end = tw_drain1

    def _loop(self):
        while not self._stop.is_set():
            items = (self._admit() if self.batching == "continuous"
                     else self._collect())
            if not items:
                continue
            t_d0 = time.perf_counter()
            # Group by trailing shape + dtype: one malformed request must
            # not poison the other requests coalesced into its cohort.
            groups: dict[tuple, list[_WorkItem]] = {}
            for it in items:
                if it.instances.ndim < 1:
                    it.future.set_exception(ValueError(
                        "instances must have a batch dimension"))
                    continue
                key = (it.instances.shape[1:], str(it.instances.dtype))
                groups.setdefault(key, []).append(it)
            for cohort in groups.values():
                self._dispatch(cohort)
            # drain-rate EWMA (requests/s through the device) feeding
            # the Retry-After shed hint
            rate = len(items) / max(time.perf_counter() - t_d0, 1e-6)
            self._drain_rate = rate if self._drain_rate <= 0.0 \
                else 0.7 * self._drain_rate + 0.3 * rate

    def drain(self, timeout_s: float = 10.0) -> dict:
        """Graceful close: stop accepting, flush the pending cohort
        through the device, then stop the loop. Anything still queued
        past the deadline is failed FAST with an explicit error — a
        queued request must never hang forever past server shutdown —
        and its trace closes with ledger outcome ``drained``. Returns
        ``{"flushed": n, "failed": m}``."""
        with self._submit_lock:
            self._draining = True
            pending_at_close = len(self._waiting)
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            with self._submit_lock:
                if not self._waiting:
                    break
            time.sleep(0.005)
        failed = self.shutdown(
            join_timeout=max(0.5, deadline - time.monotonic()))
        return {"flushed": max(0, pending_at_close - failed),
                "failed": failed}

    def shutdown(self, join_timeout: float = 5.0) -> int:
        """Hard stop: any request still queued is failed fast (never
        left hanging) with its trace — when it carries one — finished
        as outcome ``drained``. Returns how many stragglers were
        failed."""
        with self._submit_lock:
            self._stop.set()
        self._thread.join(timeout=join_timeout)
        failed = 0
        while True:  # fail any stragglers
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            with self._submit_lock:
                self._waiting.pop(id(item), None)
            err = BatcherClosedError(
                "batcher shut down before this request was "
                "dispatched (drained)")
            if item.ctx is not None:
                # first-wins finish: the handler's own error path then
                # no-ops — the ledger records the drain, not a generic
                # error (ISSUE 12 drain contract)
                item.ctx.finish("drained", error=str(err))
            item.future.set_exception(err)
            failed += 1
        return failed
