"""Experiment routing: A/B splits, shadow traffic, and bandit routing.

The reference ships these as Seldon prototypes — abtest (random traffic
split), mab / epsilon-greedy multi-armed bandit, and outlier detection
mixins (kubeflow/seldon/*, SURVEY.md §2.3 "Alt serving stacks"). Here they
are routers in front of Servables: a router picks the backend per request,
records outcomes, and exposes per-arm stats. Used standalone or mounted on
the ModelServer as a virtual model ("router:<name>" predicts via its
chosen arm).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class ArmStats:
    name: str
    requests: int = 0        # routing decisions
    reward_sum: float = 0.0  # accumulated reward signal
    reward_count: int = 0    # reward observations (implicit or feedback)
    failures: int = 0

    @property
    def mean_reward(self) -> float:
        return self.reward_sum / self.reward_count if self.reward_count \
            else 0.0

    def to_dict(self) -> dict:
        return {"name": self.name, "requests": self.requests,
                "meanReward": round(self.mean_reward, 6),
                "rewardCount": self.reward_count,
                "failures": self.failures}


class Router:
    """Base: pick an arm (model name) per request, record outcomes."""

    def __init__(self, arms: list[str], seed: Optional[int] = None):
        if not arms:
            raise ValueError("router needs at least one arm")
        self.arms = list(arms)
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self.stats = {a: ArmStats(a) for a in self.arms}

    def route(self) -> str:
        raise NotImplementedError

    def record_request(self, arm: str, failed: bool = False) -> None:
        """One routing decision served (or failed) by the arm."""
        with self._lock:
            s = self.stats[arm]
            s.requests += 1
            if failed:
                s.failures += 1

    def record_reward(self, arm: str, reward: float) -> None:
        """One reward observation — implicit (serving outcome) or
        explicit feedback. Deliberately does NOT count a request, so a
        :feedback call can't double-count traffic."""
        with self._lock:
            s = self.stats[arm]
            s.reward_sum += reward
            s.reward_count += 1

    def record(self, arm: str, reward: float = 0.0,
               failed: bool = False) -> None:
        """Convenience: one request + its reward in one call."""
        self.record_request(arm, failed=failed)
        self.record_reward(arm, reward)

    def stats_dict(self) -> list[dict]:
        with self._lock:
            return [self.stats[a].to_dict() for a in self.arms]


class ABTestRouter(Router):
    """Random split by traffic weights (the seldon abtest prototype:
    ``traffic`` percentage between two predictors; generalized to N)."""

    def __init__(self, arms: list[str],
                 weights: Optional[list[float]] = None,
                 seed: Optional[int] = None):
        super().__init__(arms, seed)
        if weights is None:
            weights = [1.0] * len(arms)
        if len(weights) != len(arms) or any(w < 0 for w in weights) or \
                sum(weights) <= 0:
            raise ValueError(f"bad weights {weights} for arms {arms}")
        total = sum(weights)
        self.weights = [w / total for w in weights]

    def route(self) -> str:
        r = self.rng.random()
        acc = 0.0
        for arm, w in zip(self.arms, self.weights):
            acc += w
            if r < acc:
                return arm
        return self.arms[-1]


class EpsilonGreedyRouter(Router):
    """Multi-armed bandit (the seldon mab prototype): explore with
    probability epsilon, otherwise exploit the best mean reward."""

    def __init__(self, arms: list[str], epsilon: float = 0.1,
                 seed: Optional[int] = None):
        super().__init__(arms, seed)
        if not 0.0 <= epsilon <= 1.0:
            raise ValueError(f"epsilon must be in [0,1], got {epsilon}")
        self.epsilon = epsilon

    def route(self) -> str:
        with self._lock:
            unexplored = [a for a in self.arms
                          if self.stats[a].requests == 0]
            if unexplored:
                return self.rng.choice(unexplored)
            if self.rng.random() < self.epsilon:
                return self.rng.choice(self.arms)
            return max(self.arms, key=lambda a: self.stats[a].mean_reward)


class ShadowRouter(Router):
    """All traffic to the primary; the shadow arm receives a copy whose
    result is discarded (the canary-validation pattern)."""

    def __init__(self, primary: str, shadow: str,
                 seed: Optional[int] = None):
        super().__init__([primary, shadow], seed)
        self.primary = primary
        self.shadow = shadow

    def route(self) -> str:
        return self.primary


@dataclass
class RoutedModel:
    """A router mounted over a ModelRepository: predict() routes to the
    chosen arm's servable. With ``implicit_reward`` (default) the serving
    outcome is the reward signal (success=1, failure=0); experiments with
    task-level feedback set it False and send rewards via
    ``record_feedback`` (the seldon /send-feedback analog) so availability
    doesn't pollute the quality signal."""

    router: Router
    repository: object  # ModelRepository (duck-typed to avoid the import)
    name: str = "router"
    implicit_reward: bool = True
    # override to route arms through a shared execution path (the model
    # server sets this to its MicroBatcher so routed and direct traffic
    # batch together); default is the servable's raw predict
    predict_resolver: Optional[object] = None
    # request observability (serving/request_trace.py ServingObs):
    # adopted from the ModelServer in add_router(). Shadow copies get
    # their OWN request trace + latency series labeled role=shadow, so
    # a cold shadow JIT compile is attributable and never pollutes the
    # primary's SLO series.
    request_obs: Optional[object] = None
    # shadow copies run here so shadow latency (e.g. a cold JIT compile)
    # never adds to the primary response — seldon mirrored-traffic
    # semantics. Failures and stats are recorded from the worker thread.
    _shadow_pool: object = field(default=None, repr=False)

    def _arm_predict(self, arm: str, ctx=None):
        if self.predict_resolver is not None:
            fn = self.predict_resolver(arm)
        else:
            fn = self.repository.get(arm).predict
        if ctx is None:
            return fn
        # batcher.predict threads the request ctx through; a bare
        # servable/fake predict doesn't take it. Decide by signature
        # up front — a retry-on-TypeError fallback would re-execute
        # the prediction when the predict BODY raises its own
        # TypeError (double device work, double stats).
        import inspect
        try:
            accepts_ctx = "ctx" in inspect.signature(fn).parameters
        except (TypeError, ValueError):
            accepts_ctx = False
        if not accepts_ctx:
            return fn
        return lambda instances: fn(instances, ctx=ctx)

    def _record(self, arm: str, ok: bool) -> None:
        self.router.record_request(arm, failed=not ok)
        if self.implicit_reward:
            self.router.record_reward(arm, 1.0 if ok else 0.0)

    def predict(self, instances: np.ndarray, ctx=None):
        arm = self.router.route()
        if ctx is not None:
            # the span's model is the chosen ARM (per-arm latency
            # series); the router identity rides the attrs
            ctx.model = arm
            ctx.note(router=self.name)
        try:
            result = self._arm_predict(arm, ctx=ctx)(instances)
        except Exception:
            self._record(arm, ok=False)
            raise
        self._record(arm, ok=True)
        if isinstance(self.router, ShadowRouter):
            self._shadow_submit(self.router.shadow, instances,
                                parent_ctx=ctx)
        return result

    def _shadow_submit(self, shadow: str, instances: np.ndarray,
                       parent_ctx=None) -> None:
        if self._shadow_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            object.__setattr__(self, "_shadow_pool",
                               ThreadPoolExecutor(max_workers=1,
                                                  thread_name_prefix="shadow"))
        # the shadow copy's own request trace: derived id (so the
        # primary's timeline links to it), role=shadow throughout
        shadow_ctx = None
        if self.request_obs is not None:
            rid = (parent_ctx.request_id + "-shadow") \
                if parent_ctx is not None else None
            shadow_ctx = self.request_obs.begin(
                shadow, request_id=rid, role="shadow",
                force_sample=bool(parent_ctx is not None
                                  and parent_ctx.sampled))
            shadow_ctx.note(router=self.name, shadow_of=self.router.primary)

        def run():
            try:
                self._arm_predict(shadow, ctx=shadow_ctx)(instances)
                self._record(shadow, ok=True)
                if shadow_ctx is not None:
                    shadow_ctx.finish("ok")
            except Exception as e:  # noqa: BLE001 - shadow must never break serving
                self._record(shadow, ok=False)
                if shadow_ctx is not None:
                    shadow_ctx.finish("error",
                                      error=f"{type(e).__name__}: {e}")

        self._shadow_pool.submit(run)

    def drain_shadow(self, timeout: float = 10.0) -> None:
        """Wait for in-flight shadow copies (tests / shutdown)."""
        if self._shadow_pool is not None:
            from concurrent.futures import ThreadPoolExecutor
            pool: ThreadPoolExecutor = self._shadow_pool
            pool.shutdown(wait=True)
            object.__setattr__(self, "_shadow_pool", None)

    def record_feedback(self, arm: str, reward: float) -> None:
        self.router.record_reward(arm, reward)

    def status(self) -> dict:
        return {"name": self.name,
                "routerType": type(self.router).__name__,
                "arms": self.router.stats_dict()}
