"""The gRPC predict surface: TF-Serving's PredictionService on :9000.

The reference deploys TF-Serving with gRPC :9000 + REST :8000
(tf-serving.libsonnet:137,197) and its http-proxy speaks this exact
service (components/k8s-model-server/http-proxy/server.py:27-40). The TPU
model server serves the same wire contract — PredictRequest/PredictResponse
and GetModelStatus with upstream field numbers (serving/tpu_serving_pb2.py,
source proto in native/proto/tpu_serving.proto) — so stock TF-Serving
clients work unmodified.

Implementation notes: grpcio generic handlers (no generated service stubs
needed — protoc's message codegen plus method registration by full name),
sharing the ModelServer's MicroBatchers so gRPC and REST traffic batch
together on the device.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

try:
    # one guard for the whole optional surface: grpcio AND the protobuf
    # runtime behind the generated pb2 (neither is a hard dependency; the
    # REST server must keep starting without them)
    import grpc
    from . import tpu_serving_pb2 as pb
    HAVE_GRPC = True
except ImportError:  # pragma: no cover - both are in the base image
    grpc = None
    pb = None
    HAVE_GRPC = False

SERVICE = "tensorflow.serving.PredictionService"

if HAVE_GRPC:
    _NP_TO_DT = {
        np.dtype(np.float32): pb.DT_FLOAT,
        np.dtype(np.float64): pb.DT_DOUBLE,
        np.dtype(np.int32): pb.DT_INT32,
        np.dtype(np.uint8): pb.DT_UINT8,
        np.dtype(np.int16): pb.DT_INT16,
        np.dtype(np.int8): pb.DT_INT8,
        np.dtype(np.int64): pb.DT_INT64,
        np.dtype(np.bool_): pb.DT_BOOL,
        np.dtype(np.uint32): pb.DT_UINT32,
        np.dtype(np.uint64): pb.DT_UINT64,
        np.dtype(np.float16): pb.DT_HALF,
    }
    _DT_TO_NP = {v: k for k, v in _NP_TO_DT.items()}

    # repeated-field name per dtype for sparse (non-tensor_content)
    # encoding; DT_HALF is special-cased in tensor_to_ndarray (half_val
    # carries raw float16 bit patterns in int32 slots, TF convention)
    _DT_VAL_FIELD = {
        pb.DT_FLOAT: "float_val", pb.DT_DOUBLE: "double_val",
        pb.DT_INT32: "int_val", pb.DT_UINT8: "int_val",
        pb.DT_INT16: "int_val", pb.DT_INT8: "int_val",
        pb.DT_INT64: "int64_val", pb.DT_BOOL: "bool_val",
        pb.DT_UINT32: "uint32_val", pb.DT_UINT64: "uint64_val",
    }
else:  # pragma: no cover
    _NP_TO_DT = {}
    _DT_TO_NP = {}
    _DT_VAL_FIELD = {}


def tensor_to_ndarray(t: pb.TensorProto) -> np.ndarray:
    """TensorProto → numpy, accepting both tensor_content and *_val forms
    (clients use either; tf.make_tensor_proto prefers tensor_content)."""
    if t.dtype not in _DT_TO_NP:
        raise ValueError(f"unsupported tensor dtype {t.dtype}")
    np_dtype = _DT_TO_NP[t.dtype]
    shape = [d.size for d in t.tensor_shape.dim]
    if t.tensor_content:
        arr = np.frombuffer(t.tensor_content, dtype=np_dtype)
    elif t.dtype == pb.DT_HALF:
        # half_val carries raw float16 bit patterns in int32 slots
        arr = np.array(list(t.half_val), dtype=np.uint16).view(np.float16)
    else:
        field = _DT_VAL_FIELD[t.dtype]
        arr = np.array(list(getattr(t, field)), dtype=np_dtype)
        # TF semantics: a single value broadcasts to the full shape
        n = int(np.prod(shape)) if shape else arr.size
        if arr.size == 1 and n > 1:
            arr = np.full(n, arr[0], dtype=np_dtype)
    return arr.reshape(shape) if shape else arr


def ndarray_to_tensor(a: np.ndarray) -> pb.TensorProto:
    a = np.asarray(a)
    if a.dtype not in _NP_TO_DT:
        a = a.astype(np.float32)  # e.g. bfloat16 outputs
    t = pb.TensorProto()
    t.dtype = _NP_TO_DT[a.dtype]
    for s in a.shape:
        t.tensor_shape.dim.add().size = s
    t.tensor_content = np.ascontiguousarray(a).tobytes()
    return t


class GrpcPredictServer:
    """PredictionService over a ModelServer (shares its MicroBatchers)."""

    def __init__(self, model_server, host: str = "0.0.0.0",
                 port: int = 9000, max_workers: int = 8,
                 drain_grace_s: float = 10.0):
        if not HAVE_GRPC:
            raise RuntimeError("grpcio is not available")
        # graceful-shutdown budget: stop() lets in-flight RPCs run this
        # long before hard-cancelling (the REST server's drain analog)
        self.drain_grace_s = drain_grace_s
        # serving cold-start: the first Predict per batch bucket pays an
        # XLA compile unless the persistent cache is live — a gRPC-only
        # deployment (no REST main()) must wire it too, BEFORE the first
        # request can jit (runtime/compile_cache.py; no-op when no
        # KFTPU_COMPILE_CACHE_DIR, idempotent beside http_server's call)
        from ..runtime.compile_cache import enable_compilation_cache
        enable_compilation_cache()
        self.model_server = model_server
        self.host, self.port = host, port
        self.max_workers = max_workers
        self._server: Optional["grpc.Server"] = None

    # -- handlers -----------------------------------------------------------

    def _predict(self, request: pb.PredictRequest,
                 context) -> pb.PredictResponse:
        from .batcher import QueueFullError
        from .request_trace import REQUEST_ID_HEADER, mint_request_id
        name = request.model_spec.name
        # request id over gRPC metadata (the x-request-id header's
        # wire-equivalent), echoed as initial metadata — one id stamps
        # every stage span, REST and gRPC alike
        rid = ""
        for k, v in (context.invocation_metadata() or ()):
            if k == REQUEST_ID_HEADER:
                rid = v
                break
        rid = rid or mint_request_id()
        try:
            context.send_initial_metadata(((REQUEST_ID_HEADER, rid),))
        except Exception:  # noqa: BLE001 — metadata is best-effort
            pass
        if self.model_server.replica.draining:
            # draining: refuse new RPCs with retryable UNAVAILABLE (the
            # REST 503 analog) — in-flight ones keep running under the
            # stop(grace) budget
            context.abort(grpc.StatusCode.UNAVAILABLE, "draining")
        try:
            batcher = self.model_server.batcher(name)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        if not request.inputs:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "no inputs in PredictRequest")
        # single-input models take the tensor directly; the conventional
        # key is "instances" (REST parity) or "inputs"
        key = ("instances" if "instances" in request.inputs else
               ("inputs" if "inputs" in request.inputs else
                next(iter(request.inputs))))
        ctx = self.model_server.obs.begin(name, request_id=rid)
        self.model_server.replica.inflight_inc(name)
        try:
            instances = tensor_to_ndarray(request.inputs[key])
            out = batcher.predict(instances, ctx=ctx)
        except QueueFullError as e:
            # bounded-queue shed: explicit RESOURCE_EXHAUSTED, the
            # request's wait recorded as queue badput in its ledger
            ctx.finish("shed", error=str(e))
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except ValueError as e:
            ctx.finish("error", error=f"ValueError: {e}")
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except Exception as e:  # noqa: BLE001 — surface as INTERNAL
            ctx.finish("error", error=f"{type(e).__name__}: {e}")
            # an exception may carry its own HTTP status (the chaos
            # 5xx-burst fault): 503 maps to retryable UNAVAILABLE
            code = grpc.StatusCode.UNAVAILABLE \
                if int(getattr(e, "http_status", 0)) == 503 \
                else grpc.StatusCode.INTERNAL
            context.abort(code, f"{type(e).__name__}: {e}")
        finally:
            self.model_server.replica.inflight_dec(name)
        import time as _time
        t_resp = _time.time()
        if ctx.t_pipeline_end is not None:
            t_resp = min(t_resp, max(ctx.t_pipeline_end, ctx.t_accept))
        # response construction can fail too (an output dtype the
        # tensor codec rejects) — that request must still land in the
        # ledger and registry, never silently vanish
        try:
            resp = pb.PredictResponse()
            resp.model_spec.name = name
            resp.model_spec.signature_name = (
                request.model_spec.signature_name or "serving_default")
            if isinstance(out, dict):
                wanted = set(request.output_filter)
                for k, v in out.items():
                    if wanted and k not in wanted:
                        continue
                    resp.outputs[k].CopyFrom(
                        ndarray_to_tensor(np.asarray(v)))
            else:
                resp.outputs["outputs"].CopyFrom(
                    ndarray_to_tensor(np.asarray(out)))
        except Exception as e:  # noqa: BLE001 — surface as INTERNAL
            ctx.finish("error", error=f"{type(e).__name__}: {e}")
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")
        ctx.stage("respond", t_resp, _time.time())
        ctx.finish("ok")
        return resp

    def _get_model_status(self, request: pb.GetModelStatusRequest,
                          context) -> pb.GetModelStatusResponse:
        name = request.model_spec.name
        resp = pb.GetModelStatusResponse()
        try:
            servable = self.model_server.repository.get(name)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        vs = resp.model_version_status.add()
        vs.version = int(servable.version)
        vs.state = pb.ModelVersionStatus.AVAILABLE
        vs.status.error_code = 0
        return resp

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        handlers = grpc.method_handlers_generic_handler(SERVICE, {
            "Predict": grpc.unary_unary_rpc_method_handler(
                self._predict,
                request_deserializer=pb.PredictRequest.FromString,
                response_serializer=pb.PredictResponse.SerializeToString),
            "GetModelStatus": grpc.unary_unary_rpc_method_handler(
                self._get_model_status,
                request_deserializer=pb.GetModelStatusRequest.FromString,
                response_serializer=(
                    pb.GetModelStatusResponse.SerializeToString)),
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_workers,
                                       thread_name_prefix="grpc-predict"))
        self._server.add_generic_rpc_handlers((handlers,))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        self._server.start()
        log.info("gRPC PredictionService on :%d", self.port)
        return self.port

    def stop(self, grace: Optional[float] = None) -> None:
        """Graceful shutdown: new RPCs are rejected immediately while
        in-flight ones get ``grace`` seconds (default: the server's
        ``drain_grace_s``) to COMPLETE before being cancelled — a
        deploy rollout must not drop the RPCs it already accepted.
        Blocks until the server has fully terminated."""
        if self._server is not None:
            grace = self.drain_grace_s if grace is None else grace
            self._server.stop(grace).wait()


def predict_stub(channel):
    """Client-side multicallables for tests/tools (stub without codegen)."""
    return {
        "Predict": channel.unary_unary(
            f"/{SERVICE}/Predict",
            request_serializer=pb.PredictRequest.SerializeToString,
            response_deserializer=pb.PredictResponse.FromString),
        "GetModelStatus": channel.unary_unary(
            f"/{SERVICE}/GetModelStatus",
            request_serializer=pb.GetModelStatusRequest.SerializeToString,
            response_deserializer=pb.GetModelStatusResponse.FromString),
    }
