"""The coordinator: fans kfctl verbs to the platform driver + manifest engine.

Reference: bootstrap/pkg/kfapp/coordinator/coordinator.go — NewKfApp (:192,
flags→KfDef), LoadKfApp (:337, re-read app.yaml), Apply/Generate/Init
(:407,524,580 fan out to platform + package managers). The package manager
here is the programmatic manifest registry (manifests/) instead of ksonnet.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Optional

from ..api import k8s
from ..api.kfdef import (KfDef, KfDefSpec, RESOURCE_ALL, RESOURCE_K8S,
                         RESOURCE_PLATFORM)
from ..cluster import FakeCluster, KubeClient
from ..cluster.apply import apply_manifests, delete_manifests
from ..manifests import build_component, component_names
from ..utils import yamlio
from .platforms import get_platform

log = logging.getLogger(__name__)

MANIFESTS_DIR = "manifests"
CLUSTER_STATE_FILE = "cluster-state.json"


def load_cluster_state(app_dir: str) -> FakeCluster:
    """The app's persisted simulated cluster (or a fresh one) — the single
    place that knows the snapshot-file convention."""
    path = os.path.join(app_dir, CLUSTER_STATE_FILE)
    if os.path.exists(path):
        with open(path) as f:
            return FakeCluster.from_snapshot(json.load(f))
    return FakeCluster()


class Coordinator:
    """One deployment app (app_dir with app.yaml + generated manifests)."""

    def __init__(self, kfdef: KfDef, client: Optional[KubeClient] = None):
        self.kfdef = kfdef
        self.platform = get_platform(kfdef.spec.platform)
        self._client = client

    # -- construction (NewKfApp / LoadKfApp analogs) ------------------------

    @classmethod
    def new(cls, app_dir: str, **spec_kwargs) -> "Coordinator":
        name = os.path.basename(os.path.abspath(app_dir))
        kfdef = KfDef(name=name,
                      spec=KfDefSpec(app_dir=os.path.abspath(app_dir),
                                     **spec_kwargs))
        kfdef.validate()
        return cls(kfdef)

    @classmethod
    def load(cls, app_dir: str) -> "Coordinator":
        return cls(KfDef.load(os.path.abspath(app_dir)))

    # -- the simulated-cluster client (persisted across CLI invocations) ----

    @property
    def client(self) -> KubeClient:
        if self._client is None:
            kubeconfig = (self.kfdef.spec.kubeconfig
                          or os.environ.get("KFTPU_KUBECONFIG", ""))
            if kubeconfig:
                from ..cluster.http_client import HttpKubeClient
                self._client = HttpKubeClient.from_kubeconfig(kubeconfig)
            else:
                self._client = load_cluster_state(self.kfdef.spec.app_dir)
        return self._client

    def _persist_client(self) -> None:
        if isinstance(self._client, FakeCluster):
            path = os.path.join(self.kfdef.spec.app_dir, CLUSTER_STATE_FILE)
            with open(path, "w") as f:
                json.dump(self._client.to_snapshot(), f)

    # -- verbs --------------------------------------------------------------

    def init(self, resources: str = RESOURCE_ALL) -> None:
        os.makedirs(self.kfdef.spec.app_dir, exist_ok=True)
        if resources in (RESOURCE_ALL, RESOURCE_PLATFORM):
            self.platform.init(self.kfdef)
        self.kfdef.set_condition("Initialized", "True", reason="InitDone")
        self.kfdef.save()
        log.info("initialized app at %s (platform=%s, %d components)",
                 self.kfdef.spec.app_dir, self.kfdef.spec.platform,
                 len(self.kfdef.spec.components))

    def effective_components(self) -> tuple[list[str], dict]:
        """Components + params with the spec's flavor overlay merged (the
        kustomize-v2 MergeKustomization analog, manifests/overlays.py).
        With spec.configDir set, the on-disk layout's base supplies the
        component list and its overlays the flavors (the repo walk,
        kustomize.go:524-560)."""
        if self.kfdef.spec.config_dir:
            from ..manifests.overlays import resolve_config_dir
            return resolve_config_dir(self.kfdef.spec.config_dir,
                                      self.kfdef.spec.components,
                                      self.kfdef.spec.component_params,
                                      self.kfdef.spec.flavor)
        from ..manifests.overlays import resolve
        return resolve(self.kfdef.spec.components,
                       self.kfdef.spec.component_params,
                       self.kfdef.spec.flavor)

    def generate(self, resources: str = RESOURCE_ALL) -> list[str]:
        """Render every component's manifests to manifests/<name>.yaml
        (the ksonnet.Generate / componentAdd analog, ksonnet.go:316)."""
        written = []
        if resources in (RESOURCE_ALL, RESOURCE_PLATFORM):
            self.platform.generate(self.kfdef)
        if resources in (RESOURCE_ALL, RESOURCE_K8S):
            out_dir = os.path.join(self.kfdef.spec.app_dir, MANIFESTS_DIR)
            os.makedirs(out_dir, exist_ok=True)
            components, params = self.effective_components()
            for stale in os.listdir(out_dir):
                # flavor switches drop components: clear stale renders so
                # apply never picks up the previous flavor's manifests
                if stale.endswith(".yaml") and \
                        stale[:-5] not in components:
                    os.unlink(os.path.join(out_dir, stale))
            for comp in components:
                objs = build_component(comp, params.get(comp, {}))
                path = os.path.join(out_dir, f"{comp}.yaml")
                with open(path, "w") as f:
                    f.write(yamlio.dump_all(objs))
                written.append(path)
        self.kfdef.set_condition("Generated", "True", reason="GenerateDone")
        self.kfdef.save()
        return written

    def _load_generated(self) -> list[dict]:
        out_dir = os.path.join(self.kfdef.spec.app_dir, MANIFESTS_DIR)
        if not os.path.isdir(out_dir):
            raise FileNotFoundError(
                f"{out_dir} not found — run `kfctl generate` first")
        objs: list[dict] = []
        components, _ = self.effective_components()
        for comp in components:
            path = os.path.join(out_dir, f"{comp}.yaml")
            if os.path.exists(path):
                with open(path) as f:
                    objs.extend(yamlio.load_all(f.read()))
        return objs

    def apply(self, resources: str = RESOURCE_ALL,
              sleep=None) -> "ApplyOutcome":
        if resources in (RESOURCE_ALL, RESOURCE_PLATFORM):
            self.platform.apply(self.kfdef)
        outcome = ApplyOutcome()
        if resources in (RESOURCE_ALL, RESOURCE_K8S):
            ns = k8s.make("v1", "Namespace", self.kfdef.spec.namespace)
            objs = [ns, *self._load_generated()]
            result = apply_manifests(self.client, objs,
                                     namespace=self.kfdef.spec.namespace,
                                     sleep=sleep)
            outcome.applied = len(result.applied)
            outcome.failed = list(result.failed)
            self._persist_client()
        status = "True" if not outcome.failed else "False"
        self.kfdef.set_condition("Available", status, reason="ApplyDone",
                                 message=f"{outcome.applied} objects applied")
        self.kfdef.save()
        return outcome

    def delete(self, resources: str = RESOURCE_ALL) -> None:
        if resources in (RESOURCE_ALL, RESOURCE_K8S):
            try:
                delete_manifests(self.client, self._load_generated())
            except FileNotFoundError:
                pass
            self.client.delete_many(
                [k8s.make("v1", "Namespace", self.kfdef.spec.namespace)])
            self._persist_client()
        if resources in (RESOURCE_ALL, RESOURCE_PLATFORM):
            self.platform.delete(self.kfdef)
        self.kfdef.set_condition("Available", "False", reason="Deleted")
        self.kfdef.save()

    def show(self) -> dict:
        comps = {}
        components, _ = self.effective_components()  # flavor-aware
        for comp in components:
            path = os.path.join(self.kfdef.spec.app_dir, MANIFESTS_DIR,
                                f"{comp}.yaml")
            n = 0
            if os.path.exists(path):
                with open(path) as f:
                    n = len(yamlio.load_all(f.read()))
            comps[comp] = n
        out = {"name": self.kfdef.name,
               "platform": self.kfdef.spec.platform,
               "namespace": self.kfdef.spec.namespace,
               "components": comps,
               "conditions": [c.type + "=" + c.status
                              for c in self.kfdef.conditions]}
        if self.kfdef.spec.flavor:
            out["flavor"] = self.kfdef.spec.flavor
        return out


class ApplyOutcome:
    def __init__(self):
        self.applied = 0
        self.failed: list = []


# ---------------------------------------------------------------- CLI verbs


def register_verbs(sub: argparse._SubParsersAction) -> None:
    p_init = sub.add_parser("init", help="create a deployment app directory")
    p_init.add_argument("app_dir")
    p_init.add_argument("--platform", default="existing")
    p_init.add_argument("--project", default="")
    p_init.add_argument("--zone", default="")
    p_init.add_argument("--namespace", default="kubeflow")
    p_init.add_argument("--use-basic-auth", action="store_true")
    p_init.add_argument("--tpu-topology", default="v5e-8")
    p_init.add_argument("--components", default="",
                        help="comma-separated override of the component list")
    p_init.add_argument("--flavor", default="",
                        help="named config overlay (local | iap | "
                             "basic_auth, or an overlay from "
                             "--config-dir) merged at generate time")
    p_init.add_argument("--config-dir", default="",
                        help="on-disk config layout (base/ + overlays/"
                             "<name>/config.yaml); base supplies the "
                             "component list, overlays become flavors")
    p_init.add_argument("--kubeconfig", default="",
                        help="target a real apiserver instead of the "
                             "persisted simulated cluster")
    p_init.set_defaults(func=_cmd_init)

    for verb, fn in [("generate", _cmd_generate), ("apply", _cmd_apply),
                     ("delete", _cmd_delete)]:
        p = sub.add_parser(verb, help=f"{verb} platform/k8s resources")
        p.add_argument("resources", nargs="?", default="all",
                       choices=["all", "k8s", "platform"])
        p.add_argument("--app-dir", default=".")
        if verb == "generate":
            p.add_argument("--flavor", default=None,
                           help="set the app's config flavor (persisted "
                                "to app.yaml so apply matches the render)")
        p.set_defaults(func=fn)

    p_show = sub.add_parser("show", help="show app state")
    p_show.add_argument("--app-dir", default=".")
    p_show.set_defaults(func=_cmd_show)

    p_comp = sub.add_parser("components", help="list installable components")
    p_comp.set_defaults(func=_cmd_components)

    p_compl = sub.add_parser("completion",
                             help="print bash completion script")
    p_compl.set_defaults(func=_cmd_completion)

    p_boot = sub.add_parser(
        "serve-bootstrap",
        help="run the deploy-as-a-service REST server (ksServer analog)")
    p_boot.add_argument("--apps-root", default="./apps")
    p_boot.add_argument("--host", default="127.0.0.1")
    p_boot.add_argument("--port", type=int, default=8085)
    p_boot.set_defaults(func=_cmd_serve_bootstrap)

    p_api = sub.add_parser(
        "serve-apiserver",
        help="serve the app's simulated cluster over the kube REST wire "
             "format (mock apiserver for the manager / web apps)")
    p_api.add_argument("--app-dir", default=".")
    p_api.add_argument("--host", default="127.0.0.1")
    p_api.add_argument("--port", type=int, default=8443)
    p_api.add_argument("--write-kubeconfig", default="",
                       help="also write a kubeconfig pointing at this server")
    p_api.set_defaults(func=_cmd_serve_apiserver)


def _cmd_init(args) -> int:
    kwargs = dict(platform=args.platform, project=args.project,
                  zone=args.zone, namespace=args.namespace,
                  use_basic_auth=args.use_basic_auth,
                  default_tpu_topology=args.tpu_topology,
                  flavor=args.flavor)
    if args.components:
        kwargs["components"] = [c.strip() for c in args.components.split(",")]
    elif args.config_dir:
        # the on-disk base supplies the list; don't double it with the
        # built-in defaults
        kwargs["components"] = []
    if args.config_dir:
        kwargs["config_dir"] = os.path.abspath(args.config_dir)
    if args.kubeconfig:
        kwargs["kubeconfig"] = os.path.abspath(args.kubeconfig)
    coord = Coordinator.new(args.app_dir, **kwargs)
    coord.init()
    print(f"app initialized at {coord.kfdef.spec.app_dir}")
    return 0


def _cmd_generate(args) -> int:
    coord = Coordinator.load(args.app_dir)
    if getattr(args, "flavor", None) is not None:
        coord.kfdef.spec.flavor = args.flavor
    written = coord.generate(args.resources)
    print(f"generated {len(written)} component manifests"
          + (f" (flavor={coord.kfdef.spec.flavor})"
             if coord.kfdef.spec.flavor else ""))
    return 0


def _cmd_apply(args) -> int:
    coord = Coordinator.load(args.app_dir)
    outcome = coord.apply(args.resources)
    print(f"applied {outcome.applied} objects"
          + (f", {len(outcome.failed)} FAILED" if outcome.failed else ""))
    return 1 if outcome.failed else 0


def _cmd_delete(args) -> int:
    coord = Coordinator.load(args.app_dir)
    coord.delete(args.resources)
    print("deleted")
    return 0


def _cmd_show(args) -> int:
    coord = Coordinator.load(args.app_dir)
    print(json.dumps(coord.show(), indent=2))
    return 0


def _cmd_completion(args) -> int:
    # the cobra-generated completion of the reference, reduced to verbs
    print("""\
_kfctl_complete() {
  local verbs="init generate apply delete show components version \\
completion serve-bootstrap serve-apiserver"
  COMPREPLY=($(compgen -W "$verbs" -- "${COMP_WORDS[COMP_CWORD]}"))
}
complete -F _kfctl_complete kfctl""")
    return 0


def _cmd_serve_bootstrap(args) -> int:
    import time as _time

    from .bootstrap_server import BootstrapServer
    server = BootstrapServer(args.apps_root, host=args.host, port=args.port)
    port = server.start()
    print(f"bootstrap service listening on {args.host}:{port} "
          f"(apps under {args.apps_root})")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def _cmd_serve_apiserver(args) -> int:
    import signal
    import threading

    from ..cluster.apiserver import ClusterAPIServer

    # always serve the app's LOCAL simulated cluster — never proxy a
    # kubeconfig-selected client (serving a real apiserver through this
    # shim would be a loop, and HttpKubeClient can't back unfiltered
    # watches)
    app_dir = os.path.abspath(args.app_dir)
    state_path = os.path.join(app_dir, CLUSTER_STATE_FILE)
    cluster = load_cluster_state(app_dir)
    server = ClusterAPIServer(cluster, host=args.host, port=args.port)
    port = server.start()
    print(f"apiserver (simulated cluster) listening on {args.host}:{port}")
    if args.write_kubeconfig:
        write_local_kubeconfig(args.write_kubeconfig,
                               f"http://{args.host}:{port}")
        print(f"kubeconfig written to {args.write_kubeconfig}")

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        # persist on ANY exit path (SIGTERM/SIGINT/crash), not just Ctrl-C
        server.stop()
        with open(state_path, "w") as f:
            json.dump(cluster.to_snapshot(), f)
    return 0


def write_local_kubeconfig(path: str, server_url: str) -> None:
    """A minimal kubeconfig pointing at a local simulated apiserver."""
    import yaml
    cfg = {
        "apiVersion": "v1", "kind": "Config",
        "clusters": [{"name": "kubeflow-tpu-sim",
                      "cluster": {"server": server_url}}],
        "users": [{"name": "default", "user": {}}],
        "contexts": [{"name": "kubeflow-tpu-sim",
                      "context": {"cluster": "kubeflow-tpu-sim",
                                  "user": "default"}}],
        "current-context": "kubeflow-tpu-sim",
    }
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)


def _cmd_components(args) -> int:
    from ..manifests import REGISTRY
    for name in component_names():
        print(f"{name:24s} {REGISTRY[name].description}")
    return 0
