"""Deployment-Manager simulator: the executor for zero-egress dev/test.

Models the surface GcpPlatform.apply drives (platforms.py): deployment
insert/update returning async operations that progress RUNNING → DONE
across polls, operation errors, project IAM policy read-modify-write, and
service-account key minting. The same seam a production executor fills
with googleapis clients — so the full gcp.go sequence (updateDM →
blockingWait → IAM → secrets) is exercisable without a cloud.
"""

from __future__ import annotations

import base64
import itertools
import json
from typing import Optional


class GcpSimulator:
    """call(method, request) -> response, with injectable op behavior."""

    def __init__(self, polls_until_done: int = 2,
                 fail_op: Optional[str] = None):
        self.polls_until_done = polls_until_done
        self.fail_op = fail_op            # op name to fail, if any
        self.deployments: dict[str, dict] = {}
        self.iam_policy: dict = {"etag": "etag-0", "bindings": []}
        self.calls: list[tuple[str, dict]] = []
        self._ops: dict[str, dict] = {}
        self._seq = itertools.count(1)

    # -- executor entrypoint -------------------------------------------------

    def __call__(self, method: str, request: dict) -> dict:
        self.calls.append((method, dict(request)))
        handler = getattr(self, "_" + method.replace(".", "_"), None)
        if handler is None:
            raise ValueError(f"GcpSimulator: unknown method {method!r}")
        return handler(request)

    # -- deployments ---------------------------------------------------------

    def _new_op(self, kind: str, target: str) -> dict:
        name = f"op-{next(self._seq)}"
        op = {"name": name, "operationType": kind, "targetLink": target,
              "status": "RUNNING", "_polls": 0}
        self._ops[name] = op
        return {k: v for k, v in op.items() if not k.startswith("_")}

    def _deployments_get(self, req: dict) -> Optional[dict]:
        # seam contract: None = not found (platforms.py _update_dm)
        return self.deployments.get(req["deployment"])

    def _deployments_insert(self, req: dict) -> dict:
        self.deployments[req["deployment"]] = {
            "name": req["deployment"], "fingerprint": "fp-1",
            "config": req.get("config", "")}
        return self._new_op("insert", req["deployment"])

    def _deployments_update(self, req: dict) -> dict:
        if req["deployment"] not in self.deployments:
            raise KeyError(req["deployment"])
        if req.get("fingerprint") != \
                self.deployments[req["deployment"]]["fingerprint"]:
            raise ValueError("fingerprint mismatch (concurrent update)")
        dep = self.deployments[req["deployment"]]
        dep["fingerprint"] = f"fp-{next(self._seq)}"
        dep["config"] = req.get("config", dep["config"])
        return self._new_op("update", req["deployment"])

    def _deployments_delete(self, req: dict) -> dict:
        self.deployments.pop(req["deployment"], None)
        return self._new_op("delete", req["deployment"])

    def _operations_get(self, req: dict) -> dict:
        op = self._ops[req["operation"]]
        op["_polls"] += 1
        if op["_polls"] >= self.polls_until_done:
            op["status"] = "DONE"
            if op["name"] == self.fail_op:
                op["error"] = {"errors": [
                    {"code": "RESOURCE_ERROR", "message": "quota exceeded"}]}
        return {k: v for k, v in op.items() if not k.startswith("_")}

    # -- IAM / SA keys -------------------------------------------------------

    def _projects_getIamPolicy(self, req: dict) -> dict:
        return json.loads(json.dumps(self.iam_policy))

    def _projects_setIamPolicy(self, req: dict) -> dict:
        policy = req["policy"]
        if policy.get("etag") != self.iam_policy["etag"]:
            raise ValueError("etag mismatch (concurrent policy write)")
        self.iam_policy = {
            "etag": f"etag-{next(self._seq)}",
            "bindings": policy.get("bindings", [])}
        return self.iam_policy

    def _serviceAccounts_keys_create(self, req: dict) -> dict:
        payload = json.dumps({"type": "service_account",
                              "client_email": req["name"]}).encode()
        return {"name": f"{req['name']}/keys/k-{next(self._seq)}",
                "privateKeyData": base64.b64encode(payload).decode()}
