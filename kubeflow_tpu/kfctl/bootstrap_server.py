"""Bootstrap REST service: deploy-as-a-service over the coordinator.

The reference's bootstrap server (bootstrap/cmd/bootstrap/app/
ksServer.go:156 NewServer; routes :1462-1470 — /kfctl/apps/create,
/kfctl/apps/apply, /kfctl/e2eDeploy — plus a Prometheus /metrics) backs
the click-to-deploy UI and the in-cluster bootstrapper. Same surface here
as a thin HTTP layer over Coordinator, with deploy counters in Prometheus
text form and per-app serialization (concurrent deploys of the SAME app
are rejected 409 the way the reference's per-app mutex serializes them).

Routes:
  POST /kfctl/apps/create   {name, platform?, components?, params?}
  POST /kfctl/apps/apply    {name}
  POST /kfctl/e2eDeploy     {name, ...}        (create + generate + apply)
  POST /kfctl/apps/delete   {name}
  POST /kfctl/iam/apply     {project, cluster, email?, action?}
  POST /kfctl/initProject   {project, projectNumber}
  GET  /kfctl/apps                              (list + conditions)
  GET  /kfctl/apps/{name}                       (show)
  GET  /metrics
  GET  /healthz

The IAM routes (ksServer.go:1465-1466) run over the same GCP executor
seam GcpPlatform uses; without one configured they 503 (zero-egress dev
default) rather than pretending to have edited a cloud policy.
"""

from __future__ import annotations

import logging
import os
import threading

from ..webapps._http import ApiError, JsonApp, JsonServer, RawResponse
from .coordinator import Coordinator

log = logging.getLogger(__name__)


class _Counters:
    def __init__(self):
        self._lock = threading.Lock()
        self.deploys = 0
        self.failures = 0

    def inc(self, failed: bool) -> None:
        with self._lock:
            self.deploys += 1
            if failed:
                self.failures += 1

    def text(self) -> str:
        with self._lock:
            return ("# TYPE kubeflow_bootstrap_deploys_total counter\n"
                    f"kubeflow_bootstrap_deploys_total {self.deploys}\n"
                    "# TYPE kubeflow_bootstrap_deploy_failures_total counter\n"
                    f"kubeflow_bootstrap_deploy_failures_total "
                    f"{self.failures}\n")


class BootstrapService:
    """App registry rooted at ``apps_root``; one directory per app."""

    def __init__(self, apps_root: str, gcp_executor=None):
        self.apps_root = os.path.abspath(apps_root)
        os.makedirs(self.apps_root, exist_ok=True)
        self.counters = _Counters()
        self.gcp_executor = gcp_executor
        self._busy: set[str] = set()
        self._lock = threading.Lock()

    def _app_dir(self, name: str) -> str:
        if not name or "/" in name or name.startswith("."):
            raise ApiError(400, f"invalid app name {name!r}")
        return os.path.join(self.apps_root, name)

    def _acquire(self, name: str) -> None:
        with self._lock:
            if name in self._busy:
                raise ApiError(409, f"app {name} has an operation in "
                                    f"progress")
            self._busy.add(name)

    def _release(self, name: str) -> None:
        with self._lock:
            self._busy.discard(name)

    # -- operations ---------------------------------------------------------

    def create(self, body: dict) -> dict:
        name = body.get("name", "")
        app_dir = self._app_dir(name)
        spec_kwargs = {}
        for key in ("platform", "components", "namespace", "project",
                    "zone", "flavor"):
            if body.get(key) is not None:
                spec_kwargs[key] = body[key]
        if spec_kwargs.get("flavor"):
            from ..manifests.overlays import FLAVORS
            if spec_kwargs["flavor"] not in FLAVORS:
                raise ApiError(400, f"unknown flavor "
                                    f"{spec_kwargs['flavor']!r}; known: "
                                    f"{sorted(FLAVORS)}")
        if body.get("params"):
            spec_kwargs["component_params"] = body["params"]
        # unknown components are a 400 before anything touches disk
        from ..manifests.registry import REGISTRY
        for comp in spec_kwargs.get("components") or []:
            if comp not in REGISTRY:
                raise ApiError(400, f"unknown component {comp!r}; see "
                                    f"GET /kfctl/components")
        self._acquire(name)
        try:
            # existence check under the busy lock: checked before it, two
            # racing creates could both pass and the loser would silently
            # re-initialize (and reset) the winner's app
            if os.path.exists(os.path.join(app_dir, "app.yaml")):
                raise ApiError(409, f"app {name} already exists")
            try:
                coord = Coordinator.new(app_dir, **spec_kwargs)
                coord.init()
                coord.generate()
            except ApiError:
                raise
            except Exception:
                # transactional create: a half-initialized app dir would
                # wedge the name at 409 forever and make a retried
                # e2eDeploy "succeed" while deploying nothing
                import shutil
                shutil.rmtree(app_dir, ignore_errors=True)
                raise
        finally:
            self._release(name)
        return coord.show()

    def apply(self, name: str) -> dict:
        app_dir = self._app_dir(name)
        self._acquire(name)
        try:
            # existence check + load under the lock: a racing delete must
            # yield a clean 404, not a raw FileNotFoundError 500
            if not os.path.exists(os.path.join(app_dir, "app.yaml")):
                raise ApiError(404, f"app {name} not found")
            coord = Coordinator.load(app_dir)
            try:
                outcome = coord.apply()
            except Exception:
                # hard failures must still count — the failure counter
                # exists precisely for the prober watching /metrics
                self.counters.inc(failed=True)
                raise
            self.counters.inc(failed=bool(outcome.failed))
            return {"applied": outcome.applied,
                    "failed": outcome.failed, **coord.show()}
        finally:
            self._release(name)

    def e2e_deploy(self, body: dict) -> dict:
        """create + generate + apply in one call (the /kfctl/e2eDeploy
        path click-to-deploy uses, ksServer.go deployHandler). Idempotent
        on the create half so a failed deploy can be retried; create-phase
        failures count as failed deploys in /metrics."""
        name = body.get("name", "")
        if not os.path.exists(os.path.join(self._app_dir(name), "app.yaml")):
            try:
                self.create(body)
            except ApiError as e:
                if e.status != 409:
                    self.counters.inc(failed=True)
                    raise
                # a racing e2eDeploy created it first — idempotent: fall
                # through to apply
            except Exception:
                self.counters.inc(failed=True)
                raise
        return self.apply(name)

    def delete(self, name: str) -> dict:
        """Tear down and REMOVE the app dir: a deleted name must be
        re-creatable through the API (the CLI keeps the dir; a service has
        no other way to free the name)."""
        app_dir = self._app_dir(name)
        self._acquire(name)
        try:
            # existence check under the busy flag (like apply): a racing
            # delete/apply otherwise hits Coordinator.load on a removed dir
            if not os.path.exists(os.path.join(app_dir, "app.yaml")):
                raise ApiError(404, f"app {name} not found")
            Coordinator.load(app_dir).delete()
            import shutil
            shutil.rmtree(app_dir, ignore_errors=True)
        finally:
            self._release(name)
        return {"deleted": name}

    def list_apps(self) -> list[dict]:
        out = []
        for entry in sorted(os.listdir(self.apps_root)):
            if os.path.exists(os.path.join(self.apps_root, entry,
                                           "app.yaml")):
                try:
                    out.append(Coordinator.load(
                        os.path.join(self.apps_root, entry)).show())
                except Exception as e:  # noqa: BLE001 - listing is best-effort
                    out.append({"name": entry, "error": str(e)})
        return out

    def show(self, name: str) -> dict:
        app_dir = self._app_dir(name)
        if not os.path.exists(os.path.join(app_dir, "app.yaml")):
            raise ApiError(404, f"app {name} not found")
        return Coordinator.load(app_dir).show()

    # -- project IAM (ksServer.go:1465-1466) --------------------------------

    def _require_executor(self):
        if self.gcp_executor is None:
            raise ApiError(503, "no GCP executor configured (zero-egress "
                                "dev: construct BootstrapService with "
                                "gcp_executor=, e.g. a GcpSimulator)")
        return self.gcp_executor

    def apply_iam(self, body: dict) -> dict:
        """Rewrite the project policy for a deployment's generated SAs +
        IAP user. Serialized per project: two concurrent writers would
        race the policy read-modify-write (the reference holds a
        per-project mutex for the same reason, initHandler.go:45)."""
        from .iam import apply_iam
        executor = self._require_executor()
        project = body.get("project", "")
        cluster = body.get("cluster", "")
        if not project or not cluster:
            raise ApiError(400, "project and cluster are required")
        action = body.get("action", "add")
        if action not in ("add", "remove"):
            raise ApiError(400, f"action must be add|remove, got {action!r}")
        key = f"project:{project}"
        self._acquire(key)
        try:
            policy = apply_iam(executor, project=project, cluster=cluster,
                               email=body.get("email", ""), action=action)
        finally:
            self._release(key)
        return {"project": project, "action": action, "policy": policy}

    def init_project(self, body: dict) -> dict:
        """Grant the DM service account projectIamAdmin
        (initHandler.go makeInitProjectEndpoint)."""
        from .iam import init_project
        executor = self._require_executor()
        project = body.get("project", "")
        number = str(body.get("projectNumber", "") or "")
        if not project or not number:
            raise ApiError(400, "project and projectNumber are required")
        key = f"project:{project}"
        self._acquire(key)
        try:
            policy = init_project(executor, project=project,
                                  project_number=number)
        finally:
            self._release(key)
        return {"project": project, "policy": policy}


# the click-to-deploy page (gcp-click-to-deploy React UI analog): form →
# POST /kfctl/e2eDeploy, progress log, app listing — one static JS file
DEPLOY_HTML = """<!doctype html>
<html><head><title>Deploy Kubeflow TPU</title><meta charset="utf-8"><style>
body{font-family:sans-serif;margin:2rem auto;max-width:44rem}
form{display:grid;grid-template-columns:10rem 1fr;gap:0.6rem}
input,select{padding:0.4rem}button{grid-column:2;padding:0.6rem}
#deploy-log{background:#111;color:#9f9;font-family:monospace;
min-height:8rem;max-height:16rem;overflow-y:auto;padding:0.6rem;
margin-top:1rem;white-space:pre-wrap}
#deploy-log .error{color:#f99}#deploy-log .ok{color:#fff}
.empty{color:#777}</style></head><body>
<h1>Deploy Kubeflow TPU</h1>
<form id="deploy-form">
  <label>deployment name</label><input name="appname" required
    pattern="[a-z0-9][a-z0-9-]*" value="kubeflow">
  <label>platform</label><select name="platform">
    <option value="existing">existing cluster</option>
    <option value="gcp">gcp</option>
    <option value="minikube">minikube</option></select>
  <label>GCP project</label><input name="project" placeholder="(gcp only)">
  <label>zone</label><input name="zone" list="tpu-zones"
    placeholder="(gcp only, e.g. us-central2-b)">
  <datalist id="tpu-zones">
    <option value="us-central1-a"></option>
    <option value="us-central2-b"></option>
    <option value="us-east1-d"></option>
    <option value="us-east5-a"></option>
    <option value="europe-west4-a"></option>
    <option value="asia-east1-c"></option>
  </datalist>
  <label>namespace</label><input name="namespace" value="kubeflow">
  <label>config flavor</label><select name="flavor">
    <option value="">default</option><option>local</option>
    <option>iap</option><option>basic_auth</option></select>
  <label>components</label><select id="components" multiple size="8">
  </select>
  <button type="submit">Create deployment</button>
</form>
<div id="deploy-log"></div>
<h2>Deployments</h2><ul id="apps"></ul>
<h2>Project IAM</h2>
<form id="iam-form">
  <label>GCP project</label><input name="iamProject" required>
  <label>project number</label><input name="iamNumber"
    placeholder="(runs initProject first when set)">
  <label>cluster</label><input name="iamCluster" required>
  <label>IAP user email</label><input name="iamEmail" type="email">
  <label>action</label><select name="iamAction">
    <option value="add">add</option><option value="remove">remove</option>
  </select>
  <button type="submit">Apply IAM</button>
</form>
<script src="/deploy.js"></script>
</body></html>"""

_STATIC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "webapps", "static")


def build_bootstrap_app(service: BootstrapService) -> JsonApp:
    app = JsonApp()

    @app.route("GET", "/healthz")
    def healthz(params, query, body):
        return 200, {"ok": True}

    @app.route("GET", "/")
    def deploy_page(params, query, body):
        return 200, RawResponse(DEPLOY_HTML,
                                content_type="text/html; charset=utf-8")

    @app.route("GET", "/deploy.js")
    def deploy_js(params, query, body):
        with open(os.path.join(_STATIC_DIR, "deploy.js")) as f:
            return 200, RawResponse(
                f.read(),
                content_type="application/javascript; charset=utf-8")

    @app.route("GET", "/metrics")
    def metrics(params, query, body):
        return 200, RawResponse(service.counters.text())

    @app.route("GET", "/kfctl/components")
    def components(params, query, body):
        from ..manifests.registry import component_names
        return 200, {"components": component_names()}

    @app.route("POST", "/kfctl/apps/create")
    def create(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        return 200, service.create(body)

    @app.route("POST", "/kfctl/apps/apply")
    def apply(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        return 200, service.apply(body["name"])

    @app.route("POST", "/kfctl/e2eDeploy")
    def e2e(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        return 200, service.e2e_deploy(body)

    @app.route("POST", "/kfctl/apps/delete")
    def delete(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        return 200, service.delete(body["name"])

    @app.route("POST", "/kfctl/iam/apply")
    def iam_apply(params, query, body):
        return 200, service.apply_iam(body or {})

    @app.route("POST", "/kfctl/initProject")
    def init_project(params, query, body):
        return 200, service.init_project(body or {})

    @app.route("GET", "/kfctl/apps")
    def list_apps(params, query, body):
        return 200, {"apps": service.list_apps()}

    @app.route("GET", "/kfctl/apps/{name}")
    def show(params, query, body):
        return 200, service.show(params["name"])

    return app


class BootstrapServer(JsonServer):
    def __init__(self, apps_root: str, gcp_executor=None, **kw):
        self.service = BootstrapService(apps_root, gcp_executor=gcp_executor)
        super().__init__(build_bootstrap_app(self.service), name="bootstrap",
                         **kw)
