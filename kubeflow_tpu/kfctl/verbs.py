"""kfctl verb registration (placeholder until the coordinator lands).

Each verb maps to the coordinator fan-out described in SURVEY.md §3.1.
"""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    # Populated by the coordinator milestone; keeping the import seam stable.
    try:
        from .coordinator import register_verbs
    except ImportError:
        return
    register_verbs(sub)
