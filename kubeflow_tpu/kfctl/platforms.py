"""Platform drivers (the KfApp implementations, L0 of SURVEY.md §1).

Reference: the `KfApp` Go interface Init/Generate/Apply/Delete(ResourceEnum)
(bootstrap/pkg/apis/apps/group.go:99-104) with platform impls looked up by
name (gcp.go, minikube.go, dockerfordesktop.go). Same shape here; the `gcp`
driver emits deployment-manager-style configs with **TPU pod-slice node
pools** where the reference emitted GPU pools, and gates actual cloud calls
behind an injectable executor (no network in dev).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

from ..api.kfdef import (KfDef, PLATFORM_DOCKER_FOR_DESKTOP, PLATFORM_EXISTING,
                         PLATFORM_GCP, PLATFORM_MINIKUBE, PLATFORM_NONE)
from ..api.topology import parse_topology
from ..utils import yamlio

log = logging.getLogger(__name__)


class Platform:
    """Init/Generate/Apply/Delete over platform-scoped resources."""

    name = "none"

    def init(self, kfdef: KfDef) -> None:  # noqa: B027
        pass

    def generate(self, kfdef: KfDef) -> None:  # noqa: B027
        pass

    def apply(self, kfdef: KfDef) -> None:  # noqa: B027
        pass

    def delete(self, kfdef: KfDef) -> None:  # noqa: B027
        pass


class NonePlatform(Platform):
    name = PLATFORM_NONE


class ExistingCluster(Platform):
    """Deploy onto a cluster that already exists (kubeconfig / in-memory)."""

    name = PLATFORM_EXISTING


class Minikube(Platform):
    """Local minikube (minikube.go analog): validates the VM exists."""

    name = PLATFORM_MINIKUBE

    def init(self, kfdef: KfDef) -> None:
        log.info("minikube platform: assuming an existing minikube VM "
                 "(reference parity: minikube.go relies on pre-created VM)")


class DockerForDesktop(Platform):
    name = PLATFORM_DOCKER_FOR_DESKTOP


class GcpPlatform(Platform):
    """GCP driver (gcp.go analog, 1,616 LoC in the reference).

    generate: writes deployment-manager-style configs into
    <app_dir>/gcp_config/ — cluster with TPU pod-slice node pools, IAM
    bindings, storage (generateDMConfigs analog, gcp.go:1238).
    apply/delete: calls the injected executor with the prepared requests
    (updateDM analog, gcp.go:562); by default the executor raises, since
    this build runs with zero cloud egress.
    """

    name = PLATFORM_GCP

    def __init__(self, executor: Optional[Callable[[str, dict], None]] = None):
        self.executor = executor

    def _config_dir(self, kfdef: KfDef) -> str:
        return os.path.join(kfdef.spec.app_dir, "gcp_config")

    def generate(self, kfdef: KfDef) -> None:
        topo = parse_topology(kfdef.spec.default_tpu_topology)
        d = self._config_dir(kfdef)
        os.makedirs(d, exist_ok=True)
        cluster = {
            "resources": [{
                "name": f"{kfdef.name}-cluster",
                "type": "container.v1.cluster",
                "properties": {
                    "zone": kfdef.spec.zone or "us-central2-b",
                    "cluster": {
                        "name": f"{kfdef.name}",
                        "initialClusterVersion": "latest",
                        "nodePools": [
                            {"name": "cpu-pool", "initialNodeCount": 2,
                             "config": {"machineType": "e2-standard-8"}},
                            {"name": "tpu-pool",
                             "initialNodeCount": topo.num_hosts,
                             "config": {
                                 "machineType": f"ct5lp-hightpu-{topo.chips_per_host}t",
                                 "labels": {
                                     "cloud.google.com/gke-tpu-accelerator":
                                         f"tpu-{topo.generation.name}",
                                     "cloud.google.com/gke-tpu-topology":
                                         topo.name,
                                 }}},
                        ],
                    },
                },
            }],
        }
        yamlio.dump_file(cluster, os.path.join(d, "cluster-kubeflow.yaml"))
        iam = {"bindings": [
            {"role": "roles/tpu.admin",
             "members": [f"serviceAccount:{kfdef.name}-admin@"
                         f"{kfdef.spec.project}.iam.gserviceaccount.com"]},
            {"role": "roles/container.admin",
             "members": [f"serviceAccount:{kfdef.name}-admin@"
                         f"{kfdef.spec.project}.iam.gserviceaccount.com"]},
        ]}
        yamlio.dump_file(iam, os.path.join(d, "iam_bindings.yaml"))
        log.info("gcp configs written to %s", d)

    def apply(self, kfdef: KfDef) -> None:
        if self.executor is None:
            raise RuntimeError(
                "gcp platform apply requires cloud access (no egress in this "
                "environment); configs were generated under gcp_config/ — "
                "apply them with `gcloud deployment-manager deployments "
                "create` or inject an executor")
        self.executor("deployments.insert",
                      {"config": os.path.join(self._config_dir(kfdef),
                                              "cluster-kubeflow.yaml")})

    def delete(self, kfdef: KfDef) -> None:
        if self.executor is not None:
            self.executor("deployments.delete", {"name": f"{kfdef.name}-cluster"})


_PLATFORMS: dict[str, Callable[[], Platform]] = {
    PLATFORM_NONE: NonePlatform,
    PLATFORM_EXISTING: ExistingCluster,
    PLATFORM_MINIKUBE: Minikube,
    PLATFORM_DOCKER_FOR_DESKTOP: DockerForDesktop,
    PLATFORM_GCP: GcpPlatform,
}


def get_platform(name: str) -> Platform:
    """Platform lookup by name (group.go:134-144 analog)."""
    try:
        return _PLATFORMS[name]()
    except KeyError:
        raise ValueError(
            f"unknown platform {name!r}; known: {sorted(_PLATFORMS)}") from None
