"""Platform drivers (the KfApp implementations, L0 of SURVEY.md §1).

Reference: the `KfApp` Go interface Init/Generate/Apply/Delete(ResourceEnum)
(bootstrap/pkg/apis/apps/group.go:99-104) with platform impls looked up by
name (gcp.go, minikube.go, dockerfordesktop.go). Same shape here; the `gcp`
driver emits deployment-manager-style configs with **TPU pod-slice node
pools** where the reference emitted GPU pools, and gates actual cloud calls
behind an injectable executor (no network in dev).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

from ..api.kfdef import (KfDef, PLATFORM_DOCKER_FOR_DESKTOP, PLATFORM_EXISTING,
                         PLATFORM_GCP, PLATFORM_MINIKUBE, PLATFORM_NONE)
from ..api.topology import parse_topology
from ..utils import yamlio

log = logging.getLogger(__name__)


class Platform:
    """Init/Generate/Apply/Delete over platform-scoped resources."""

    name = "none"

    def init(self, kfdef: KfDef) -> None:  # noqa: B027
        pass

    def generate(self, kfdef: KfDef) -> None:  # noqa: B027
        pass

    def apply(self, kfdef: KfDef) -> None:  # noqa: B027
        pass

    def delete(self, kfdef: KfDef) -> None:  # noqa: B027
        pass


class NonePlatform(Platform):
    name = PLATFORM_NONE


class ExistingCluster(Platform):
    """Deploy onto a cluster that already exists (kubeconfig / in-memory)."""

    name = PLATFORM_EXISTING


def _subprocess_runner(cmd: list) -> str:
    """Default runner for the local platform drivers: shell out the way
    minikube.go does; a missing CLI is a loud, actionable error."""
    import subprocess
    try:
        return subprocess.run(cmd, check=True, capture_output=True,
                              timeout=30, text=True).stdout
    except FileNotFoundError:
        raise RuntimeError(
            f"{cmd[0]!r} CLI not found — install it or pass a runner")
    except subprocess.TimeoutExpired:
        raise RuntimeError(f"{' '.join(cmd)} timed out after 30s")
    except subprocess.CalledProcessError as e:
        # minikube reports stopped VMs via exit code with detail on stdout
        detail = (e.stderr or "").strip() or (e.stdout or "").strip()
        raise RuntimeError(f"{' '.join(cmd)} failed: {detail}")


class Minikube(Platform):
    """Local minikube driver (minikube.go analog, 154 LoC): verifies the
    VM is running and the kube context points at it before k8s apply —
    through an injectable command runner defaulting to subprocess (the
    reference shells out to `minikube status` / kubectl config)."""

    name = PLATFORM_MINIKUBE

    def __init__(self, runner: Callable[[list], str] = _subprocess_runner):
        self.runner = runner

    def init(self, kfdef: KfDef) -> None:
        try:
            status = self.runner(["minikube", "status",
                                  "--format", "{{.Host}}"]).strip()
        except RuntimeError as e:
            # a stopped/nonexistent VM exits non-zero — same remedy
            raise RuntimeError(
                f"minikube VM is not running ({e}); "
                "run `minikube start` first") from None
        if status.lower() != "running":
            raise RuntimeError(
                f"minikube VM is not running (status={status!r}); "
                "run `minikube start` first")
        context = self.runner(["kubectl", "config",
                               "current-context"]).strip()
        if context != "minikube":
            raise RuntimeError(
                f"kube context is {context!r}, not 'minikube' — "
                "`kubectl config use-context minikube`")

    def apply(self, kfdef: KfDef) -> None:
        # platform resources are the VM itself; verify it is still up
        self.init(kfdef)


class DockerForDesktop(Platform):
    """docker-for-desktop driver (dockerfordesktop.go analog): the
    reference builds this as a Go .so plugin; here it is just another
    registered platform that checks the docker-desktop kube context."""

    name = PLATFORM_DOCKER_FOR_DESKTOP

    def __init__(self, runner: Callable[[list], str] = _subprocess_runner):
        self.runner = runner

    def init(self, kfdef: KfDef) -> None:
        context = self.runner(["kubectl", "config",
                               "current-context"]).strip()
        if context not in ("docker-for-desktop", "docker-desktop"):
            raise RuntimeError(
                f"kube context is {context!r}, not docker-desktop")


class CloudOpError(RuntimeError):
    """A cloud operation finished with errors (blockingWait failure)."""


class Backoff:
    """Exponential backoff schedule (gcp.go newDefaultBackoff :129)."""

    def __init__(self, initial_s: float = 1.0, factor: float = 2.0,
                 max_interval_s: float = 30.0, deadline_s: float = 1200.0):
        self.initial_s = initial_s
        self.factor = factor
        self.max_interval_s = max_interval_s
        self.deadline_s = deadline_s

    def intervals(self):
        total, cur = 0.0, self.initial_s
        while total < self.deadline_s:
            yield cur
            total += cur
            cur = min(cur * self.factor, self.max_interval_s)


def blocking_wait(executor: "Callable[[str, dict], dict]", op: dict,
                  backoff: Optional[Backoff] = None,
                  sleep: Callable[[float], None] = None) -> dict:
    """Poll a deployment-manager operation to DONE with exponential
    backoff (gcp.go blockingWait :267-308). Raises CloudOpError on an
    errored op, TimeoutError past the backoff deadline."""
    import time as _time
    sleep = sleep or _time.sleep
    backoff = backoff or Backoff()
    name = op.get("name", "")

    def check(op: dict) -> bool:
        if op.get("status") != "DONE":
            return False
        errors = (op.get("error") or {}).get("errors")
        if errors:
            raise CloudOpError(f"operation {name} failed: {errors}")
        return True

    if check(op):
        return op
    for interval in backoff.intervals():
        sleep(interval)
        op = executor("operations.get", {"operation": name})
        if check(op):  # the final poll must count too
            return op
    raise TimeoutError(f"operation {name} did not complete within "
                       f"{backoff.deadline_s}s")


class GcpPlatform(Platform):
    """GCP driver (gcp.go analog, 1,616 LoC in the reference).

    generate: writes deployment-manager-style configs into
    <app_dir>/gcp_config/ — cluster with TPU pod-slice node pools, IAM
    bindings, storage (generateDMConfigs analog, gcp.go:1238).

    apply/delete drive the full reference sequence behind the executor
    seam (zero-egress dev default: no executor → actionable error):
      1. deployments.get → insert or update        (updateDM, gcp.go:562)
      2. poll the returned op with exponential backoff
                                             (blockingWait, gcp.go:267-308)
      3. getIamPolicy → merge bindings → setIamPolicy
                                             (updateIamPolicy, gcp.go:392)
      4. service-account key → k8s secret manifests
                                             (createSecrets, gcp.go:1391)
      5. admin RBAC manifest                 (ConfigK8s/bindAdmin, gcp.go:440)
    The executor is `call(method, request) -> response`; a production
    executor maps methods onto googleapis clients 1:1.
    """

    name = PLATFORM_GCP

    def __init__(self, executor: Optional[Callable[[str, dict], dict]] = None,
                 backoff: Optional[Backoff] = None,
                 sleep: Callable[[float], None] = None):
        self.executor = executor
        self.backoff = backoff
        self.sleep = sleep

    def _config_dir(self, kfdef: KfDef) -> str:
        return os.path.join(kfdef.spec.app_dir, "gcp_config")

    def generate(self, kfdef: KfDef) -> None:
        topo = parse_topology(kfdef.spec.default_tpu_topology)
        d = self._config_dir(kfdef)
        os.makedirs(d, exist_ok=True)
        cluster = {
            "resources": [{
                "name": f"{kfdef.name}-cluster",
                "type": "container.v1.cluster",
                "properties": {
                    "zone": kfdef.spec.zone or "us-central2-b",
                    "cluster": {
                        "name": f"{kfdef.name}",
                        "initialClusterVersion": "latest",
                        "nodePools": [
                            {"name": "cpu-pool", "initialNodeCount": 2,
                             "config": {"machineType": "e2-standard-8"}},
                            {"name": "tpu-pool",
                             "initialNodeCount": topo.num_hosts,
                             "config": {
                                 "machineType": f"ct5lp-hightpu-{topo.chips_per_host}t",
                                 "labels": {
                                     "cloud.google.com/gke-tpu-accelerator":
                                         f"tpu-{topo.generation.name}",
                                     "cloud.google.com/gke-tpu-topology":
                                         topo.name,
                                 }}},
                        ],
                    },
                },
            }],
        }
        yamlio.dump_file(cluster, os.path.join(d, "cluster-kubeflow.yaml"))
        iam = {"bindings": [
            {"role": "roles/tpu.admin",
             "members": [f"serviceAccount:{kfdef.name}-admin@"
                         f"{kfdef.spec.project}.iam.gserviceaccount.com"]},
            {"role": "roles/container.admin",
             "members": [f"serviceAccount:{kfdef.name}-admin@"
                         f"{kfdef.spec.project}.iam.gserviceaccount.com"]},
        ]}
        yamlio.dump_file(iam, os.path.join(d, "iam_bindings.yaml"))
        log.info("gcp configs written to %s", d)

    # -- apply stages (updateDM → blockingWait → IAM → secrets → RBAC) ------

    def _deployment_name(self, kfdef: KfDef) -> str:
        return f"{kfdef.name}-cluster"

    def _update_dm(self, kfdef: KfDef) -> dict:
        """Insert-or-update the DM deployment (gcp.go updateDM :562)."""
        name = self._deployment_name(kfdef)
        config_path = os.path.join(self._config_dir(kfdef),
                                   "cluster-kubeflow.yaml")
        request = {"project": kfdef.spec.project, "deployment": name,
                   "config": config_path}
        # executor seam convention: deployments.get returns None for a
        # missing deployment (a googleapis-backed executor catches its
        # HttpError 404 and returns None — documented contract, not an
        # exception type the simulator happens to raise)
        existing = self.executor("deployments.get",
                                 {"project": kfdef.spec.project,
                                  "deployment": name})
        method = "deployments.update" if existing else "deployments.insert"
        if existing:
            # DM update requires the current fingerprint (gcp.go :600)
            request["fingerprint"] = existing.get("fingerprint", "")
        return self.executor(method, request)

    def _update_iam(self, kfdef: KfDef) -> None:
        """Read-modify-write the project IAM policy, preserving existing
        members (gcp.go updateIamPolicy — naive set overwrites races)."""
        policy = self.executor("projects.getIamPolicy",
                               {"project": kfdef.spec.project})
        bindings = {b["role"]: list(b.get("members", []))
                    for b in policy.get("bindings", [])}
        wanted = yamlio.load_file(
            os.path.join(self._config_dir(kfdef), "iam_bindings.yaml"))
        for b in wanted.get("bindings", []):
            members = bindings.setdefault(b["role"], [])
            for m in b.get("members", []):
                if m not in members:
                    members.append(m)
        self.executor("projects.setIamPolicy", {
            "project": kfdef.spec.project,
            "policy": {"etag": policy.get("etag", ""),
                       "bindings": [{"role": r, "members": m}
                                    for r, m in sorted(bindings.items())]},
        })

    def _create_secrets(self, kfdef: KfDef) -> None:
        """Mint the admin SA key and stage it as a k8s Secret manifest for
        the k8s apply phase (gcp.go createSecrets :1391 creates
        admin-gcp-sa + user-gcp-sa + oauth secrets in-cluster)."""
        sa = (f"{kfdef.name}-admin@{kfdef.spec.project}"
              f".iam.gserviceaccount.com")
        key = self.executor("serviceAccounts.keys.create", {"name": sa})
        secrets = [{
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": "admin-gcp-sa",
                         "namespace": kfdef.spec.namespace},
            "data": {"admin-gcp-sa.json":
                     key.get("privateKeyData", "")},
        }]
        yamlio.dump_file({"secrets": secrets},
                         os.path.join(self._config_dir(kfdef),
                                      "secrets.yaml"))

    def _bind_admin(self, kfdef: KfDef) -> None:
        """Stage the cluster-admin binding applied right after cluster
        creation (gcp.go ConfigK8s/bindAdmin :440)."""
        binding = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "default-admin"},
            "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                        "kind": "ClusterRole", "name": "cluster-admin"},
            "subjects": [{"kind": "User",
                          "name": f"{kfdef.name}-admin@{kfdef.spec.project}"
                                  f".iam.gserviceaccount.com"}],
        }
        yamlio.dump_file(binding,
                         os.path.join(self._config_dir(kfdef),
                                      "default-admin.yaml"))

    def apply(self, kfdef: KfDef) -> None:
        if self.executor is None:
            raise RuntimeError(
                "gcp platform apply requires cloud access (no egress in this "
                "environment); configs were generated under gcp_config/ — "
                "apply them with `gcloud deployment-manager deployments "
                "create` or inject an executor")
        op = self._update_dm(kfdef)
        blocking_wait(self.executor, op, backoff=self.backoff,
                      sleep=self.sleep)
        self._update_iam(kfdef)
        self._create_secrets(kfdef)
        self._bind_admin(kfdef)

    def delete(self, kfdef: KfDef) -> None:
        if self.executor is None:
            return
        op = self.executor("deployments.delete",
                           {"project": kfdef.spec.project,
                            "deployment": self._deployment_name(kfdef)})
        blocking_wait(self.executor, op, backoff=self.backoff,
                      sleep=self.sleep)


_PLATFORMS: dict[str, Callable[[], Platform]] = {
    PLATFORM_NONE: NonePlatform,
    PLATFORM_EXISTING: ExistingCluster,
    PLATFORM_MINIKUBE: Minikube,
    PLATFORM_DOCKER_FOR_DESKTOP: DockerForDesktop,
    PLATFORM_GCP: GcpPlatform,
}


def get_platform(name: str) -> Platform:
    """Platform lookup by name (group.go:134-144 analog)."""
    try:
        return _PLATFORMS[name]()
    except KeyError:
        raise ValueError(
            f"unknown platform {name!r}; known: {sorted(_PLATFORMS)}") from None
