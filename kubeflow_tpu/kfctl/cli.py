"""kfctl command-line entry point.

Verbs mirror the reference CLI (bootstrap/cmd/kfctl: init, generate, apply,
delete, show, version). Verb implementations live in the coordinator; this
module is argument parsing only.
"""

from __future__ import annotations

import argparse
import sys

from .. import __version__


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kfctl",
        description="Deploy and manage the TPU-native Kubeflow platform.",
    )
    sub = p.add_subparsers(dest="verb")
    sub.add_parser("version", help="print version")
    # init/generate/apply/delete/show live in the coordinator module
    # (imported lazily so `kfctl version` works without cluster deps)
    from .coordinator import register_verbs
    register_verbs(sub)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.verb == "version" or args.verb is None:
        print(f"kfctl (kubeflow-tpu) {__version__}")
        return 0
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
