"""kfctl — the deployment CLI (init/generate/apply/delete/show) and coordinator."""
