"""Project-IAM operations behind the bootstrap server's GCP seam.

The reference's bootstrap server exposes two IAM routes
(ksServer.go:1465-1466): /kfctl/iam/apply — rewrite the project policy
for a deployment's generated service accounts + the IAP user
(gcpUtils.go:145 ClearServiceAccountPolicy, :179 UpdatePolicy, :229
ApplyIamPolicy over a bindings template) — and /kfctl/initProject —
grant the Deployment-Manager service account projectIamAdmin so DM can
edit IAM during deploy (initHandler.go makeInitProjectEndpoint/BindRole).

Same semantics here over the executor seam GcpPlatform already uses
(projects.getIamPolicy / projects.setIamPolicy), so the GcpSimulator
exercises the full read-modify-write including etag conflicts. The
bindings template is TPU-era: tpu.admin/container.admin for the admin
SA, storage+aiplatform for the user SA, log/metric writers for the VM
SA, iap.httpsResourceAccessor for the IAP account.
"""

from __future__ import annotations

from typing import Callable

IAM_ADMIN_ROLE = "roles/resourcemanager.projectIamAdmin"

# Placeholder names are the reference template's contract
# (iam_bindings_template.yaml): the request's cluster/project/email
# resolve them to concrete accounts at apply time.
SA_ADMIN = "set-kubeflow-admin-service-account"
SA_USER = "set-kubeflow-user-service-account"
SA_VM = "set-kubeflow-vm-service-account"
SA_IAP = "set-kubeflow-iap-account"

IAM_BINDINGS_TEMPLATE = {
    "bindings": [
        {"members": [SA_ADMIN],
         "roles": ["roles/tpu.admin", "roles/container.admin",
                   "roles/servicemanagement.admin",
                   "roles/compute.networkAdmin"]},
        {"members": [SA_USER],
         "roles": ["roles/storage.admin", "roles/viewer",
                   "roles/aiplatform.user", "roles/bigquery.admin"]},
        {"members": [SA_VM],
         "roles": ["roles/logging.logWriter",
                   "roles/monitoring.metricWriter",
                   "roles/storage.objectViewer"]},
        {"members": [SA_IAP],
         "roles": ["roles/iap.httpsResourceAccessor"]},
    ],
}


def prepare_account(account: str) -> str:
    """Prefix a bare account with its IAM member kind
    (gcpUtils.go:168 PrepareAccount)."""
    if account.startswith(("serviceAccount:", "user:", "group:")):
        return account
    if "iam.gserviceaccount.com" in account or \
            account.endswith("gserviceaccount.com"):
        return "serviceAccount:" + account
    return "user:" + account


def _generated_accounts(project: str, cluster: str) -> dict[str, str]:
    """The deployment's auto-generated SAs, placeholder → member."""
    return {
        SA_ADMIN: prepare_account(
            f"{cluster}-admin@{project}.iam.gserviceaccount.com"),
        SA_USER: prepare_account(
            f"{cluster}-user@{project}.iam.gserviceaccount.com"),
        SA_VM: prepare_account(
            f"{cluster}-vm@{project}.iam.gserviceaccount.com"),
    }


def clear_service_account_policy(policy: dict, project: str,
                                 cluster: str) -> None:
    """Drop every binding member that is one of the deployment's
    generated SAs — leftovers from previous applies are reset before the
    template is re-applied (gcpUtils.go:145)."""
    generated = set(_generated_accounts(project, cluster).values())
    policy["bindings"] = [
        {"role": b.get("role", ""),
         "members": [m for m in b.get("members", [])
                     if m not in generated]}
        for b in policy.get("bindings", [])
    ]


def update_policy(policy: dict, *, project: str, cluster: str,
                  email: str, action: str = "add") -> None:
    """Merge the resolved bindings template into ``policy`` in place
    (gcpUtils.go:179): action "add" inserts members, "remove" deletes
    them; untouched existing members survive (read-modify-write, never a
    blind overwrite)."""
    members_by_role: dict[str, list[str]] = {}
    for b in policy.get("bindings", []):
        members_by_role.setdefault(b.get("role", ""), [])
        for m in b.get("members", []):
            if m not in members_by_role[b["role"]]:
                members_by_role[b["role"]].append(m)

    mapping = _generated_accounts(project, cluster)
    mapping[SA_IAP] = prepare_account(email) if email else ""
    for binding in IAM_BINDINGS_TEMPLATE["bindings"]:
        for placeholder in binding["members"]:
            member = mapping.get(placeholder, placeholder)
            if not member:
                continue  # no IAP email in the request
            for role in binding["roles"]:
                members = members_by_role.setdefault(role, [])
                if action == "add" and member not in members:
                    members.append(member)
                elif action == "remove" and member in members:
                    members.remove(member)

    policy["bindings"] = [{"role": r, "members": m}
                          for r, m in sorted(members_by_role.items()) if m]


def apply_iam(executor: Callable[[str, dict], dict], *, project: str,
              cluster: str, email: str = "", action: str = "add") -> dict:
    """The /kfctl/iam/apply operation: get → clear generated SAs →
    apply template → set, preserving the policy etag so a concurrent
    writer surfaces as a conflict instead of a lost update."""
    if action not in ("add", "remove"):
        raise ValueError(f"action must be add|remove, got {action!r}")
    policy = executor("projects.getIamPolicy", {"project": project})
    clear_service_account_policy(policy, project, cluster)
    update_policy(policy, project=project, cluster=cluster, email=email,
                  action=action)
    return executor("projects.setIamPolicy", {
        "project": project,
        "policy": {"etag": policy.get("etag", ""),
                   "bindings": policy["bindings"]},
    })


def init_project(executor: Callable[[str, dict], dict], *, project: str,
                 project_number: str) -> dict:
    """The /kfctl/initProject operation: bind the project's
    Deployment-Manager service account
    (<number>@cloudservices.gserviceaccount.com) to projectIamAdmin so
    DM-driven deploys may edit IAM (initHandler.go BindRole)."""
    dm_sa = prepare_account(
        f"{project_number}@cloudservices.gserviceaccount.com")
    policy = executor("projects.getIamPolicy", {"project": project})
    bindings = {b.get("role", ""): list(b.get("members", []))
                for b in policy.get("bindings", [])}
    members = bindings.setdefault(IAM_ADMIN_ROLE, [])
    if dm_sa not in members:
        members.append(dm_sa)
    return executor("projects.setIamPolicy", {
        "project": project,
        "policy": {"etag": policy.get("etag", ""),
                   "bindings": [{"role": r, "members": m}
                                for r, m in sorted(bindings.items())]},
    })
