"""Benchmark: ResNet-50 synthetic-ImageNet training throughput on TPU.

The vehicle matches the reference's headline benchmark machinery — the
tf_cnn_benchmarks ResNet-50 TFJob (tf-controller-examples/tf-cnn/;
kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet runs it with synthetic
data). The reference publishes no numbers (BASELINE.md), so the baseline is
our own recorded first-light figure; vs_baseline = value / BASELINE_IMG_S.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "mfu": N, "extras": {...}}

mfu is computed against the DETECTED chip generation's bf16 peak; extras
also reports mfu against the chip's *measured* achievable matmul rate
(calibrated at bench start — see PERF.md for why those differ on tunneled
chips) and the startup→first-step latency (BASELINE.md north-star #2).
"""

from __future__ import annotations

import json
import sys
import time

# First-light measurement on one TPU v5e chip (bf16, batch 256, synthetic
# data, this repo @ milestone 3). Later rounds must beat it.
BASELINE_IMG_S = 1000.0

# ResNet-50 @224 fwd ≈ 4.09 GFLOP/image; fwd+bwd ≈ 3x fwd (dgrad + wgrad
# each cost ~one fwd). Conventional MFU flop model (matmul/conv MACs only).
TRAIN_GFLOP_PER_IMAGE = 3 * 4.09

# bf16 peak TFLOP/s by device_kind substring (public spec sheets)
PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0, "v5": 459.0,          # 'v5' alone = v5p
    "v4": 275.0, "v3": 123.0, "v2": 46.0,
    "v6 lite": 918.0, "v6e": 918.0,
}


def detect_peak_tflops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key in sorted(PEAK_TFLOPS, key=len, reverse=True):
        if key in kind:
            return PEAK_TFLOPS[key]
    return None


def measure_achievable_tflops() -> float:
    """Calibrate the chip's sustained large-matmul rate (the honest MFU
    denominator on virtualized/tunneled chips that underdeliver spec)."""
    import jax
    import jax.numpy as jnp

    n = 8192
    x = jnp.ones((n, n), jnp.bfloat16)
    f = jax.jit(lambda a, b: a @ b)
    y = f(x, x)
    float(y[0, 0])
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        y = f(y, x)
    float(y[0, 0])
    dt = time.perf_counter() - t0
    return 2 * n ** 3 * iters / dt / 1e12


def _probe_backend(timeout_s: float = 180.0) -> bool:
    """Bounded backend init: a wedged TPU tunnel makes jax.devices() hang
    for MINUTES-to-forever (killed TPU processes leave the tunnel
    unresponsive), which would turn the whole bench run into a silent
    hang with no artifact. Probe in a daemon thread; on timeout, force
    the CPU backend so the run still emits its JSON line (with an error
    note) instead of nothing."""
    import threading
    ok = threading.Event()
    done = threading.Event()

    def probe():
        try:
            import jax
            jax.devices()
            ok.set()
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True, name="backend-probe")
    t.start()
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if done.wait(1.0):
            # thread finished: either devices() worked, or it raised
            # promptly (no jax / plugin error) — fail FAST in that case,
            # don't burn the whole timeout on a non-hang
            return ok.is_set()
    print(f"# backend init exceeded {timeout_s:.0f}s (tunnel wedged?); "
          "falling back to CPU", file=sys.stderr, flush=True)
    return False


def main() -> int:
    t_start = time.perf_counter()
    import os
    # the fallback child carries this marker: never probe/respawn again
    # (a second failure must end the chain, not fork a grandchild)
    backend_ok = bool(os.environ.get("KFTPU_BENCH_BACKEND_ERROR")) or \
        _probe_backend()
    if not backend_ok:
        # the probe thread is stuck inside backend init; a fresh
        # CPU-pinned process is the only clean escape
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PALLAS_AXON_POOL_IPS": "",
               "KFTPU_BENCH_BACKEND_ERROR": "tpu backend unreachable"}
        import subprocess
        return subprocess.call([sys.executable, __file__], env=env)
    import jax
    import optax

    from kubeflow_tpu.models import resnet as R
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    dev = jax.devices()[0]
    platform = dev.platform
    on_tpu = platform == "tpu"

    n_chips = len(jax.devices())
    if on_tpu:
        # batch 128/chip measured fastest on v5e (128: ~2600, 256: ~2500,
        # 512: ~2360, 1024: ~2020 img/s) — the step is HBM-roofline-bound
        # (PERF.md), so larger batches only add activation traffic
        batch_per_chip, image_size, steps, warmup = 128, 224, 40, 4
    else:  # CPU smoke mode so the script stays runnable anywhere
        batch_per_chip, image_size, steps, warmup = 8, 64, 4, 1
    global_batch = batch_per_chip * n_chips

    model = R.resnet50(num_classes=1000)
    builder = TrainStepBuilder(
        mesh=build_mesh(),
        loss_fn=R.make_loss_fn(model),
        optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                              optax.sgd(0.1, momentum=0.9)),
    )
    state = builder.init(R.init_fn(model, image_size=image_size),
                         jax.random.PRNGKey(0))
    step_fn = builder.build()
    batch = R.synthetic_batch(jax.random.PRNGKey(1), global_batch, image_size)
    if on_tpu:
        # feed bf16 images: the model's first act is the bf16 cast, so this
        # is loss-free and halves the input-image HBM read (PERF.md)
        import jax.numpy as jnp
        batch["images"] = batch["images"].astype(jnp.bfloat16)
    batch = builder.place_batch(batch)

    # sync via host transfer (float()), not block_until_ready: on the
    # tunneled axon platform block_until_ready returns before the compute
    # finishes, which inflated throughput ~70x; a device->host fetch of the
    # last step's loss is a hard barrier everywhere
    state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    # startup→first-step latency: process start → first train step done
    # (init + compile dominated). BASELINE.md north-star metric #2.
    startup_first_step_s = time.perf_counter() - t_start

    for _ in range(warmup - 1):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    img_s = global_batch * steps / dt
    img_s_chip = img_s / n_chips

    flops_per_chip = img_s_chip * TRAIN_GFLOP_PER_IMAGE * 1e9
    peak = detect_peak_tflops(dev)
    mfu = flops_per_chip / (peak * 1e12) if peak else None
    extras = {
        "device_kind": getattr(dev, "device_kind", platform),
        "startup_first_step_s": round(startup_first_step_s, 2),
        "peak_tflops_spec": peak,
        "model_tflops": round(flops_per_chip / 1e12, 1),
    }
    backend_error = os.environ.get("KFTPU_BENCH_BACKEND_ERROR")
    if backend_error:
        # this run is the CPU-fallback child: record WHY the number is not
        # a TPU measurement so the artifact is never silently misread
        extras["error"] = backend_error
    if on_tpu:
        achievable = measure_achievable_tflops()
        extras["achievable_matmul_tflops"] = round(achievable, 1)
        extras["mfu_vs_achievable"] = round(flops_per_chip / (achievable * 1e12), 3)

    print(json.dumps({
        "metric": "resnet50_synthetic_imagenet_train_throughput",
        "value": round(img_s_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 3),
        "mfu": round(mfu, 3) if mfu is not None else None,
        "extras": extras,
    }))
    print(f"# platform={platform} chips={n_chips} batch={global_batch} "
          f"image={image_size} steps={steps} wall={dt:.2f}s "
          f"loss={float(metrics['loss']):.3f} "
          f"first_step={startup_first_step_s:.1f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
