"""Benchmark: training throughput on TPU (ResNet-50 primary + sub-benches).

The vehicle matches the reference's headline benchmark machinery — the
tf_cnn_benchmarks ResNet-50 TFJob (tf-controller-examples/tf-cnn/;
kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet runs it with synthetic
data). The reference publishes no numbers (BASELINE.md), so the baseline is
our own recorded first-light figure; vs_baseline = value / BASELINE_IMG_S.

Default run prints ONE JSON line (the driver contract):
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "mfu": N, "extras": {...}}
with two sub-benchmarks folded into extras (each failure-guarded so the
primary artifact always lands):
  - extras.fused: the ghost-BN fused-block variant (ops/fused_block_train)
  - extras.lm: transformer-LM tokens/sec + MFU (bf16, flash attention,
    chip-filling batch — the compute-bound workload whose MFU the HBM
    roofline can't excuse)

`--mode resnet|resnet-fused|lm` runs one benchmark standalone and prints
its own JSON line (used while tuning; the driver runs the default).

mfu is computed against the DETECTED chip generation's bf16 peak; extras
also reports mfu against the chip's *measured* achievable matmul rate
(calibrated at bench start — see PERF.md for why those differ on tunneled
chips) and the startup→first-step latency (BASELINE.md north-star #2).
"""

from __future__ import annotations

import json
import sys
import time

from kubeflow_tpu.utils.chips import (BASELINE_IMG_S,  # noqa: E402
                                      RESNET50_TRAIN_GFLOP_PER_IMAGE
                                      as TRAIN_GFLOP_PER_IMAGE,
                                      detect_peak_tflops)
# the HLO collective vocabulary lives in ONE module (ISSUE 13,
# lint-pinned): the comm analyzer and this bench count the same op
# literals by construction. Re-exported because the dryrun and the
# weight-update tests historically import it from here.
from kubeflow_tpu.obs.collectives import collective_counts  # noqa: E402,F401


def measure_achievable_tflops() -> float:
    """Calibrate the chip's sustained large-matmul rate (the honest MFU
    denominator on virtualized/tunneled chips that underdeliver spec)."""
    import jax
    import jax.numpy as jnp

    n = 8192
    x = jnp.ones((n, n), jnp.bfloat16)
    f = jax.jit(lambda a, b: a @ b)
    y = f(x, x)
    float(y[0, 0])
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        y = f(y, x)
    float(y[0, 0])
    dt = time.perf_counter() - t0
    return 2 * n ** 3 * iters / dt / 1e12


def _read_lines(path: str) -> list[str]:
    """Non-empty lines of a file; [] when unreadable."""
    try:
        with open(path) as f:
            return [line for line in f if line.strip()]
    except OSError:
        return []


def _probe_backend(timeout_s: float = 180.0) -> bool:
    """Bounded backend init: a wedged TPU tunnel makes jax.devices() hang
    for MINUTES-to-forever (killed TPU processes leave the tunnel
    unresponsive), which would turn the whole bench run into a silent
    hang with no artifact. Probe in a daemon thread; on timeout, force
    the CPU backend so the run still emits its JSON line (with an error
    note) instead of nothing."""
    import threading
    ok = threading.Event()
    done = threading.Event()

    def probe():
        try:
            import jax
            jax.devices()
            ok.set()
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True, name="backend-probe")
    t.start()
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if done.wait(1.0):
            # thread finished: either devices() worked, or it raised
            # promptly (no jax / plugin error) — fail FAST in that case,
            # don't burn the whole timeout on a non-hang
            return ok.is_set()
    print(f"# backend init exceeded {timeout_s:.0f}s (tunnel wedged?); "
          "falling back to CPU", file=sys.stderr, flush=True)
    return False


def _measure(step_fn, state, batch, steps: int, warmup: int,
             t_start: float) -> tuple[float, float, float]:
    """Run the step loop with hard host-fetch barriers. Returns
    (wall seconds for `steps`, startup→first-step seconds, last loss).

    Sync via host transfer (float()), not block_until_ready: on the
    tunneled axon platform block_until_ready returns before the compute
    finishes, which inflated throughput ~70x; a device->host fetch of the
    last step's loss is a hard barrier everywhere."""
    state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    first_step_s = time.perf_counter() - t_start
    for _ in range(warmup - 1):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    loss = float(metrics["loss"])
    return time.perf_counter() - t0, first_step_s, loss


def bench_resnet(fused: bool = False, t_start: float | None = None) -> dict:
    """ResNet-50 synthetic-ImageNet training throughput (the headline
    number). fused=True runs the opt-in ghost-BN fused-block variant
    (ops/fused_block_train.py) — same model FLOPs, fewer HBM bytes."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models import resnet as R
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    t_start = time.perf_counter() if t_start is None else t_start
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_chips = len(jax.devices())
    if on_tpu:
        # batch 128/chip measured fastest on v5e (128: ~2600, 256: ~2500,
        # 512: ~2360, 1024: ~2020 img/s) — the step is HBM-roofline-bound
        # (PERF.md), so larger batches only add activation traffic
        batch_per_chip, image_size, steps, warmup = 128, 224, 40, 4
    else:  # CPU smoke mode so the script stays runnable anywhere
        batch_per_chip, image_size, steps, warmup = 8, 64, 4, 1
    global_batch = batch_per_chip * n_chips

    mesh = build_mesh()
    model = R.resnet50(num_classes=1000)
    loss_fn = R.make_fused_loss_fn(model, mesh=mesh) if fused \
        else R.make_loss_fn(model)
    builder = TrainStepBuilder(
        mesh=mesh,
        loss_fn=loss_fn,
        optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                              optax.sgd(0.1, momentum=0.9)),
    )
    state = builder.init(R.init_fn(model, image_size=image_size),
                         jax.random.PRNGKey(0))
    step_fn = builder.build()
    batch = R.synthetic_batch(jax.random.PRNGKey(1), global_batch, image_size)
    if on_tpu:
        # feed bf16 images: the model's first act is the bf16 cast, so this
        # is loss-free and halves the input-image HBM read (PERF.md)
        batch["images"] = batch["images"].astype(jnp.bfloat16)
    batch = builder.place_batch(batch)

    dt, first_step_s, loss = _measure(step_fn, state, batch, steps, warmup,
                                      t_start)
    img_s_chip = global_batch * steps / dt / n_chips
    flops_per_chip = img_s_chip * TRAIN_GFLOP_PER_IMAGE * 1e9
    peak = detect_peak_tflops(dev)
    routing = None
    if fused:
        # record which kernel each block routed to — the artifact must
        # say what was actually measured. R.fused_block_routing shares
        # the decision function with fused_train_apply itself, so this
        # cannot drift from what ran; collapse to per-stage summaries
        # (unique routes in block order) for artifact size.
        per_block = R.fused_block_routing(depth=50, image_size=image_size)
        routing = {}
        for name, route in per_block.items():
            stage = name.split("_")[0]
            routes = routing.setdefault(stage, [])
            if route not in routes:
                routes.append(route)
    return {
        "metric": "resnet50_synthetic_imagenet_train_throughput" +
                  ("_fused" if fused else ""),
        "value": round(img_s_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 3),
        "mfu": round(flops_per_chip / (peak * 1e12), 3) if peak else None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "startup_first_step_s": round(first_step_s, 2),
            "peak_tflops_spec": peak,
            "model_tflops": round(flops_per_chip / 1e12, 1),
            "global_batch": global_batch,
            "loss": round(loss, 3),
            **({"fused_routing": routing} if routing else {}),
        },
        "_flops_per_chip": flops_per_chip,
    }


def bench_lm(t_start: float | None = None,
             long_context: bool = False) -> dict:
    """Transformer-LM training throughput: tokens/sec + MFU (bf16, flash
    attention, chip-filling batch). The compute-bound companion to the
    memory-bound ResNet number — its MFU is the honest utilization
    figure for the LLM parallelism stack (VERDICT r3 item 3).

    ``long_context`` stretches the sequence 8x at constant tokens/step
    (seq 8192 x batch 4 on TPU) — the single-chip vehicle for the flash
    kernel's long-sequence scaling (multi-chip ring attention is the
    dryrun's job; one chip has no sequence axis to shard)."""
    import jax
    import optax

    from kubeflow_tpu.models import transformer as T
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    t_start = time.perf_counter() if t_start is None else t_start
    import os
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_chips = len(jax.devices())
    if on_tpu:
        # ~217M-param LM (GPT-2-medium width at half its depth); 32k
        # tokens/step fills the chip (seq 1024 x batch 32/chip) without
        # breaching v5e HBM. head_dim 128 = the TPU lane width: head_dim
        # 64 lane-pads every attention buffer 2x (measured HBM OOM on
        # first chip contact) and halves flash-kernel MXU utilization.
        # KFTPU_LM_ATTENTION=einsum is the fallback for a flash Mosaic
        # compile going bad on first silicon contact (hack/tpu_session.sh
        # retries with it so SOME measured LM line still lands).
        cfg = T.TransformerConfig(
            vocab_size=32000, num_layers=12, embed_dim=1024, num_heads=8,
            head_dim=128, mlp_dim=4096,
            max_seq_len=8192 if long_context else 1024,
            attention=os.environ.get("KFTPU_LM_ATTENTION", "flash"))
        seq_len, batch_per_chip, steps, warmup = \
            (8192, 4, 10, 2) if long_context else (1024, 32, 20, 3)
    else:
        cfg = T.TransformerConfig.tiny()
        if long_context:
            import dataclasses
            cfg = dataclasses.replace(cfg, max_seq_len=512,
                                      attention="flash")
        seq_len, batch_per_chip, steps, warmup = \
            (512, 1, 2, 1) if long_context else (128, 4, 3, 1)
    global_batch = batch_per_chip * n_chips

    spec = T.workload_spec(cfg, seq_len=seq_len)
    builder = TrainStepBuilder(
        mesh=build_mesh(), loss_fn=spec.loss_fn,
        optimizer=optax.adamw(3e-4),
        rules=spec.rules, param_logical_axes=spec.param_logical_axes)
    state = builder.init(spec.init_fn, jax.random.PRNGKey(0))
    step_fn = builder.build()
    batch = builder.place_batch(
        spec.batch_fn(jax.random.PRNGKey(1), global_batch))

    dt, first_step_s, loss = _measure(step_fn, state, batch, steps, warmup,
                                      t_start)
    tok_s_chip = global_batch * seq_len * steps / dt / n_chips
    # 6P per token over MATMUL params only (fwd+bwd MACs): block
    # qkv/proj/mlp + the vocab head. The input embedding is a gather
    # (~0 matmul FLOPs), so it counts toward params but not MFU.
    d = cfg.embed_dim
    p_matmul = 12 * cfg.num_layers * d * d + cfg.vocab_size * d
    # causal attention touches only the lower triangle: half the full
    # 12·L·d·s score+value FLOPs (standard causal-LM accounting)
    attn = 6 * cfg.num_layers * (cfg.num_heads * cfg.head_dim) * seq_len
    flops_per_tok = 6 * p_matmul + attn
    params_total = p_matmul + cfg.vocab_size * d    # + embedding table
    flops_per_chip = tok_s_chip * flops_per_tok
    peak = detect_peak_tflops(dev)
    return {
        "metric": "transformer_lm_train_throughput" +
                  ("_long" if long_context else ""),
        "value": round(tok_s_chip, 0),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,   # first measured LM line IS the baseline
        "mfu": round(flops_per_chip / (peak * 1e12), 3) if peak else None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "startup_first_step_s": round(first_step_s, 2),
            "params_m": round(params_total / 1e6),
            "seq_len": seq_len,
            "global_batch": global_batch,
            "tokens_per_step": global_batch * seq_len,
            "model_tflops": round(flops_per_chip / 1e12, 1),
            "attention": cfg.attention,
            "loss": round(loss, 3),
        },
        "_flops_per_chip": flops_per_chip,
    }


def bench_serving(t_start: float | None = None) -> dict:
    """Model-server data-plane latency/throughput (the reference's E2E
    probes its TF-Serving deployment, testing/test_tf_serving.py:110;
    here it is a measured benchmark): resnet50 servable, cold first
    request vs warmed, p50/p99/throughput per batch bucket, REST and
    gRPC. REST carries JSON floats (wire cost grows ~20x over binary),
    so REST runs the small buckets and gRPC the full sweep — exactly how
    the reference splits traffic between its http-proxy and :9000."""
    import numpy as np

    import jax

    from kubeflow_tpu.serving.client import predict as http_predict
    from kubeflow_tpu.serving.http_server import ModelServer

    t_start = time.perf_counter() if t_start is None else t_start
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        depth, image_size, buckets, reqs = 50, 224, [1, 8, 32], 40
    else:  # CPU smoke mode
        depth, image_size, buckets, reqs = 18, 32, [1, 4], 6
    name = f"resnet{depth}"

    server = ModelServer(host="127.0.0.1", port=0,
                         max_batch=max(buckets))
    servable = server.repository.load(name, name, num_classes=1000,
                                      image_size=image_size)
    servable.max_batch = max(buckets)
    port = server.start()
    addr = f"127.0.0.1:{port}"
    rng = np.random.default_rng(0)

    def image_batch(n: int) -> np.ndarray:
        return rng.standard_normal(
            (n, image_size, image_size, 3)).astype(np.float32)

    # cold: the very first request pays the XLA compile (the serving
    # cold-start the warmup path exists to hide)
    t0 = time.perf_counter()
    http_predict(addr, name, image_batch(1).tolist(), timeout_s=600.0)
    cold_first_request_s = time.perf_counter() - t0
    startup_first_request_s = time.perf_counter() - t_start

    t0 = time.perf_counter()
    warmed = servable.warmup(buckets)
    warmup_s = time.perf_counter() - t0

    def percentiles(latencies: list[float], bucket: int) -> dict:
        lat = sorted(latencies)
        p50 = lat[len(lat) // 2]
        p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
        return {"p50_ms": round(p50 * 1e3, 2),
                "p99_ms": round(p99 * 1e3, 2),
                "throughput_img_s": round(bucket * len(lat) / sum(lat), 1)}

    rows: dict = {"rest": {}, "grpc": {}}
    # REST: the JSON body is serialized ONCE and posted raw each
    # iteration, so the loop times the wire + server, not the client
    # formatting ~megabytes of floats per request; bucket capped
    # (3 MB/image JSON at 224px)
    import urllib.request
    url = f"http://{addr}/v1/models/{name}:predict"
    for b in [x for x in buckets if x <= 8]:
        body = json.dumps({"instances": image_batch(b).tolist(),
                           "dtype": "float32"}).encode()
        lats = []
        for _ in range(reqs):
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=600.0) as resp:
                resp.read()
            lats.append(time.perf_counter() - t0)
        rows["rest"][f"batch{b}"] = percentiles(lats, b)

    gsrv = channel = None
    try:
        import grpc as grpc_mod

        from kubeflow_tpu.serving import tpu_serving_pb2 as pb
        from kubeflow_tpu.serving.grpc_server import (GrpcPredictServer,
                                                      ndarray_to_tensor,
                                                      predict_stub)
        gsrv = GrpcPredictServer(server, host="127.0.0.1", port=0)
        gport = gsrv.start()
        channel = grpc_mod.insecure_channel(f"127.0.0.1:{gport}")
        stub = predict_stub(channel)
        for b in buckets:
            req = pb.PredictRequest()
            req.model_spec.name = name
            req.inputs["instances"].CopyFrom(
                ndarray_to_tensor(image_batch(b)))
            lats = []
            for _ in range(reqs):
                t0 = time.perf_counter()
                stub["Predict"](req)
                lats.append(time.perf_counter() - t0)
            rows["grpc"][f"batch{b}"] = percentiles(lats, b)
    except Exception as e:  # noqa: BLE001 — REST rows must still land
        rows["grpc"] = {"error": f"{type(e).__name__}: {e}"}
    finally:
        if channel is not None:
            channel.close()
        if gsrv is not None:
            gsrv.stop()
    server.stop()

    # headline: best sustained device throughput (largest gRPC bucket;
    # REST bucket if gRPC unavailable)
    grpc_ok = isinstance(rows["grpc"], dict) and "error" not in rows["grpc"]
    best = (rows["grpc"] if grpc_ok else rows["rest"])
    top_bucket = sorted(best, key=lambda k: int(k[5:]))[-1]
    return {
        "metric": f"resnet{depth}_serving_throughput",
        "value": best[top_bucket]["throughput_img_s"],
        "unit": "images/sec",
        "vs_baseline": None,   # first measured serving line IS the baseline
        "mfu": None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "image_size": image_size,
            "cold_first_request_s": round(cold_first_request_s, 2),
            "startup_first_request_s": round(startup_first_request_s, 2),
            "warmup_s": round(warmup_s, 2),
            "warmed_buckets": warmed,
            "reqs_per_bucket": reqs,
            "latency": rows,
        },
        "_flops_per_chip": 0.0,
    }


def assemble_block_row(count: int, route_str: str, xla_s: float,
                       fused_s: float | None) -> tuple[dict, str, float]:
    """Fold one geometry's timings into its artifact row: returns
    (row, winner_route, winner_seconds). Pure — unit-tested so the
    routing table the TPU session publishes can't regress on logic."""
    row = {"count": count, "route_model": route_str,
           "xla_ms": round(xla_s * 1e3, 3)}
    if fused_s is not None:
        row["fused_ms"] = round(fused_s * 1e3, 3)
        row["fused_vs_xla"] = round(xla_s / fused_s, 3)
    winner_s = min(xla_s, fused_s) if fused_s is not None else xla_s
    winner = "xla" if winner_s == xla_s else route_str
    row["winner"] = winner
    return row, winner, winner_s


def publish_routing_table(routes: dict, path: str, meta: dict) -> None:
    """Atomically publish the measured routing table for
    KFTPU_FUSED_ROUTING_TABLE consumers: the directory is created (losing
    minutes of TPU microbench time to a missing bench-matrix/ in the cwd
    would be absurd) and a timeout mid-dump can't leave a truncated
    file."""
    import os
    out_dir = os.path.dirname(path)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({**meta, "routes": routes}, f, indent=1)
    os.replace(tmp, path)


def bench_fused_blocks(t_start: float | None = None,
                       routing_out: str | None = None) -> dict:
    """Per-block kernel attribution: for every distinct stride-1
    bottleneck geometry in resnet50 the fused path covers, time ONE
    block's train step (fwd+bwd via value_and_grad) under XLA vs the
    routed fused kernel, pick the measured winner, and (on TPU) write
    the winners as a routing table fused_train_apply consumes via
    KFTPU_FUSED_ROUTING_TABLE. The round-5 silicon session measured the
    end-to-end fused path at 0.53x XLA (PERF.md) — this mode answers
    WHICH kernels lose (and whether any win) in one tunnel window."""
    import os

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import resnet as R
    from kubeflow_tpu.ops.fused_block_train import fused_bottleneck_train
    from kubeflow_tpu.ops.fused_block_train_spatial import (
        fused_bottleneck_train_spatial)

    # the microbench REGENERATES the measured table, so it must route by
    # the VMEM model, not by a previously-measured table — otherwise a
    # stale "xla" entry is sticky forever (that geometry would never get
    # a fused measurement again)
    os.environ.pop("KFTPU_FUSED_ROUTING_TABLE", None)

    t_start = time.perf_counter() if t_start is None else t_start
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        batch, image_size, iters, warmup = 128, 224, 30, 3
    else:  # CPU smoke: tiny geometry, interpret-mode kernels
        batch, image_size, iters, warmup = 2, 32, 2, 1

    def time_block(fn, x, params) -> float:
        """Median-of-iters seconds for loss+grads of one block step."""
        def loss(p, xin):
            out, _stats = fn(xin, p)
            return jnp.mean(out.astype(jnp.float32))
        g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
        val, _ = g(params, x)
        float(val)                       # compile + hard barrier
        for _ in range(warmup):
            val, _ = g(params, x)
        float(val)
        t0 = time.perf_counter()
        for _ in range(iters):
            val, _ = g(params, x)
        float(val)
        return (time.perf_counter() - t0) / iters

    rows, routes = {}, {}
    xla_total = best_total = 0.0
    for geom in R.stride1_geometries(depth=50, image_size=image_size):
        h, cin, cmid, cout = (geom["h"], geom["cin"], geom["cmid"],
                              geom["cout"])
        params = R.random_block_params(jax.random.PRNGKey(0), cin, cmid,
                                       cout, geom["proj"])
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, h, h, cin),
                              jnp.bfloat16)
        xla_s = time_block(
            lambda xin, p: R._xla_block_train(xin, p, 1), x, params)
        kind, th = R._fused_route(h, h, cin, cmid, cout)
        route_str = kind + (f":{th}" if th is not None else "")
        fused_s = None
        if kind == "batch":
            fused_s = time_block(
                lambda xin, p: fused_bottleneck_train(xin, p), x, params)
        elif kind == "spatial":
            fused_s = time_block(
                lambda xin, p, _th=th: fused_bottleneck_train_spatial(
                    xin, p, tile_h=_th), x, params)
        row, winner, winner_s = assemble_block_row(
            geom["count"], route_str, xla_s, fused_s)
        rows[geom["key"]] = row
        routes[geom["key"]] = winner
        xla_total += xla_s * geom["count"]
        best_total += winner_s * geom["count"]

    # measured-routing estimate: stride-1 blocks are ~80% of step time
    # (PERF.md roofline), so the end-to-end bound is conservative
    speedup_blocks = xla_total / best_total if best_total else 1.0
    if routing_out and on_tpu:
        publish_routing_table(
            routes, routing_out,
            {"device_kind": getattr(dev, "device_kind", dev.platform),
             "batch": batch, "image_size": image_size})
    return {
        "metric": "resnet50_fused_block_microbench",
        "value": round(speedup_blocks, 3),
        "unit": "stride1_block_speedup_measured_routing_vs_xla",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "global_batch": batch,
            "image_size": image_size,
            "blocks": rows,
            "routing_table_written": bool(routing_out and on_tpu),
        },
        "_flops_per_chip": 0.0,
    }


def estimate_weight_update_hbm(param_elems: int, state_elems: int,
                               n_rep: int) -> dict:
    """Estimated per-chip HBM bytes ONE optimizer update moves (all f32):
    reads the reduced gradients + params + optimizer state, writes params
    + optimizer state — 4·(3P + 2S) bytes replicated. The ZeRO-2 sharded
    update touches a 1/N shard of each, so per-chip traffic is ~full/N
    (the all-gather's full-param write is the step's one remaining
    full-size HBM pass and is counted against BOTH paths by the final
    param write). Pure — unit-tested, and the A/B artifact row embeds it
    so the measured delta is always next to the modeled bound."""
    full = 4 * (3 * param_elems + 2 * state_elems)
    return {
        "param_elems": param_elems,
        "opt_state_elems": state_elems,
        "replicas": n_rep,
        "full_bytes_per_chip": full,
        "sharded_bytes_per_chip": -(-full // n_rep),
    }

def bench_weight_update(t_start: float | None = None) -> dict:
    """A/B the cross-replica sharded weight update (ZeRO-2, Xu et al.)
    against the replicated update on the headline ResNet-50 regime:
    same model, same data, same optimizer, weight_update flipped. Records
    per-step times for both paths, the loss delta (must be ≤1e-5 — the
    sharded path is numerics-identical), the compiled step's collective
    mix, and the modeled per-chip optimizer HBM bytes (full vs 1/N) so
    the measured delta lands next to the bound it is chasing (PERF.md
    "Weight-update sharding")."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models import resnet as R
    from kubeflow_tpu.parallel.mesh import build_mesh, replica_degree
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    t_start = time.perf_counter() if t_start is None else t_start
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_chips = len(jax.devices())
    if on_tpu:
        batch_per_chip, image_size, steps, warmup = 128, 224, 30, 3
    else:  # CPU smoke (same config bench_resnet smokes with)
        batch_per_chip, image_size, steps, warmup = 8, 64, 3, 1
    global_batch = batch_per_chip * n_chips

    mesh = build_mesh()
    n_rep = replica_degree(mesh)
    model = R.resnet50(num_classes=1000)
    loss_fn = R.make_loss_fn(model)
    batch = R.synthetic_batch(jax.random.PRNGKey(1), global_batch,
                              image_size)
    if on_tpu:
        batch["images"] = batch["images"].astype(jnp.bfloat16)

    ab: dict = {}
    hbm = None
    for mode in ("replicated", "sharded"):
        builder = TrainStepBuilder(
            mesh=mesh,
            loss_fn=loss_fn,
            optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                                  optax.sgd(0.1, momentum=0.9)),
            weight_update=mode,
        )
        state = builder.init(R.init_fn(model, image_size=image_size),
                             jax.random.PRNGKey(0))
        if hbm is None:
            hbm = estimate_weight_update_hbm(
                sum(int(l.size) for l in jax.tree.leaves(state.params)),
                sum(int(getattr(l, "size", 0))
                    for l in jax.tree.leaves(state.opt_state)),
                n_rep)
        step_fn = builder.build()
        placed = builder.place_batch(batch)
        # resnet carries BN batch_stats, so the sharded path reports
        # zero2-gspmd (global-batch BN preserved; update_strategy)
        row = {"strategy": builder.update_strategy(state.variables)}
        if mode == "sharded":
            # AOT-compile once: the same executable yields the HLO for the
            # collective counts AND runs the measured loop (calling the
            # jitted fn after lower() would re-trace and pay a second
            # full XLA compile — minutes on TPU)
            step_fn = step_fn.lower(state, placed).compile()
            row["collectives"] = collective_counts(step_fn.as_text())
        dt, _first, loss = _measure(step_fn, state, placed, steps, warmup,
                                    time.perf_counter())
        row["step_ms"] = round(dt / steps * 1e3, 3)
        row["loss"] = loss
        ab[mode] = row

    loss_delta = abs(ab["replicated"]["loss"] - ab["sharded"]["loss"])
    for row in ab.values():
        row["loss"] = round(row.pop("loss"), 5)
    speedup = ab["replicated"]["step_ms"] / ab["sharded"]["step_ms"] \
        if ab["sharded"]["step_ms"] else 1.0
    return {
        "metric": "resnet50_weight_update_ab",
        "value": round(speedup, 3),
        "unit": "replicated_step_time_over_sharded",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "global_batch": global_batch,
            "weight_update": {
                **ab,
                "replicas": n_rep,
                "loss_delta": round(loss_delta, 8),
                "optimizer_hbm_bytes_per_chip": hbm,
            },
        },
        "_flops_per_chip": 0.0,
    }


def bench_kernels(t_start: float | None = None) -> dict:
    """Raw-speed kernel tier A/B (ISSUE 16): each optimized rung against
    the stock path it replaces, on the same model, same data, same seed.

    - attention: einsum vs the flash Pallas kernel (transformer LM,
      tokens/sec + MFU per arm; first-step loss parity ≤1e-5 — same
      params, so the delta is pure attention numerics).
    - optimizer: the stock optax adam chain vs the fused-Adam Pallas
      update, both through the zero2-explicit sharded weight update
      (pure-DP mesh, replicated params); parity = max |param delta|
      after the measured steps ≤1e-5.
    - serving: the int8 tier's measured accuracy delta on the LM
      servable, plus the gate-refusal drill — the within-channel-
      outlier toy MUST be refused at max_delta=0.01 with its delta
      ledgered (a gate that cannot refuse is not a gate).

    Off-TPU the Pallas kernels run interpret=True: the parity numbers
    are real (same computation graph the TPU tiles execute), the
    tokens/sec are NOT silicon numbers (extras.interpret records this;
    the TPU-measured table lands in PERF.md with the nightly matrix)."""
    import dataclasses
    import os
    import subprocess

    import jax

    t_start = time.perf_counter() if t_start is None else t_start
    if jax.devices()[0].platform == "cpu" and len(jax.devices()) < 8 \
            and not os.environ.get("KFTPU_BENCH_KERNELS_CHILD"):
        # the zero2-explicit optimizer arm needs the 8-virtual-device
        # data mesh; the flag must be set before jax initializes →
        # re-exec (the bench_comm pattern)
        env = {**os.environ, "KFTPU_BENCH_KERNELS_CHILD": "1",
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                             " --xla_force_host_platform_device_count=8")}
        res = subprocess.run([sys.executable, __file__, "--mode",
                              "kernels"], env=env, capture_output=True,
                             text=True, timeout=900)
        for line in reversed(res.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                row = json.loads(line)
                row["_flops_per_chip"] = 0.0
                return row
        raise RuntimeError("kernels bench child emitted no JSON row "
                           f"(rc={res.returncode}): {res.stderr[-2000:]}")

    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models import transformer as T
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.runtime.recipe import make_optimizer
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_chips = len(jax.devices())
    if on_tpu:
        seq_len, batch_per_chip, steps, warmup = 1024, 8, 10, 2
        base_cfg = T.TransformerConfig(
            vocab_size=32000, num_layers=12, embed_dim=1024, num_heads=8,
            head_dim=128, mlp_dim=4096, max_seq_len=1024)
    else:
        seq_len, batch_per_chip, steps, warmup = 128, 4, 3, 1
        base_cfg = T.TransformerConfig.tiny()
    # f32 both arms: the A/B gates on ≤1e-5 parity, and bf16 rounding of
    # the attention output would swamp that long before kernel numerics
    base_cfg = dataclasses.replace(base_cfg, dtype=jnp.float32)
    global_batch = batch_per_chip * n_chips
    mesh = build_mesh()

    def run_arm(cfg, optimizer, weight_update="replicated"):
        """Measured loop that KEEPS the final state (parity needs the
        params; _measure hands back only the loss)."""
        spec = T.workload_spec(cfg, seq_len=seq_len)
        builder = TrainStepBuilder(mesh=mesh, loss_fn=spec.loss_fn,
                                   optimizer=optimizer,
                                   weight_update=weight_update)
        state = builder.init(spec.init_fn, jax.random.PRNGKey(0))
        step_fn = builder.build()
        batch = builder.place_batch(
            spec.batch_fn(jax.random.PRNGKey(1), global_batch))
        losses = []
        state, metrics = step_fn(state, batch)          # compile + step 1
        losses.append(float(metrics["loss"]))
        for _ in range(warmup - 1):
            state, metrics = step_fn(state, batch)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))           # hard barrier
        dt = time.perf_counter() - t0
        return dt, losses, state, builder.update_strategy()

    # MFU accounting per bench_lm: 6P per token over matmul params, plus
    # the causal half of the attention score+value FLOPs
    d = base_cfg.embed_dim
    p_matmul = 12 * base_cfg.num_layers * d * d + base_cfg.vocab_size * d
    attn = 6 * base_cfg.num_layers * \
        (base_cfg.num_heads * base_cfg.head_dim) * seq_len
    flops_per_tok = 6 * p_matmul + attn
    peak = detect_peak_tflops(dev)

    def rung_row(dt):
        tok_s_chip = global_batch * seq_len * steps / dt / n_chips
        fpc = tok_s_chip * flops_per_tok
        return {"tokens_per_sec_chip": round(tok_s_chip, 1),
                "mfu": round(fpc / (peak * 1e12), 4) if peak else None}, fpc

    # ---- attention rung: einsum vs flash ---------------------------------
    import optax
    attention_ab = {}
    flash_fpc = 0.0
    for attn_kind in ("einsum", "flash"):
        cfg = dataclasses.replace(base_cfg, attention=attn_kind)
        dt, losses, _state, _ = run_arm(cfg, optax.adam(3e-4))
        row, fpc = rung_row(dt)
        row.update(step_ms=round(dt / steps * 1e3, 2),
                   first_loss=losses[0], last_loss=round(losses[-1], 5))
        attention_ab[attn_kind] = row
        if attn_kind == "flash":
            flash_fpc = fpc
    attn_parity = abs(attention_ab["flash"].pop("first_loss") -
                      attention_ab["einsum"].pop("first_loss"))
    assert attn_parity <= 1e-5, \
        f"flash first-step loss parity {attn_parity} > 1e-5"
    attention_ab["loss_delta_step1"] = round(attn_parity, 9)

    # ---- optimizer rung: stock adam vs fused_adam, zero2-explicit --------
    optimizer_ab = {}
    final_params = {}
    for tier in ("stock", "fused_adam"):
        opt, _sched = make_optimizer("adam", 1e-3, weight_decay=1e-4,
                                     kernels=tier)
        cfg = dataclasses.replace(base_cfg, attention="einsum")
        dt, losses, state, strategy = run_arm(cfg, opt,
                                              weight_update="sharded")
        row, _ = rung_row(dt)
        row.update(step_ms=round(dt / steps * 1e3, 2),
                   last_loss=round(losses[-1], 5), strategy=strategy)
        optimizer_ab[tier] = row
        final_params[tier] = jax.device_get(state.params)
    param_delta = max(
        float(np.max(np.abs(np.asarray(a, np.float32) -
                            np.asarray(b, np.float32))))
        for a, b in zip(jax.tree.leaves(final_params["stock"]),
                        jax.tree.leaves(final_params["fused_adam"])))
    assert param_delta <= 1e-5, \
        f"fused_adam param parity {param_delta} > 1e-5 after {steps} steps"
    optimizer_ab["param_delta"] = round(param_delta, 9)
    fused_speedup = optimizer_ab["stock"]["step_ms"] / \
        optimizer_ab["fused_adam"]["step_ms"] \
        if optimizer_ab["fused_adam"]["step_ms"] else 1.0

    # ---- serving rung: int8 behind the parity gate -----------------------
    from kubeflow_tpu.serving.servable import (ModelRepository,
                                               QuantizationRefused,
                                               Servable, quantize_servable)
    repo = ModelRepository()
    # random-weights smoke model: near-tied logits make the argmax
    # delta a few percent, honestly measured — the explicit 0.05 gate
    # admits it; the MUST-REFUSE drill below pins the gate's teeth
    lm = repo.load("lm", "transformer_lm", kernels="int8",
                   quant_max_delta=0.05,
                   vocab_size=256, embed_dim=32, num_heads=2, head_dim=16,
                   num_layers=1, mlp_dim=64, max_seq_len=16,
                   dtype=jnp.float32)
    serving = {"accuracy_delta": lm.quant["accuracy_delta"],
               "max_delta": lm.quant["max_delta"],
               "weight_bytes_float": lm.quant["weight_bytes_float"],
               "weight_bytes_int8": lm.quant["weight_bytes_int8"]}
    # gate-refusal drill: per-channel absmax survives cross-channel
    # range, so the must-refuse toy plants the outlier INSIDE a decisive
    # channel — int8 resolution (~0.79) swallows its 0.3-margin rows
    W = np.zeros((8, 3), np.float32)
    W[7, 1] = 100.0
    W[0, 1] = 0.3
    W[0, 2] = 0.2
    W[7, 2] = 0.1
    toy = Servable(
        name="gate-toy",
        predict_fn=lambda p, x: {"logits": x @ p["w"],
                                 "classes": jnp.argmax(x @ p["w"], -1)},
        params={"w": jnp.asarray(W)},
        input_signature={"inputs": {"shape": [-1, 8], "dtype": "float32"}})
    try:
        quantize_servable(toy, calibration=[np.eye(8, dtype=np.float32)],
                          max_delta=0.01)
        refused, refused_delta = False, None
    except QuantizationRefused as e:
        refused, refused_delta = True, getattr(e, "delta", None)
    assert refused, "the int8 parity gate failed to refuse the " \
        "past-threshold model — a gate that cannot refuse is not a gate"
    serving["gate_refusal_drill"] = {
        "refused": refused,
        "measured_delta": refused_delta,
        "max_delta": 0.01,
    }

    return {
        "metric": "kernel_tier_ab",
        "value": round(fused_speedup, 3),
        "unit": "stock_adam_step_time_over_fused",
        "vs_baseline": None,
        "mfu": attention_ab["flash"]["mfu"],
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            # interpret-mode Pallas: parity real, rates NOT silicon
            "interpret": not on_tpu,
            "seq_len": seq_len,
            "global_batch": global_batch,
            "steps": steps,
            "attention": attention_ab,
            "optimizer": optimizer_ab,
            "serving_int8": serving,
        },
        "_flops_per_chip": flash_fpc,
    }


def bench_comm(t_start: float | None = None) -> dict:
    """Communication observability (ISSUE 13): the DCN bytes/step
    yardstick on the 2-slice DCN CPU mesh (the test_distributed.py dcn
    topology — two v5e-4 slices, data axis across the modeled DCN
    boundary), across the weight-update modes, plus the full-reshard
    detector's positive/negative drill.

    Arms (each compiled AOT, the HLO analyzed by obs/collectives.py):

    - ``replicated`` / ``zero2-explicit`` / ``zero2-gspmd``: the pure-DP
      transformer on the 2-slice contract mesh, weight-update mode
      flipped (KFTPU_BENCH_COMM_MODES trims the list for smoke runs).
      Asserted: the detector passes (no involuntary reshard), DCN
      traffic is present, and the zero2 arms' modeled optimizer-update
      DCN bytes are STRICTLY below the replicated arm's. (Total wire
      bytes are conserved — RS+AG ≡ AR — so the totals columns are
      recorded beside the update metric; docs/operations.md.)
    - ``known-bad`` / ``known-bad-legacy``: the dryrun's 4th config
      (data=2 x fsdp=2 x tensor=2, rules-sharded params), whose SPMD
      compile used to log the "involuntary full rematerialization"
      warning (MULTICHIP_r05). ISSUE 15 rung 1 (DCN-aware rules)
      killed it: the fixed arm must compile CLEAN with strictly fewer
      DCN bytes/step than the legacy arm, which recompiles the pre-fix
      layout (dcn_aware=False) as the live positive control the
      detector still must FLAG.
    - ``single-slice``: the same pure-DP model on a 1-slice mesh.
      Asserted: zero DCN bytes, detector clean.

    The per-arm table (DCN/ICI bytes per step, collectives per link,
    modeled update bytes) is the baseline the MPMD-pipeline PR and the
    kill-the-involuntary-remat fix will be judged against (PERF.md
    "Communication observability")."""
    import os
    import subprocess

    t_start = time.perf_counter() if t_start is None else t_start
    import jax

    if jax.devices()[0].platform == "cpu" and len(jax.devices()) < 8 \
            and not os.environ.get("KFTPU_BENCH_COMM_CHILD"):
        # the 2-slice mesh needs 8 virtual devices; the flag must be set
        # before jax initializes → re-exec (the bench_input pattern)
        env = {**os.environ, "KFTPU_BENCH_COMM_CHILD": "1",
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                             " --xla_force_host_platform_device_count=8")}
        res = subprocess.run([sys.executable, __file__, "--mode", "comm"],
                             env=env, capture_output=True, text=True,
                             timeout=900)
        for line in reversed(res.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                row = json.loads(line)
                row["_flops_per_chip"] = 0.0
                return row
        raise RuntimeError("comm bench child emitted no JSON row "
                           f"(rc={res.returncode}): {res.stderr[-2000:]}")

    import optax

    from kubeflow_tpu.api.topology import TopologyContract, parse_topology
    from kubeflow_tpu.api.trainingjob import ShardingSpec
    from kubeflow_tpu.models import transformer as T
    from kubeflow_tpu.obs.collectives import (analyze_hlo,
                                              detect_full_reshard,
                                              modeled_update_dcn_bytes,
                                              slice_assignment)
    from kubeflow_tpu.parallel.mesh import build_mesh, mesh_from_contract
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    dev = jax.devices()[0]
    n_dev = len(jax.devices())
    chips_per_slice = n_dev // 2
    contract = TopologyContract(
        coordinator_address="bench:8476", num_processes=2, process_id=0,
        slice_topology=parse_topology(f"v5e-{chips_per_slice}"),
        num_slices=2, slice_id=0)
    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=2, embed_dim=64, num_heads=4,
        head_dim=16, mlp_dim=128, max_seq_len=64)
    spec = T.workload_spec(cfg=cfg, seq_len=64)

    def compile_arm(mesh, weight_update="replicated", rules=False,
                    num_slices=2, dcn_aware=True):
        builder = TrainStepBuilder(
            mesh=mesh, loss_fn=spec.loss_fn,
            optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                                  optax.adamw(1e-3)),
            rules=spec.rules if rules else None,
            param_logical_axes=spec.param_logical_axes if rules else None,
            weight_update=weight_update, num_slices=num_slices,
            dcn_aware=dcn_aware)
        state = builder.init(spec.init_fn, jax.random.PRNGKey(0))
        batch = builder.place_batch(
            spec.batch_fn(jax.random.PRNGKey(1), 2 * n_dev))
        return builder.build().lower(state, batch).compile().as_text()

    def profile_arm(hlo, mesh, num_slices):
        prof = analyze_hlo(
            hlo, slice_assignment(mesh, num_slices),
            mesh_axes=[(a, int(mesh.shape[a])) for a in mesh.axis_names])
        verdict = detect_full_reshard(prof)
        update = modeled_update_dcn_bytes(prof, hlo)
        return prof, {
            "dcn_bytes_per_step": round(prof.dcn_bytes_per_step),
            "ici_bytes_per_step": round(prof.ici_bytes_per_step),
            "dcn_collectives": prof.collectives("dcn"),
            "ici_collectives": prof.collectives("ici"),
            "modeled_dcn_ms": round(prof.modeled_dcn_seconds * 1e3, 3),
            "update_style": update["style"],
            "update_dcn_bytes": round(update["bytes"]),
            "dcn_full_reshard": verdict.flagged,
        }

    mesh_dp = mesh_from_contract(contract, ShardingSpec(data=n_dev))
    arms: dict = {}
    wanted = [m.strip() for m in os.environ.get(
        "KFTPU_BENCH_COMM_MODES",
        "replicated,zero2-explicit,zero2-gspmd").split(",") if m.strip()]
    arm_builders = {
        "replicated": lambda: compile_arm(mesh_dp, "replicated"),
        "zero2-explicit": lambda: compile_arm(mesh_dp, "sharded"),
        # trivial rules on the pure-DP mesh force the GSPMD strategy
        # while params stay effectively replicated — same comparison
        # basis as the explicit arm
        "zero2-gspmd": lambda: compile_arm(mesh_dp, "sharded",
                                           rules=True),
    }
    for mode in wanted:
        hlo = arm_builders[mode]()
        _, arms[mode] = profile_arm(hlo, mesh_dp, num_slices=2)
        assert not arms[mode]["dcn_full_reshard"], \
            f"detector false-positive on clean arm {mode}: {arms[mode]}"
        assert arms[mode]["dcn_bytes_per_step"] > 0, \
            f"2-slice arm {mode} shows no DCN traffic: {arms[mode]}"

    # the zero2 arms must model STRICTLY fewer optimizer-update DCN
    # bytes than replicated (the broadcast redundancy the sharded
    # update removes; totals are conserved and recorded beside it)
    if "replicated" in arms:
        for mode in wanted:
            if mode == "replicated":
                continue
            assert arms[mode]["update_dcn_bytes"] < \
                arms["replicated"]["update_dcn_bytes"], \
                f"{mode} update bytes not below replicated: {arms}"

    # the (formerly) known-bad config (MULTICHIP_r05: involuntary full
    # remat). ISSUE 15 rung 1 killed the reshard — the DCN-aware rules
    # (parallel/sharding_rules.py dcn_aware) replicate the tok_embed
    # table's gather-indexed vocab dim on multi-slice meshes, so the
    # SAME sharding spec now compiles CLEAN with strictly fewer DCN
    # bytes/step. The legacy arm (dcn_aware=False) recompiles the
    # pre-fix layout as the live positive control: the detector's
    # true-positive drill stays pinned against a REAL compiled program,
    # and the byte delta is measured, not remembered.
    mesh_bad = mesh_from_contract(
        contract, ShardingSpec(data=2, fsdp=chips_per_slice // 2,
                               tensor=2))
    hlo_legacy = compile_arm(mesh_bad, "replicated", rules=True,
                             dcn_aware=False)
    _, legacy = profile_arm(hlo_legacy, mesh_bad, num_slices=2)
    arms["known-bad-legacy"] = legacy
    assert legacy["dcn_full_reshard"], \
        f"detector missed the legacy known-bad DCN config: {legacy}"

    hlo_bad = compile_arm(mesh_bad, "replicated", rules=True)
    _, bad = profile_arm(hlo_bad, mesh_bad, num_slices=2)
    arms["known-bad"] = bad
    assert not bad["dcn_full_reshard"], \
        f"DCN-aware rules did not kill the involuntary reshard: {bad}"
    assert bad["dcn_bytes_per_step"] < legacy["dcn_bytes_per_step"], \
        f"fixed arm not strictly below the legacy reshard bytes: " \
        f"{bad} vs {legacy}"

    # single-slice control: everything is ICI, detector clean
    mesh_one = build_mesh(ShardingSpec(data=n_dev))
    hlo_one = compile_arm(mesh_one, "replicated", num_slices=1)
    _, one = profile_arm(hlo_one, mesh_one, num_slices=1)
    arms["single-slice"] = one
    assert one["dcn_bytes_per_step"] == 0 and \
        not one["dcn_full_reshard"], \
        f"single-slice arm shows DCN traffic or a flag: {one}"

    # headline = the replicated 2-slice arm, or (when the modes knob
    # trimmed it) the first 2-slice arm that DID run — the unit string
    # names whichever arm the number came from, so a trimmed smoke run
    # can never record the single-slice zero under a replicated label
    headline_arm = "replicated" if "replicated" in arms else \
        (wanted[0] if wanted else "known-bad")
    return {
        "metric": "comm_dcn_bytes_per_step",
        "value": arms[headline_arm]["dcn_bytes_per_step"],
        "unit": f"modeled_dcn_bytes_per_step_{headline_arm}_2slice",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "devices": n_dev,
            "slices": 2,
            "comm": arms,
            "detector": {
                "flags_legacy_known_bad": legacy["dcn_full_reshard"],
                "fixed_arm_clean": not bad["dcn_full_reshard"],
                "fixed_below_legacy_dcn_bytes":
                    bad["dcn_bytes_per_step"] <
                    legacy["dcn_bytes_per_step"],
                "clean_arms_pass": True,
            },
            "startup_first_step_s": round(
                time.perf_counter() - t_start, 2),
        },
        "_flops_per_chip": 0.0,
    }


def bench_multislice(t_start: float | None = None) -> dict:
    """MPMD pipeline-over-DCN (ISSUE 15 rung 2): parity, scaling, and
    bubble accounting for the one-program-per-slice path
    (parallel/multislice.py) against the single-program DCN mesh.

    Arms (8 virtual CPU devices, slices emulated as contiguous 2- or
    4-device groups — stated caveat: emulated slices share host cores,
    so MEASURED serial wall does not scale; the schedule MODEL's
    makespan from measured per-op durations is the honest parallel
    number, and both are recorded):

    - **parity**: the MPMD 2-stage pipeline vs the single-program
      plain-scan DP arm, identical init rng + batch stream + optimizer
      (engine cross-stage global-norm clip == optax
      clip_by_global_norm), f32 compute. Asserted: loss trajectory
      matches to <= 1e-5 at fixed global batch.
    - **ladder**: 1 → 2 → 4 slices (KFTPU_BENCH_MS_SLICES), fixed
      global batch: modeled tokens/sec (tokens / 1F1B makespan),
      measured serial tokens/sec, scaling efficiency
      (modeled_tput_S / (S x modeled_tput_1)), measured bubble
      fraction vs the (S-1)/(M+S-1) ideal, explicit DCN bytes/step.
    - **vs single-program**: the 2-slice GSPMD DP arm's modeled HLO
      DCN bytes/step (obs/collectives.py) beside the MPMD arm's
      measured explicit-transfer bytes — the PR 13 yardstick applied
      to the new path.
    - **goodput**: the WORKER-integrated path (train() with
      multislice_pipeline over KFTPU_NUM_SLICES=2) streams window +
      pipeline-bubble spans to a sink; the ledger must include a
      nonzero ``pipeline_bubble`` badput category and still sum to
      wall-clock within 2% (obs/goodput.py).
    """
    import os
    import subprocess
    import tempfile

    t_start = time.perf_counter() if t_start is None else t_start
    import jax

    if jax.devices()[0].platform == "cpu" and len(jax.devices()) < 8 \
            and not os.environ.get("KFTPU_BENCH_MS_CHILD"):
        env = {**os.environ, "KFTPU_BENCH_MS_CHILD": "1",
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                             " --xla_force_host_platform_device_count=8")}
        res = subprocess.run([sys.executable, __file__, "--mode",
                              "multislice"],
                             env=env, capture_output=True, text=True,
                             timeout=1800)
        for line in reversed(res.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                row = json.loads(line)
                row["_flops_per_chip"] = 0.0
                return row
        raise RuntimeError("multislice bench child emitted no JSON row "
                           f"(rc={res.returncode}): {res.stderr[-2000:]}")

    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.api.topology import TopologyContract, parse_topology
    from kubeflow_tpu.api.trainingjob import ShardingSpec
    from kubeflow_tpu.models import transformer as T
    from kubeflow_tpu.obs import goodput as gp
    from kubeflow_tpu.obs.collectives import (analyze_hlo,
                                              slice_assignment)
    from kubeflow_tpu.obs.trace import load_spans
    from kubeflow_tpu.parallel.mesh import build_mesh, mesh_from_contract
    from kubeflow_tpu.parallel.multislice import MPMDPipeline, stage_meshes
    from kubeflow_tpu.runtime.trainstep import (MultisliceTrainStepBuilder,
                                                TrainStepBuilder)

    dev = jax.devices()[0]
    n_dev = len(jax.devices())
    steps = _env_int("KFTPU_BENCH_MS_STEPS", 3)
    seq_len = 64
    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=4, embed_dim=64, num_heads=4,
        head_dim=16, mlp_dim=128, max_seq_len=seq_len,
        dtype=jnp.float32)   # f32: the <=1e-5 parity bar is exact math,
    #                          not bf16 re-chunking roundoff
    spec = T.pipelined_workload_spec(cfg=cfg, seq_len=seq_len, mesh=None)
    global_batch = 16
    batches = [spec.batch_fn(jax.random.PRNGKey(100 + i), global_batch)
               for i in range(steps)]

    # ---- parity: MPMD 2-stage vs single-program plain-scan DP ----------
    ref = TrainStepBuilder(
        mesh=build_mesh(ShardingSpec(data=n_dev)), loss_fn=spec.loss_fn,
        optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                              optax.adamw(1e-3)))
    state_r = ref.init(spec.init_fn, jax.random.PRNGKey(0))
    step_r = ref.build()
    losses_ref = []
    for b in batches:
        state_r, m = step_r(state_r, ref.place_batch(b))
        losses_ref.append(float(m["loss"]))

    ms2 = MultisliceTrainStepBuilder(
        cfg=cfg, num_slices=2, num_microbatches=4,
        optimizer=optax.adamw(1e-3), grad_clip_norm=1.0)
    state_m = ms2.init(spec.init_fn, jax.random.PRNGKey(0))
    step_m = ms2.build()
    losses_ms = []
    for b in batches:
        state_m, m = step_m(state_m, ms2.place_batch(b))
        losses_ms.append(float(m["loss"]))
    parity_delta = max(abs(a - b) for a, b in zip(losses_ref, losses_ms))
    assert parity_delta <= 1e-5, \
        f"MPMD parity broke: {losses_ref} vs {losses_ms}"

    # ---- ladder: 1 -> 2 -> 4 slices at fixed global batch -------------
    wanted = [int(s) for s in os.environ.get(
        "KFTPU_BENCH_MS_SLICES", "1,2,4").split(",") if s.strip()]
    chips_per = 2   # a slice = 2 emulated chips; 4 slices fit 8 devices
    micro = 8       # mb=2 rows divides the 2-chip data axis
    init_fn, embed_fn, block_fn, head_loss_fn = T.multislice_stage_fns(cfg)
    ladder = {}
    tokens_per_step = global_batch * seq_len
    for S in wanted:
        engine = MPMDPipeline(
            meshes=stage_meshes(jax.devices()[:S * chips_per], S),
            embed_fn=embed_fn, block_fn=block_fn,
            head_loss_fn=head_loss_fn, optimizer=optax.adamw(1e-3),
            num_microbatches=micro, grad_clip_norm=1.0)
        st = engine.init(lambda r: init_fn(r, seq_len),
                         jax.random.PRNGKey(0))
        last = None
        for i, b in enumerate(batches):
            st, _ = engine.step(st, engine.place_batch(b))
            if i:   # skip the compile step; keep the best-of-rest
                rep = engine.last_report
                if last is None or rep.makespan_s < last.makespan_s:
                    last = rep
        rep = last if last is not None else engine.last_report
        ladder[S] = {
            "modeled_tokens_per_s": round(
                tokens_per_step / rep.makespan_s, 1)
            if rep.makespan_s else None,
            "measured_serial_tokens_per_s": round(
                tokens_per_step / rep.serial_wall_s, 1)
            if rep.serial_wall_s else None,
            "bubble_fraction": round(rep.bubble_fraction, 4),
            "ideal_bubble_fraction": rep.to_dict()[
                "idealBubbleFraction"],
            "dcn_bytes_per_step": rep.dcn_bytes,
            "dcn_transfers_per_step": rep.dcn_transfers,
        }
    eff = {}
    if 1 in ladder and ladder[1]["modeled_tokens_per_s"]:
        base = ladder[1]["modeled_tokens_per_s"]
        for S in wanted:
            if S == 1 or not ladder.get(S, {}).get(
                    "modeled_tokens_per_s"):
                continue
            eff[str(S)] = round(
                ladder[S]["modeled_tokens_per_s"] / (S * base), 4)
            ladder[S]["scaling_efficiency_modeled"] = eff[str(S)]

    # ---- vs the single-program DCN mesh (the PR 13 yardstick) ----------
    contract = TopologyContract(
        coordinator_address="bench:8476", num_processes=2, process_id=0,
        slice_topology=parse_topology(f"v5e-{n_dev // 2}"),
        num_slices=2, slice_id=0)
    mesh_sp = mesh_from_contract(contract, ShardingSpec(data=n_dev))
    sp = TrainStepBuilder(mesh=mesh_sp, loss_fn=spec.loss_fn,
                          optimizer=optax.adamw(1e-3), num_slices=2)
    st_sp = sp.init(spec.init_fn, jax.random.PRNGKey(0))
    b_sp = sp.place_batch(batches[0])
    hlo_sp = sp.build().lower(st_sp, b_sp).compile().as_text()
    prof_sp = analyze_hlo(
        hlo_sp, slice_assignment(mesh_sp, 2),
        mesh_axes=[(a, int(mesh_sp.shape[a]))
                   for a in mesh_sp.axis_names])
    single_program = {
        "modeled_dcn_bytes_per_step": round(prof_sp.dcn_bytes_per_step),
        "dcn_collectives": prof_sp.collectives("dcn"),
    }

    # ---- worker-integrated goodput drill -------------------------------
    from kubeflow_tpu.runtime.worker import train
    with tempfile.TemporaryDirectory() as td:
        sink = os.path.join(td, "spans.jsonl")
        saved = {k: os.environ.get(k)
                 for k in ("KFTPU_NUM_SLICES",)}
        os.environ["KFTPU_NUM_SLICES"] = "2"
        try:
            result = train(
                workload="transformer-pipelined", steps=6,
                global_batch=32, sync_every=2, span_path=sink,
                multislice_pipeline=True, handle_sigterm=False,
                checkpoint_dir=None)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        ledger = gp.decompose(load_spans(sink))
    bubble_s = ledger["badputSeconds"][gp.BADPUT_PIPELINE_BUBBLE]
    assert bubble_s > 0, \
        f"no pipeline_bubble badput in the worker ledger: {ledger}"
    assert gp.categories_sum_ok(ledger), \
        f"ledger categories do not sum to wall-clock: {ledger}"
    goodput = {
        "worker_steps": result.steps,
        "ledger_wall_s": ledger["wallSeconds"],
        "pipeline_bubble_s": round(bubble_s, 4),
        "categories_sum_ok": True,
    }

    headline = eff.get("2")
    return {
        "metric": "multislice_scaling_efficiency_2slice_modeled",
        "value": headline,
        "unit": "modeled_tput_2slice / (2 x modeled_tput_1slice); "
                "CPU-emulated slices, schedule-model number",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "devices": n_dev,
            "parity": {
                "max_loss_delta": parity_delta,
                "steps": steps,
                "losses_single_program": losses_ref,
                "losses_mpmd": losses_ms,
            },
            "ladder": {str(k): v for k, v in sorted(ladder.items())},
            "scaling_efficiency_modeled": eff,
            "single_program_dcn_mesh": single_program,
            "goodput": goodput,
            "caveat": "CPU emulation: slices share host cores, so "
                      "measured serial wall does not scale; the "
                      "schedule model (measured per-op durations on "
                      "the 1F1B grid) is the parallel number",
            "startup_first_step_s": round(
                time.perf_counter() - t_start, 2),
        },
        "_flops_per_chip": 0.0,
    }


def _env_int(name: str, default: int) -> int:
    """Strict like the worker's env parsing: a typo'd knob must fail
    loudly, not silently run the bench at the default."""
    import os
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return int(v)   # ValueError names the offending value


def bench_input(t_start: float | None = None) -> dict:
    """Input-pipeline microbench: per-stage rates (record read,
    decode+augment, sharded H2D, the multi-process augment ring) and the
    serial-vs-overlapped A/B (PERF.md "Overlapped input pipeline").

    Both arms consume the SAME records at the same batch/geometry and
    pace each step with a fixed simulated device-step budget — a timed
    wait, because a real TPU computes without spending host CPU, and on
    the CPU mesh a jitted step would burn the very cores the input
    stages are being measured on (the A/B would then measure host-CPU
    contention, not pipeline architecture). The serial arm runs every
    stage on the critical path with a hard per-step barrier (the
    pre-pipeline worker loop); the overlapped arm is the shipped path:
    augment worker processes over the shared-memory ring
    (data/mp_augment.py) + double-buffered device placement
    (data/device_prefetch.py), synced only at window edges.

    Both arms pin KFTPU_AUGMENT_IMPL=py on CPU hosts: the native augment
    kernel is itself multi-threaded in-process, which would conflate
    kernel-level parallelism with pipeline architecture on a small host
    (on TPU hosts the default native kernel runs in both arms).

    On a CPU backend with fewer than 8 devices the measurement re-execs
    itself with the 8-device host-platform flag so the H2D stage
    exercises the worker's real data-sharded placement."""
    import os
    import shutil
    import subprocess
    import tempfile

    t_start = time.perf_counter() if t_start is None else t_start
    import jax
    import numpy as np

    if jax.devices()[0].platform == "cpu" and len(jax.devices()) < 8 \
            and not os.environ.get("KFTPU_BENCH_INPUT_CHILD"):
        # the parent's backend is already initialized with 1 device; the
        # 8-device mesh needs the flag set before jax import → child
        env = {**os.environ, "KFTPU_BENCH_INPUT_CHILD": "1",
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                             " --xla_force_host_platform_device_count=8")}
        res = subprocess.run([sys.executable, __file__, "--mode", "input"],
                             env=env, capture_output=True, text=True,
                             timeout=900)
        for line in reversed(res.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                row = json.loads(line)
                row["_flops_per_chip"] = 0.0
                return row
        raise RuntimeError("input bench child emitted no JSON row "
                           f"(rc={res.returncode}): {res.stderr[-2000:]}")

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kubeflow_tpu.data.device_prefetch import DevicePrefetcher
    from kubeflow_tpu.data.imagenet import (ImageNetSource, augment_base,
                                            augment_batch, decode_records,
                                            write_shards)

    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    if on_cpu:
        os.environ.setdefault("KFTPU_AUGMENT_IMPL", "py")

    B = _env_int("KFTPU_BENCH_INPUT_BATCH", 128)
    S = _env_int("KFTPU_BENCH_INPUT_IMAGE", 96)
    NB = _env_int("KFTPU_BENCH_INPUT_BATCHES", 18)
    repeats = _env_int("KFTPU_BENCH_INPUT_REPEATS", 5)
    workers = _env_int("KFTPU_BENCH_INPUT_WORKERS", 2)
    depth = _env_int("KFTPU_BENCH_INPUT_DEPTH", 2)
    step_ms = _env_int("KFTPU_BENCH_INPUT_STEP_MS", 40)
    n_dev = len(jax.devices())
    B -= B % max(n_dev, 1)   # data-sharded placement: batch % devices == 0

    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))

    def place(batch):
        # the worker's data-sharded layout (TrainStepBuilder.place_batch):
        # batch dim split across every device on the mesh
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}

    def consume(placed):
        # simulated device step: wait for the transfer, then hold the
        # step budget WITHOUT host CPU (see docstring)
        jax.block_until_ready(placed)
        if step_ms:
            time.sleep(step_ms / 1000.0)

    tmp = tempfile.mkdtemp(prefix="kftpu-input-bench-")
    timings: dict = {}
    try:
        rng = np.random.default_rng(7)
        n_rec = B * (NB + 2)   # +2: the primed batch + slack per epoch
        images = rng.integers(0, 256, (n_rec, S, S, 3), dtype=np.uint8)
        labels = (np.arange(n_rec) % 100).astype(np.int64)
        write_shards(tmp, images, labels, shard_records=max(B, 256),
                     num_classes=100)
        del images, labels

        # -- stage attribution ------------------------------------------
        src = ImageNetSource(tmp, batch_size=B, output="uint8")
        pipe = src._epoch_pipeline(0, 3)
        raws = []
        t0 = time.perf_counter()
        for i, raw in enumerate(pipe):
            if i < 2:
                raws.append(np.array(raw))
            if i + 1 >= NB:
                break
        timings["record_read"] = (time.perf_counter() - t0) / NB
        src.close()

        imgs, _ = decode_records(raws[0], S)
        base = augment_base(3, 0, 0)
        reps = 8
        t0 = time.perf_counter()
        for _ in range(reps):
            out = augment_batch(imgs, base, 4, do_flip=True, do_crop=True,
                                output="uint8")
        timings["decode_augment"] = (time.perf_counter() - t0) / reps

        host_batch = {"images": out,
                      "labels": np.zeros(B, np.int32)}
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(place(host_batch))
        timings["h2d_sharded"] = (time.perf_counter() - t0) / reps

        # -- input-path-only rates (no step pacing) ---------------------
        def serial_arm(pace_ms):
            """Every stage on the critical path, hard per-step barrier."""
            src = ImageNetSource(tmp, batch_size=B, output="uint8")
            try:
                it = src.epoch(0, seed=3)
                consume(place(next(it)))   # prime: pipeline spin-up
                n = 0
                t0 = time.perf_counter()
                for batch in it:
                    placed = place(batch)
                    jax.block_until_ready(placed)
                    if pace_ms:
                        time.sleep(pace_ms / 1000.0)
                    n += 1
                    if n >= NB:   # checked BEFORE pulling batch NB+1:
                        break     # an extra pull here is a full augment
                return (time.perf_counter() - t0) / n
            finally:
                src.close()

        def overlapped_arm(pace_ms):
            """The shipped pipeline: mp augment ring + device prefetch,
            synced only on the batch being read. The step budget is a
            DEADLINE, not a sleep after the fetch: the worker loop
            dispatches step N and fetches/places batch N+1 while the
            device computes, so the simulated device must likewise run
            concurrently with the host-side input work (queue depth 1 —
            conservative vs the real loop's deeper dispatch queue)."""
            src = ImageNetSource(tmp, batch_size=B, output="uint8",
                                 workers=workers)
            try:
                it = DevicePrefetcher(src.batches(seed=3), place,
                                      depth=depth)
                consume(next(it))   # prime: spawn + first fill
                n = 0
                t0 = time.perf_counter()
                deadline = t0      # when the device finishes step n-1
                for placed in it:
                    jax.block_until_ready(placed)   # transfer complete
                    now = time.perf_counter()
                    if pace_ms:
                        if now < deadline:
                            time.sleep(deadline - now)
                        # step n dispatched the moment its batch is ready
                        deadline = max(now, deadline) + pace_ms / 1000.0
                    n += 1
                    if n >= NB:   # symmetric with the serial arm
                        break
                if pace_ms:         # the last dispatched step completes
                    now = time.perf_counter()
                    if now < deadline:
                        time.sleep(deadline - now)
                dt = (time.perf_counter() - t0) / n
                it.close()
                return dt
            finally:
                src.close()

        # PAIRED A/B: the arms alternate within each repeat and the
        # headline is the median of per-pair ratios — host-load drift
        # between repeats (this box is noisy) cancels inside a pair
        # where a median-of-arm-medians would not
        def paired(pace_ms):
            pairs = [(serial_arm(pace_ms), overlapped_arm(pace_ms))
                     for _ in range(repeats)]
            ratio = float(np.median([s / o for s, o in pairs]))
            return (float(np.median([s for s, _ in pairs])),
                    float(np.median([o for _, o in pairs])), ratio)

        (timings["serial_input_path"], timings["overlapped_input_path"],
         input_only_ratio) = paired(0)

        # -- the A/B under a device-step budget -------------------------
        serial_s, overlap_s, ratio = paired(step_ms)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    stages_img_s = {k: round(B / v, 1) for k, v in timings.items()}
    return {
        "metric": "input_pipeline_overlap_speedup",
        "value": round(ratio, 3),
        "unit": "serial_step_time_over_overlapped",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "devices": n_dev,
            "global_batch": B,
            "image_size": S,
            "augment_impl": os.environ.get("KFTPU_AUGMENT_IMPL", "native"),
            "input_workers": workers,
            "device_prefetch_depth": depth,
            "simulated_step_ms": step_ms,
            "batches_per_run": NB,
            "repeats": repeats,
            "stages_img_s": stages_img_s,
            "serial_ms_per_batch": round(serial_s * 1e3, 1),
            "overlapped_ms_per_batch": round(overlap_s * 1e3, 1),
            "serial_img_s": round(B / serial_s, 1),
            "overlapped_img_s": round(B / overlap_s, 1),
            "input_only_speedup": round(input_only_ratio, 3),
        },
        "_flops_per_chip": 0.0,
    }


def bench_chaos(t_start: float | None = None) -> dict:
    """Chaos soak (cluster/chaos.py): drive ONE TPUJob end to end through
    the full scripted fault menu — pod deletion (preemption), a pod crash
    under an apiserver 5xx burst, a watch-stream drop, a truncated latest
    checkpoint, and a hung-but-not-dead chief — and record whether the
    control plane recovered the job to Succeeded every time. Correctness
    bar: the final params must match an UNINJECTED soak of the same seed
    to ≤1e-5 (the checkpoint/resume/replay path recomputes identical
    numerics, including the truncated-step fallback to the previous
    intact checkpoint). Not a throughput number — the soak's value is
    the recovery ledger in extras (docs/operations.md "Failure
    handling")."""
    import os
    import shutil
    import tempfile

    t_start = time.perf_counter() if t_start is None else t_start
    import jax
    import numpy as np

    from kubeflow_tpu.cluster.chaos import ChaosSoak, SoakFault, final_params

    faults = [SoakFault(2, "pod-kill"), SoakFault(3, "api-burst"),
              SoakFault(4, "watch-drop"), SoakFault(5, "truncate-ckpt"),
              SoakFault(6, "hung-chief")]
    tmp = tempfile.mkdtemp(prefix="kftpu-chaos-")
    try:
        t0 = time.perf_counter()
        report = ChaosSoak(workdir=os.path.join(tmp, "injected"),
                           faults=faults, total_steps=8,
                           checkpoint_every=2).run()
        soak_s = time.perf_counter() - t0
        # the parity reference: same seed, same steps, zero faults
        clean = ChaosSoak(workdir=os.path.join(tmp, "clean"), faults=[],
                          total_steps=8, checkpoint_every=2).run()
        max_delta = float("nan")
        if report["outcome"] == "succeeded" and \
                clean["outcome"] == "succeeded":
            injected_params = final_params(report["checkpoint_dir"])
            clean_params = final_params(clean["checkpoint_dir"])
            max_delta = max(jax.tree.leaves(jax.tree.map(
                lambda a, b: float(np.max(np.abs(
                    np.asarray(a) - np.asarray(b)))),
                injected_params, clean_params)), default=0.0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    # Capacity-loss scenario (ISSUE 8 chaos vocabulary): a host VANISHES
    # from inventory mid-run (cluster/chaos.py CapacityLoss deletes the
    # node object — not a crash on it) under an ELASTIC job; the only
    # recovery is shrink-to-survive (no same-size rectangle exists), and
    # the job must still end Succeeded at the degraded width. Gated by
    # KFTPU_BENCH_CHAOS_CAPACITY=0 (the full shrink→grow arc with parity
    # numbers runs under --mode sched).
    capacity: dict = {"skipped": True}
    if _env_int("KFTPU_BENCH_CHAOS_CAPACITY", 1):
        from kubeflow_tpu.scheduler.soak import ElasticSoak
        tmp = tempfile.mkdtemp(prefix="kftpu-chaos-capacity-")
        try:
            t0 = time.perf_counter()
            cap = ElasticSoak(workdir=tmp, grow_phase=False).run()
            capacity = {
                "outcome": cap["outcome"],
                "events": cap["events"],
                "chips_seen": cap["chips_seen"],
                "shrank_to_survive": bool(4 in cap["chips_seen"]),
                "roundtrip_delta_across_degrees":
                    cap.get("roundtrip_delta_at_shrink"),
                "soak_wall_s": round(time.perf_counter() - t0, 1),
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    recovered = report["outcome"] == "succeeded"
    return {
        "metric": "chaos_soak_faults_recovered",
        "value": float(len(report["injected"])) if recovered else 0.0,
        "unit": "injected_faults",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "outcome": report["outcome"],
            "clean_outcome": clean["outcome"],
            "injected": report["injected"],
            "restart_reasons": report["restart_reasons"],
            "gang_restarts": report.get("gang_restarts"),
            "segments": report["segments"],
            "api_calls": report["api_calls"],
            "api_faults_injected": report["api_faults"],
            "soak_wall_s": round(soak_s, 1),
            "final_params_max_abs_delta_vs_clean": max_delta,
            "params_parity_ok": bool(recovered and max_delta <= 1e-5),
            "capacity_loss": capacity,
        },
        "_flops_per_chip": 0.0,
    }


def bench_sentinel(t_start: float | None = None) -> dict:
    """Numeric-integrity sentinel drills (runtime/sentinel.py +
    cluster/chaos.py SentinelSoak):

    1. **Detection latency** per fault kind: an in-process train() with
       the numeric-fault hook armed must trip within checkEverySteps of
       the damage surfacing (NaN via the non-finite detector, a finite
       excursion via the rolling z-score).
    2. **Rollback drill**: a full SentinelSoak (real operator on
       FakeCluster) with a NaN injection — the job rolls back to the
       LKG step (never the newest checkpoint) and the recovered params
       must match a clean soak of the same seed to ≤1e-5.
    3. **False-positive soak**: a clean run at the DEFAULT spikeZ over
       KFTPU_BENCH_SENT_FP_STEPS steps (200; smoke trims) — zero trips.
    4. **Bisection soak**: BitFlipGrad pinned to one host, firing twice
       at the same step — the second trip arms replay, the clean replay
       publishes the verdict span, the host's folded evidence crosses
       the quarantine threshold, and the goodput ledger names the
       replayed steps as rollback_recompute while still summing to
       wall-clock.
    5. **Overhead**: measured cost of NumericSentinel.observe per step
       against the drill's mean step time — modeled overhead <1%."""
    import os
    import shutil
    import tempfile

    t_start = time.perf_counter() if t_start is None else t_start
    import jax
    import numpy as np

    from kubeflow_tpu.cluster.chaos import (BitFlipGrad, NaNInjector,
                                            SentinelSoak, final_params)
    from kubeflow_tpu.obs import goodput as gp
    from kubeflow_tpu.obs.trace import load_spans
    from kubeflow_tpu.runtime import sentinel as sent
    from kubeflow_tpu.runtime.worker import train

    def _injected_train(tmp, injector, steps, **integrity_kw):
        """In-process train() with the numeric-fault hook armed via its
        env contract (the integrity knobs go through kwargs)."""
        env = {}
        if injector is not None:
            env = {sent.NUMERIC_FAULT_ENV: injector.spec(),
                   sent.NUMERIC_FAULT_MARK_ENV:
                       os.path.join(tmp, "fault.mark"),
                   sent.NUMERIC_FAULT_FIRES_ENV: str(injector.fires)}
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            return train(
                workload="transformer", steps=steps, global_batch=8,
                sync_every=1, checkpoint_dir=None, seed=0,
                handle_sigterm=False, integrity=True, **integrity_kw)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    check_every = 4
    # ---- drill 1: detection latency per kind ---------------------------
    detection = {}
    with tempfile.TemporaryDirectory() as td:
        # nan: poison after step 5 completes — damage surfaces in step
        # 6's metrics; the trip must land within checkEverySteps of that
        res = _injected_train(td, NaNInjector(at_step=5), steps=16,
                              integrity_check_every=check_every)
        surfaced = 5 + 1
        detection["nan"] = {
            "kind": (res.anomaly or {}).get("kind"),
            "trip_step": (res.anomaly or {}).get("step"),
            "steps_to_detect": res.steps - surfaced,
            "within_check_every":
                bool(res.anomaly) and 0 <= res.steps - surfaced
                < check_every,
        }
    with tempfile.TemporaryDirectory() as td:
        # spike: a finite 8x excursion after the rolling window armed
        from kubeflow_tpu.cluster.chaos import LossSpikePoisoner
        res = _injected_train(td, LossSpikePoisoner(at_step=8, scale=8.0),
                              steps=24, integrity_check_every=check_every,
                              integrity_window=4, integrity_spike_z=4.0)
        surfaced = 8 + 1
        detection["spike"] = {
            "kind": (res.anomaly or {}).get("kind"),
            "trip_step": (res.anomaly or {}).get("step"),
            "steps_to_detect": res.steps - surfaced,
            "within_check_every":
                bool(res.anomaly) and 0 <= res.steps - surfaced
                < check_every,
        }
    detected_ok = all(d["within_check_every"] for d in detection.values())

    # ---- drill 2: LKG rollback + parity vs clean -----------------------
    tmp = tempfile.mkdtemp(prefix="kftpu-sentinel-")
    try:
        t0 = time.perf_counter()
        report = SentinelSoak(workdir=os.path.join(tmp, "injected"),
                              fault=NaNInjector(at_step=5),
                              total_steps=10).run()
        clean = SentinelSoak(workdir=os.path.join(tmp, "clean"),
                             fault=None, total_steps=10).run()
        rollback_s = time.perf_counter() - t0
        max_delta = float("nan")
        if report["outcome"] == "succeeded" and \
                clean["outcome"] == "succeeded":
            injected_params = final_params(report["checkpoint_dir"])
            clean_params = final_params(clean["checkpoint_dir"])
            max_delta = max(jax.tree.leaves(jax.tree.map(
                lambda a, b: float(np.max(np.abs(
                    np.asarray(a) - np.asarray(b)))),
                injected_params, clean_params)), default=0.0)
        rollback = {
            "outcome": report["outcome"],
            "clean_outcome": clean["outcome"],
            "anomalies": report["anomalies"],
            "rollbacks": report.get("rollbacks"),
            "final_params_max_abs_delta_vs_clean": max_delta,
            "params_parity_ok": bool(max_delta <= 1e-5),
            "soak_wall_s": round(rollback_s, 1),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # ---- drill 3: false-positive soak at default spikeZ ----------------
    fp_steps = _env_int("KFTPU_BENCH_SENT_FP_STEPS", 200)
    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        res = _injected_train(td, None, steps=fp_steps)
        fp_wall = time.perf_counter() - t0
    false_positive = {
        "steps": fp_steps,
        "trips": 0 if res.anomaly is None else 1,
        "anomaly": res.anomaly,
        "spike_z": sent.DEFAULT_SPIKE_Z,
    }
    mean_step_s = fp_wall / max(1, fp_steps)

    # ---- drill 4: pinned bit-flip → bisection + quarantine + ledger ----
    tmp = tempfile.mkdtemp(prefix="kftpu-sentinel-bisect-")
    try:
        t0 = time.perf_counter()
        suspect = "tpu-pool-v5e-8-1"
        breport = SentinelSoak(
            workdir=tmp,
            fault=BitFlipGrad(at_step=5, node=suspect, scale=1e30,
                              fires=2),
            total_steps=10).run()
        ledger = gp.decompose(load_spans(
            breport["span_path"], trace_id=breport.get("trace_id")))
        bisect_s = time.perf_counter() - t0
        bisection = {
            "outcome": breport["outcome"],
            "rollbacks": breport.get("rollbacks"),
            "verdict_span": breport.get("bisection"),
            "suspect_quarantined":
                suspect in breport.get("quarantined", []),
            "rollback_recompute_s": round(
                ledger["badputSeconds"][gp.BADPUT_ROLLBACK], 4),
            "steps_rolled_back": ledger.get("stepsRolledBack"),
            "ledger_sums_to_wall": gp.categories_sum_ok(ledger),
            "soak_wall_s": round(bisect_s, 1),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # ---- drill 5: modeled per-step sentinel overhead -------------------
    probe = sent.NumericSentinel(spike_z=sent.DEFAULT_SPIKE_Z,
                                 window_steps=sent.DEFAULT_WINDOW_STEPS)
    n_obs = 10_000
    t0 = time.perf_counter()
    for i in range(n_obs):
        probe.observe(i + 1, loss=4.0 + 0.01 * (i % 7),
                      grad_norm=1.0)
    observe_s = (time.perf_counter() - t0) / n_obs
    overhead_pct = 100.0 * observe_s / max(mean_step_s, 1e-9)

    ok = (detected_ok and rollback["params_parity_ok"]
          and false_positive["trips"] == 0
          and bisection["suspect_quarantined"]
          and bisection["verdict_span"] is not None
          and bisection["ledger_sums_to_wall"]
          and overhead_pct < 1.0)
    return {
        "metric": "sentinel_drills_passed",
        "value": 1.0 if ok else 0.0,
        "unit": "all_sentinel_drills_green",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "detection": detection,
            "check_every_steps": check_every,
            "rollback": rollback,
            "false_positive": false_positive,
            "bisection": bisection,
            "overhead": {
                "observe_us_per_step": round(observe_s * 1e6, 2),
                "mean_step_ms": round(mean_step_s * 1e3, 2),
                "modeled_overhead_pct": round(overhead_pct, 4),
                "under_1pct": bool(overhead_pct < 1.0),
            },
            "startup_first_step_s": round(
                time.perf_counter() - t_start, 2),
        },
        "_flops_per_chip": 0.0,
    }


def bench_sched(t_start: float | None = None) -> dict:
    """Gang-scheduler A/B on a seeded contended cluster
    (scheduler/sim.py drives the REAL plan()/inventory code): FIFO vs
    priority+backfill vs priority+backfill+preemption vs ELASTIC
    (preempt + resize plans for minChips/maxChips-bounded gangs) over
    the same seeded workloads, reporting makespan, chip utilization,
    queue-wait percentiles, and resize/recompute counts — plus two
    real-training soaks (scheduler/soak.py): the preemption parity soak
    (a reclaimed job must finish params-identical to an uncontended
    run), and the ELASTIC shrink→grow soak (a host vanishes mid-run,
    the gang re-binds degraded, capacity returns, the gang grows back —
    ends Succeeded with a lossless cross-replica-degree checkpoint
    round trip).

    Env knobs (the sched/elastic_bench_smoke CI entries shrink the
    geometry): KFTPU_BENCH_SCHED_SEEDS / _JOBS / _POOLS / _SOAK (0
    skips the preemption soak) / _ELASTIC_SOAK (0 skips the shrink→grow
    soak)."""
    import os
    import shutil
    import tempfile

    from kubeflow_tpu.scheduler.sim import compare_policies

    t_start = time.perf_counter() if t_start is None else t_start
    seeds = list(range(_env_int("KFTPU_BENCH_SCHED_SEEDS", 5)))
    n_jobs = _env_int("KFTPU_BENCH_SCHED_JOBS", 24)
    pools = tuple((os.environ.get("KFTPU_BENCH_SCHED_POOLS") or
                   "v5e-32,v5e-16").split(","))
    t0 = time.perf_counter()
    table = compare_policies(seeds, n_jobs=n_jobs, pools=pools)
    sim_s = time.perf_counter() - t0
    fifo, pre = table["fifo"], table["preempt"]
    ela = table["elastic"]
    dominates = (pre["chip_utilization"] > fifo["chip_utilization"]
                 and pre["queue_wait_p50"] < fifo["queue_wait_p50"])
    # the elastic acceptance bar (ISSUE 8): beat the PR 4 preempt arm's
    # utilization with LESS thrown-away work — resizes (checkpointed
    # restarts, zero recompute) replacing preemptions
    elastic_ab = {
        "chip_utilization": ela["chip_utilization"],
        "vs_preempt_utilization": round(
            ela["chip_utilization"] / pre["chip_utilization"], 3)
        if pre["chip_utilization"] else None,
        "resizes_per_run": ela["resizes"],
        "recomputed_ticks": ela["recomputed_ticks"],
        "recomputed_vs_preempt": round(
            ela["recomputed_ticks"] / pre["recomputed_ticks"], 3)
        if pre["recomputed_ticks"] else None,
        "beats_pr4_baseline": bool(
            ela["chip_utilization"] > pre["chip_utilization"]
            and ela["recomputed_ticks"] <= pre["recomputed_ticks"]),
    }

    elastic_soak: dict = {"skipped": True}
    if _env_int("KFTPU_BENCH_SCHED_ELASTIC_SOAK", 1):
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.scheduler.soak import ElasticSoak
        tmp = tempfile.mkdtemp(prefix="kftpu-elastic-soak-")
        try:
            t0 = time.perf_counter()
            soak = ElasticSoak(workdir=tmp)
            report = soak.run()
            clean_delta = float("nan")
            if report["outcome"] == "succeeded":
                got = final_params(report["checkpoint_dir"])
                clean = soak.clean_params()
                clean_delta = max(jax.tree.leaves(jax.tree.map(
                    lambda a, b: float(np.max(np.abs(
                        np.asarray(a) - np.asarray(b)))),
                    got, clean)), default=0.0)
            rt = max(report.get("roundtrip_delta_at_shrink", float("nan")),
                     report.get("roundtrip_delta_final", float("nan")))
            elastic_soak = {
                "outcome": report["outcome"],
                "events": report["events"],
                "chips_seen": report["chips_seen"],
                "shrink_resume_step": report.get("shrink_resume_step"),
                "grow_resume_step": report.get("grow_resume_step"),
                # the ≤1e-5 acceptance: the checkpoint round trip across
                # replica degrees 8↔4 (sharded optimizer state reshaped
                # on restore) must be lossless
                "roundtrip_delta_across_degrees": rt,
                "roundtrip_ok": bool(rt <= 1e-5),
                # vs an undisturbed full-width run: cross-degree
                # reduction-order float drift only (reported, not
                # hidden; the round trip above is the exactness bar)
                "final_params_max_abs_delta_vs_clean": clean_delta,
                "soak_wall_s": round(time.perf_counter() - t0, 1),
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    parity: dict = {"skipped": True}
    if _env_int("KFTPU_BENCH_SCHED_SOAK", 1):
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.scheduler.soak import PreemptionSoak
        tmp = tempfile.mkdtemp(prefix="kftpu-sched-soak-")
        try:
            t0 = time.perf_counter()
            soak = PreemptionSoak(workdir=tmp)
            report = soak.run()
            max_delta = float("nan")
            if report["outcome"] == "succeeded":
                preempted = final_params(report["checkpoint_dir"])
                clean = soak.uncontended_params()
                max_delta = max(jax.tree.leaves(jax.tree.map(
                    lambda a, b: float(np.max(np.abs(
                        np.asarray(a) - np.asarray(b)))),
                    preempted, clean)), default=0.0)
            parity = {
                "outcome": report["outcome"],
                "victim_preempted_count":
                    report.get("victim_preempted_count"),
                "victim_resume_step": report.get("victim_resume_step"),
                "final_params_max_abs_delta_vs_uncontended": max_delta,
                "params_parity_ok": bool(
                    report["outcome"] == "succeeded"
                    and max_delta <= 1e-5),
                "soak_wall_s": round(time.perf_counter() - t0, 1),
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    # headline: utilization gained by the full policy over FIFO (>1 =
    # the scheduler pays for itself before counting the wait-time win)
    util_ratio = (pre["chip_utilization"] / fifo["chip_utilization"]
                  if fifo["chip_utilization"] else 1.0)
    return {
        "metric": "gang_scheduler_contended_sim",
        "value": round(util_ratio, 3),
        "unit": "preempt_vs_fifo_chip_utilization",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "seeds": len(seeds),
            "jobs_per_seed": n_jobs,
            "pools": list(pools),
            "policies": table,
            "dominates_fifo": dominates,
            "wait_p50_fifo_over_preempt": round(
                fifo["queue_wait_p50"] / pre["queue_wait_p50"], 2)
            if pre["queue_wait_p50"] else None,
            "sim_wall_s": round(sim_s, 1),
            "elastic": elastic_ab,
            "elastic_soak": elastic_soak,
            "parity": parity,
        },
        "_flops_per_chip": 0.0,
    }


def bench_health(t_start: float | None = None) -> dict:
    """Node-health quarantine A/B (ISSUE 6): does feeding runtime
    failure evidence back into placement actually buy recovery?

    Two parts, both paired quarantine-ON vs quarantine-OFF:

    1. **Degraded-node sim** (scheduler/sim.py compare_health): the
       same seeded contended workloads with the same seeded flaky host
       (recurring crash every other tick through the contention
       window), run through the REAL plan()/inventory code. Asserted:
       quarantine strictly reduces recomputed ticks — crash-looping on
       a known-bad host is pure waste the placement-blind arm keeps
       paying.
    2. **Flaky-host soak** (scheduler/soak.py HealthSoak): one
       scheduler-managed TPUJob on a two-pool cluster, real training
       segments, a pinned host that kills every pod scheduled onto it.
       ON: the operator records the suspect, the scheduler evacuates
       the binding within ONE rebind, the gang finishes on the clean
       pool. OFF: the gang crash-loops in place, one restart per trip.
       Both arms must end params-identical to a clean run (parity 0.0):
       health changes WHERE the gang runs, never what it computes.
       (Replay is structurally zero here — teardown is graceful, so
       every segment checkpoints; the sim carries the recompute A/B.)

    Env knobs (health_bench_smoke shrinks the geometry):
    KFTPU_BENCH_HEALTH_SEEDS / _JOBS / _SOAK (0 skips the soak)."""
    import os
    import shutil
    import tempfile

    from kubeflow_tpu.scheduler.sim import compare_health

    t_start = time.perf_counter() if t_start is None else t_start
    seeds = list(range(_env_int("KFTPU_BENCH_HEALTH_SEEDS", 3)))
    n_jobs = _env_int("KFTPU_BENCH_HEALTH_JOBS", 16)
    t0 = time.perf_counter()
    table = compare_health(seeds, n_jobs=n_jobs)
    sim_s = time.perf_counter() - t0
    on, off = table["quarantine_on"], table["quarantine_off"]

    soak: dict = {"skipped": True}
    if _env_int("KFTPU_BENCH_HEALTH_SOAK", 1):
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.scheduler.soak import HealthSoak
        tmp = tempfile.mkdtemp(prefix="kftpu-health-soak-")
        try:
            t0 = time.perf_counter()
            arms = {}
            clean = None
            for arm, quarantine in (("on", True), ("off", False)):
                drill = HealthSoak(
                    workdir=os.path.join(tmp, arm),
                    quarantine=quarantine)
                report = drill.run()
                if clean is None:
                    clean = drill.clean_params()
                delta = float("nan")
                if report["outcome"] == "succeeded":
                    params = final_params(report["checkpoint_dir"])
                    delta = max(jax.tree.leaves(jax.tree.map(
                        lambda a, b: float(np.max(np.abs(
                            np.asarray(a) - np.asarray(b)))),
                        params, clean)), default=0.0)
                arms[arm] = {
                    "outcome": report["outcome"],
                    "restarts": report["restarts"],
                    "fires": report["fires"],
                    "rebinds": report["rebinds"],
                    "migrated": report["migrated"],
                    "flaky_quarantined": report["flaky_quarantined"],
                    "time_to_recovery_s": report.get("recovery_s"),
                    "useful_work_fraction":
                        report["useful_work_fraction"],
                    "final_params_max_abs_delta_vs_clean": delta,
                    "params_parity_ok": bool(
                        report["outcome"] == "succeeded"
                        and delta <= 1e-5),
                }
            soak = {
                **arms,
                # the acceptance bar, machine-checkable in the artifact
                "migrated_within_one_rebind": bool(
                    arms["on"]["migrated"]
                    and arms["on"]["rebinds"] == 1),
                "off_arm_extra_restarts":
                    arms["off"]["restarts"] - arms["on"]["restarts"],
                "soak_wall_s": round(time.perf_counter() - t0, 1),
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    # headline: recomputed work the quarantine loop saves (>1 = pays)
    waste_ratio = (off["recomputed_ticks"] /
                   max(on["recomputed_ticks"], 1e-9))
    return {
        "metric": "node_health_quarantine_ab",
        "value": round(waste_ratio, 2),
        "unit": "off_over_on_recomputed_ticks",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "seeds": len(seeds),
            "jobs_per_seed": n_jobs,
            "sim": table,
            "quarantine_strictly_reduces_recompute": bool(
                on["recomputed_ticks"] < off["recomputed_ticks"]),
            "sim_wall_s": round(sim_s, 1),
            "soak": soak,
        },
        "_flops_per_chip": 0.0,
    }


def bench_obs(t_start: float | None = None) -> dict:
    """Observability overhead + end-to-end trace proof (ISSUE 5).

    Three parts:

    1. **Micro-costs** of the shared registry and span writer (per-op
       seconds measured over large loops): counter inc, gauge set,
       histogram observe, one span emit (JSONL write + flush), one
       /metrics render.
    2. **Step-time overhead**: a real train loop run with obs ON
       (default registry enabled + span sink) and OFF
       (KFTPU_OBS_DISABLE=1, no sink), alternated to cancel host
       drift; plus the MODELED per-step cost — the measured per-window
       obs work (histogram + gauge + counter + span emit) amortized
       over sync_every steps, as a fraction of the measured step time.
       The modeled number is the asserted one (<1%): the A/B wall
       ratio of a microsecond-scale effect sits inside host noise and
       is reported honestly next to it, not asserted.
    3. **Trace end-to-end**: the seeded contended-scheduler soak
       (scheduler/soak.py — victim preempted mid-run by a
       higher-priority job, both on the REAL scheduler + operator loop
       with real training segments) run with a span sink; the victim's
       whole life must reconstruct from the JSONL alone:
       queued → bound → created → running → windows → preempted →
       re-bound → windows → succeeded. Skippable with
       KFTPU_BENCH_OBS_SOAK=0 (the obs_smoke CI entry keeps it on —
       it IS the acceptance bar).

    Env knobs: KFTPU_BENCH_OBS_STEPS / _SYNC_EVERY / _REPEATS / _SOAK.
    """
    import os
    import shutil
    import statistics
    import tempfile

    from kubeflow_tpu.obs.registry import (Registry,
                                           reset_default_registry)
    from kubeflow_tpu.obs.trace import SpanWriter

    t_start = time.perf_counter() if t_start is None else t_start

    # -- 1) micro-costs ------------------------------------------------------
    reg = Registry()
    counter = reg.counter("bench_obs_total", "bench", labels=("stage",)) \
        .labels(stage="x")
    gauge = reg.gauge("bench_obs_gauge", "bench")
    hist = reg.histogram("bench_obs_seconds", "bench")
    n = 200_000

    def per_op(fn, iters: int) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters

    counter_s = per_op(lambda: counter.inc(), n)
    gauge_s = per_op(lambda: gauge.set(1.5), n)
    hist_s = per_op(lambda: hist.observe(0.01), n)
    tmp = tempfile.mkdtemp(prefix="kftpu-obs-bench-")
    try:
        writer = SpanWriter(os.path.join(tmp, "micro.jsonl"), "bench",
                            trace_id="bench")
        span_s = per_op(lambda: writer.emit("window", start=time.time(),
                                            end=time.time(), step=1,
                                            steps=10), 20_000)
        writer.close()
        # a render over a realistically sized registry (~100 series)
        for i in range(80):
            reg.counter("bench_obs_fill_total", "bench",
                        labels=("i",)).labels(i=str(i)).inc()
        render_s = per_op(lambda: reg.render(), 200)

        # -- 2) step-time overhead ------------------------------------------
        from kubeflow_tpu.runtime.worker import train
        steps = _env_int("KFTPU_BENCH_OBS_STEPS", 24)
        sync_every = _env_int("KFTPU_BENCH_OBS_SYNC_EVERY", 4)
        repeats = _env_int("KFTPU_BENCH_OBS_REPEATS", 2)
        arm_times: dict = {"on": [], "off": []}
        # alternate arms so slow host drift hits both equally; the first
        # (compile-paying) run is charged to neither via warmup=1 inside
        # summary(); run one unrecorded warm-up pass to even the cache
        train(workload="transformer", steps=4, global_batch=8,
              sync_every=sync_every, workload_kwargs={})
        for rep in range(repeats):
            # alternate arm order per repeat so first-runner bias (cache
            # warmth, host load ramps) cancels instead of accumulating
            for arm in (("off", "on"), ("on", "off"))[rep % 2]:
                env_keys = {"KFTPU_OBS_DISABLE": "1" if arm == "off"
                            else "", "KFTPU_SPAN_PATH":
                            os.path.join(tmp, "arm.jsonl")
                            if arm == "on" else ""}
                saved = {k: os.environ.get(k) for k in env_keys}
                for k, v in env_keys.items():
                    if v:
                        os.environ[k] = v
                    else:
                        os.environ.pop(k, None)
                reset_default_registry()
                try:
                    res = train(workload="transformer", steps=steps,
                                global_batch=8, sync_every=sync_every,
                                workload_kwargs={})
                finally:
                    for k, v in saved.items():
                        if v is None:
                            os.environ.pop(k, None)
                        else:
                            os.environ[k] = v
                    reset_default_registry()
                arm_times[arm].append(res.mean_step_time_s)
        step_off = statistics.median(arm_times["off"])
        step_on = statistics.median(arm_times["on"])
        # modeled: what record_window + the window span actually add,
        # amortized per step
        per_window_s = hist_s + gauge_s + counter_s + span_s
        modeled_pct = 100.0 * per_window_s / max(sync_every, 1) / step_on \
            if step_on else 0.0
        measured_pct = 100.0 * (step_on - step_off) / step_off \
            if step_off else 0.0

        # -- 3) trace end-to-end through the contended scheduler -----------
        trace_report: dict = {"skipped": True}
        if _env_int("KFTPU_BENCH_OBS_SOAK", 1):
            from kubeflow_tpu.obs.trace import (TRACE_ID_ANNOTATION,
                                                reconstruct)
            from kubeflow_tpu.api import k8s as k8s_api
            from kubeflow_tpu.scheduler.soak import PreemptionSoak
            sink = os.path.join(tmp, "trace.jsonl")
            saved_sink = os.environ.get("KFTPU_SPAN_PATH")
            os.environ["KFTPU_SPAN_PATH"] = sink
            try:
                t0 = time.perf_counter()
                soak = PreemptionSoak(workdir=os.path.join(tmp, "soak"))
                report = soak.run()
                victim = report.get("victim_manifest") or {}
                trace_id = k8s_api.annotations_of(victim).get(
                    TRACE_ID_ANNOTATION, "")
                timeline = reconstruct(sink, trace_id)
                names = timeline["names"]

                def in_order(*want) -> bool:
                    i = 0
                    for name in names:
                        if i < len(want) and name == want[i]:
                            i += 1
                    return i == len(want)

                trace_report = {
                    "outcome": report["outcome"],
                    "trace_id": trace_id,
                    "spans": len(timeline["events"]),
                    "windows": names.count("window"),
                    "wall_s": timeline["wallSeconds"],
                    # the acceptance bar: the victim's whole life —
                    # queue wait, bind, gang start, windows, preemption,
                    # re-bind, completion — reconstructed from JSONL
                    # spans alone, in order
                    "end_to_end_ok": bool(
                        report["outcome"] == "succeeded" and trace_id
                        and in_order("queued", "bound", "created",
                                     "running", "window", "preempted",
                                     "queued", "bound", "window",
                                     "succeeded")),
                    "soak_wall_s": round(time.perf_counter() - t0, 1),
                }
            finally:
                if saved_sink is None:
                    os.environ.pop("KFTPU_SPAN_PATH", None)
                else:
                    os.environ["KFTPU_SPAN_PATH"] = saved_sink
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    return {
        "metric": "obs_overhead_modeled",
        "value": round(modeled_pct, 4),
        "unit": "pct_of_step_time",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "overhead_lt_1pct": bool(modeled_pct < 1.0),
            "modeled_overhead_pct": round(modeled_pct, 4),
            "measured_ab_overhead_pct": round(measured_pct, 2),
            "step_time_on_s": round(step_on, 6),
            "step_time_off_s": round(step_off, 6),
            "sync_every": sync_every,
            "micro_costs_us": {
                "counter_inc": round(counter_s * 1e6, 3),
                "gauge_set": round(gauge_s * 1e6, 3),
                "histogram_observe": round(hist_s * 1e6, 3),
                "span_emit": round(span_s * 1e6, 3),
                "metrics_render": round(render_s * 1e6, 1),
            },
            "trace": trace_report,
        },
        "_flops_per_chip": 0.0,
    }


def bench_ctrl_chaos(t_start: float | None = None) -> dict:
    """Control-plane fault-tolerance acceptance (ISSUE 14).

    Two parts. (1) ControlPlaneSoak (scheduler/soak.py): a real TPUJob
    trains to Succeeded on the CPU mesh while the operator and the
    scheduler — each a two-replica lease-elected set over per-replica
    chaos clients — are killed mid-write and re-elected, and the
    apiserver partitions; asserted: Succeeded, params parity vs a clean
    run (≤1e-5; measured 0.0), zero duplicate pod creates, zero lost
    annotation writes (the restart-count write audit), zero mutations
    from any replica that never led, and the kill→new-leader failover
    times (recorded in PERF.md). (2) The split-brain drill: partition
    the leader, let the standby steal the lease, and prove the deposed
    leader's writes are REJECTED by the fence before reaching the wire.

    Env knobs (the ctrl_chaos_bench_smoke CI entry shrinks the
    geometry): KFTPU_BENCH_CTRL_{STEPS,OP_KILLS,SCHED_KILLS,PARTITIONS}.
    """
    import os
    import shutil
    import tempfile

    t_start = time.perf_counter() if t_start is None else t_start
    import jax
    import numpy as np

    from kubeflow_tpu.cluster.chaos import final_params
    from kubeflow_tpu.scheduler.soak import (ControlPlaneSoak,
                                             split_brain_drill)

    steps = _env_int("KFTPU_BENCH_CTRL_STEPS", 8)
    soak_kw = dict(
        total_steps=steps, checkpoint_every=2,
        operator_kills=_env_int("KFTPU_BENCH_CTRL_OP_KILLS", 3),
        scheduler_kills=_env_int("KFTPU_BENCH_CTRL_SCHED_KILLS", 2),
        partitions=_env_int("KFTPU_BENCH_CTRL_PARTITIONS", 2))
    tmp = tempfile.mkdtemp(prefix="kftpu-ctrl-chaos-")
    try:
        t0 = time.perf_counter()
        soak = ControlPlaneSoak(workdir=os.path.join(tmp, "soak"),
                                **soak_kw)
        report = soak.run()
        soak_s = time.perf_counter() - t0
        max_delta = float("nan")
        if report["outcome"] == "succeeded":
            clean = ControlPlaneSoak(workdir=os.path.join(tmp, "soak"),
                                     **soak_kw).clean_params()
            injected = final_params(report["checkpoint_dir"])
            max_delta = max(jax.tree.leaves(jax.tree.map(
                lambda a, b: float(np.max(np.abs(
                    np.asarray(a) - np.asarray(b)))),
                injected, clean)), default=0.0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    drill = split_brain_drill()

    kills = soak_kw["operator_kills"] + soak_kw["scheduler_kills"]
    failovers = report["failovers"]
    checks = {
        "soak_succeeded": report["outcome"] == "succeeded",
        "params_parity_ok": bool(max_delta <= 1e-5),
        "operator_failovers_ok":
            failovers["operator"] >= soak_kw["operator_kills"],
        "scheduler_failovers_ok":
            failovers["scheduler"] >= soak_kw["scheduler_kills"],
        "partitions_ok":
            report["partitions"] == soak_kw["partitions"],
        "zero_duplicate_pod_creates":
            report["duplicate_pod_creates"] == 0,
        "zero_lost_annotation_writes":
            not report["lost_annotation_writes"],
        "zero_never_leader_mutations":
            report["never_leader_mutations"] == 0,
        "drill_stolen_by_standby": drill["stolen_by_standby"],
        "drill_old_leader_demoted": drill["old_leader_demoted"],
        "drill_fenced_write_rejected": drill["fenced_write_rejected"],
        "drill_zero_zombie_writes":
            drill["old_leader_writes_after_steal"] == 0
            and not drill["zombie_write_landed"],
        "drill_zero_doubled_pods": drill["doubled_pod_creates"] == 0,
    }
    failover_s = report["failover_s"]
    assert all(checks.values()), {k: v for k, v in checks.items()
                                  if not v}
    return {
        "metric": "ctrl_chaos_failover_p_max_s",
        "value": round(max(failover_s), 3) if failover_s else 0.0,
        "unit": "seconds",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "checks": checks,
            "soak": {
                "outcome": report["outcome"],
                "injected": report["injected"],
                "operator_kills": soak_kw["operator_kills"],
                "scheduler_kills": soak_kw["scheduler_kills"],
                "partitions": report["partitions"],
                "kills_total": kills,
                "failovers": failovers,
                "failover_s": failover_s,
                "failover_mean_s": round(
                    sum(failover_s) / len(failover_s), 3)
                if failover_s else None,
                "gang_restarts": report.get("gang_restarts"),
                "segments": report["segments"],
                "executed_steps": report["executed_steps"],
                "duplicate_pod_creates":
                    report["duplicate_pod_creates"],
                "restart_count_writes": report["restart_count_writes"],
                "binding_writes": report["binding_writes"],
                "replicas_spawned": report["replicas_spawned"],
                "never_leader_mutations":
                    report["never_leader_mutations"],
                "fenced_rejections": report["fenced_rejections"],
                "final_params_max_abs_delta_vs_clean": max_delta,
                "soak_wall_s": round(soak_s, 1),
            },
            "split_brain": drill,
        },
        "_flops_per_chip": 0.0,
    }


def bench_ctrl_scale(t_start: float | None = None) -> dict:
    """Control-plane telemetry scale baseline (ISSUE 20).

    The seeded churn ladder (100 → 1k → 10k jobs over 50 → 250 → 1k
    nodes) driven through the REAL controllers — SliceScheduler + the
    TPUJob operator over FakeCluster — recording per rung: plan-pass
    p50/p99, write amplification, watch fan-out, and the no-op-pass
    fraction. Asserted per rung: the client-side audit reconciles
    EXACTLY against the apiserver's server-side totals (every request,
    list object count, and list byte total, per component). Asserted on
    the top rung: the slowest plan pass reconstructs phase-by-phase
    from the span JSONL alone, and the modeled audit overhead on a
    no-op pass stays under 1%.

    Most jobs at each rung are pre-completed (Succeeded) so the ACTIVE
    set stays bounded while the list payload — the thing that scales —
    grows with the rung: a 10k-job pass still parses a 10k-manifest
    snapshot. Churn per round: an admission burst, completions through
    the real pod path, a node Ready flap, and (once per rung) a forced
    preemption with every pool occupied.

    Env knobs (the ctrl_scale_bench_smoke CI entry shrinks the ladder):
    KFTPU_BENCH_CTRL_SCALE_JOBS (top-rung jobs, default 10000),
    KFTPU_BENCH_CTRL_SCALE_NODES (top-rung nodes, default 1000),
    KFTPU_BENCH_CTRL_SCALE_SEEDS (churn seeds per rung, default 1).

    Jax-free: dispatched before the backend probe, like warmstart."""
    import os
    import random
    import shutil
    import tempfile

    from kubeflow_tpu.api import k8s
    from kubeflow_tpu.api.topology import parse_topology
    from kubeflow_tpu.api.trainingjob import BINDING_ANNOTATION
    from kubeflow_tpu.cluster.fake import FakeCluster
    from kubeflow_tpu.controllers.runtime import Manager
    from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
    from kubeflow_tpu.obs import controlplane as ctrlobs
    from kubeflow_tpu.obs import registry as obsreg
    from kubeflow_tpu.obs import trace as obstrace
    from kubeflow_tpu.scheduler.core import SliceScheduler

    t_start = time.perf_counter() if t_start is None else t_start
    top_jobs = _env_int("KFTPU_BENCH_CTRL_SCALE_JOBS", 10000)
    top_nodes = _env_int("KFTPU_BENCH_CTRL_SCALE_NODES", 1000)
    seeds = max(1, _env_int("KFTPU_BENCH_CTRL_SCALE_SEEDS", 1))
    pool_topo = "v5e-32"
    hosts_per_pool = parse_topology(pool_topo).num_hosts

    ladder: list[tuple[int, int]] = []
    for div_j, div_n in ((100, 20), (10, 4), (1, 1)):
        rung = (max(4, top_jobs // div_j),
                max(hosts_per_pool, top_nodes // div_n))
        if rung not in ladder:
            ladder.append(rung)

    def tpujob(name, topo="v5e-8", priority=0, preemptible=True,
               completed=False):
        job = {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow"},
            "spec": {
                "replicaSpecs": {"TPU": {
                    "tpuTopology": topo,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {"backoffLimit": 2},
                "schedulingPolicy": {"queue": "scale",
                                     "priority": priority,
                                     "preemptible": preemptible},
            }}
        if completed:
            job["status"] = {"conditions": [
                {"type": "Succeeded", "status": "True"}]}
        return job

    def flip_node(cluster, name, ready):
        node = cluster.get("v1", "Node", "", name)
        for c in node.setdefault("status", {}).setdefault(
                "conditions", []):
            if c.get("type") == "Ready":
                c["status"] = "True" if ready else "False"
        cluster.update_status(node)

    def complete_job(cluster, manifest):
        """Finish a bound job through the real path: every pod of the
        gang succeeds; the operator folds that into the Succeeded
        condition on its next reconcile."""
        name = manifest["metadata"]["name"]
        for pod in cluster.list("v1", "Pod", "kubeflow"):
            if pod["metadata"]["name"].startswith(name + "-worker"):
                cluster.set_pod_phase("kubeflow",
                                      pod["metadata"]["name"],
                                      "Succeeded")

    rows = []
    checks: dict = {}
    span_dir = tempfile.mkdtemp(prefix="kftpu-ctrl-scale-")
    recon_names: list = []
    overhead_fraction = float("nan")
    try:
        for rung_i, (jobs, nodes) in enumerate(ladder):
            top = rung_i == len(ladder) - 1
            span_path = os.path.join(span_dir, f"rung-{jobs}.jsonl")
            os.environ[obstrace.SPAN_PATH_ENV] = span_path
            obsreg.reset_default_registry()
            obstrace.reset_default_tracers()
            ctrlobs.reset_span_sampling()

            pools = max(1, nodes // hosts_per_pool)
            cluster = FakeCluster()
            for p in range(pools):
                cluster.add_tpu_slice_nodes(pool_topo, pool=f"pool-{p}")
            node_names = [n["metadata"]["name"]
                          for n in cluster.list("v1", "Node")]
            # the completed bulk, created BEFORE any watcher exists:
            # the rung's list-payload ballast, not churn
            active_budget = min(24, max(4, jobs // 4))
            for i in range(max(0, jobs - active_budget)):
                cluster.create(tpujob(f"done-{i}", completed=True))

            mgr = Manager(cluster)
            sched_ctrl = mgr.add(SliceScheduler())
            op_ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
            # the operator is per-key: drain its initial backlog (each
            # completed-job reconcile is a cheap no-op) so churn keys
            # are reachable. The scheduler stays budget-bounded — its
            # pass is level-triggered, any key pop reads fresh state.
            op_ctrl.run_pending(max_iters=jobs + 500)

            t_rung = time.perf_counter()
            admitted = 0
            for seed in range(seeds):
                rng = random.Random(1000 * (seed + 1) + rung_i)
                for rnd in range(4):
                    burst = min(active_budget // 2,
                                4 + rng.randrange(4))
                    for _ in range(burst):
                        cluster.create(tpujob(f"live-{admitted}"))
                        admitted += 1
                    for _ in range(2):
                        sched_ctrl.run_pending(max_iters=10)
                        op_ctrl.run_pending(max_iters=400)
                        cluster.tick()
                    # complete roughly half the bound live jobs
                    live = [m for m in cluster.list(
                        "tpu.kubeflow.org/v1alpha1", "TPUJob")
                        if m["metadata"]["name"].startswith("live-")
                        and BINDING_ANNOTATION in k8s.annotations_of(m)
                        and not k8s.condition_true(m, "Succeeded")]
                    for m in live[: max(1, len(live) // 2)]:
                        complete_job(cluster, m)
                    flap = rng.choice(node_names)
                    flip_node(cluster, flap, False)
                    sched_ctrl.run_pending(max_iters=10)
                    op_ctrl.run_pending(max_iters=400)
                    flip_node(cluster, flap, True)
                    sched_ctrl.run_pending(max_iters=10)
                    op_ctrl.run_pending(max_iters=400)
                    cluster.tick()

            # forced preemption: occupy EVERY pool with a preemptible
            # full-pool gang, then admit a higher-priority head — the
            # cheapest victim is unbound, the head binds
            for p in range(pools):
                cluster.create(tpujob(f"filler-{p}", topo=pool_topo))
            for _ in range(4):
                sched_ctrl.run_pending(max_iters=max(12, pools + 4))
                op_ctrl.run_pending(max_iters=12 * pools + 200)
                cluster.tick()
            cluster.create(tpujob("vip", priority=10,
                                  preemptible=False))
            for _ in range(4):
                sched_ctrl.run_pending(max_iters=10)
                op_ctrl.run_pending(max_iters=400)
                cluster.tick()
            vip = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              "kubeflow", "vip")
            checks[f"preempt_bound_vip_{jobs}"] = \
                BINDING_ANNOTATION in k8s.annotations_of(vip)

            # no-op tail: steady state, measured — the no-op-pass
            # latency the audit-overhead model divides by
            if len(sched_ctrl.queue) == 0:
                sched_ctrl.queue.add(("", "#noop-tail"))
            t_tail = time.perf_counter()
            tail = 0
            for _ in range(30):
                if len(sched_ctrl.queue) == 0:
                    sched_ctrl.queue.add(("", "#noop-tail"))
                sched_ctrl.pump_events()
                if sched_ctrl.process_one():
                    tail += 1
            noop_mean_s = (time.perf_counter() - t_tail) / max(1, tail)

            # exact reconciliation: every component's client-side audit
            # against the server-side ledger — requests, list object
            # counts, AND list byte totals, bidirectionally
            clients = {c._name(): c.client for c in mgr.controllers}
            mismatches = ctrlobs.audit_mismatches(clients, cluster.audit)
            checks[f"audit_reconciles_exactly_{jobs}"] = not mismatches
            if mismatches:
                log_lines = mismatches[:8]
                print(f"# ctrl-scale rung {jobs}: audit mismatches: "
                      f"{log_lines}", file=sys.stderr, flush=True)

            stats = ctrlobs.pass_stats()
            sched = stats.get("scheduler", {})
            server = cluster.audit.totals()
            n_req = sum(server["requests"].values())
            rows.append({
                "jobs": jobs, "nodes": len(node_names),
                "pools": pools, "seeds": seeds,
                "sched_passes": sched.get("passes", 0),
                "plan_pass_p50_ms": round(
                    1e3 * sched.get("p50Seconds", 0.0), 2),
                "plan_pass_p99_ms": round(
                    1e3 * sched.get("p99Seconds", 0.0), 2),
                "noop_pass_fraction": sched.get("noopFraction", 0.0),
                "write_amplification": sched.get(
                    "writeAmplification", 0.0),
                "watch_fanout": round(cluster.audit.fanout(), 2),
                "server_requests": n_req,
                "relist_objects": sum(
                    s.get("relistObjects", 0) for s in stats.values()),
                "noop_pass_mean_ms": round(1e3 * noop_mean_s, 2),
                "rung_wall_s": round(time.perf_counter() - t_rung, 1),
            })

            if top:
                # (a) the slowest pass must reconstruct phase-by-phase
                # from the JSONL alone — no registry, no process state
                spans = obstrace.load_spans(span_path)
                passes = [s for s in spans
                          if s.get("name") == ctrlobs.CTRL_PASS_SPAN
                          and s.get("component") == "scheduler"]
                checks["top_rung_emitted_pass_spans"] = bool(passes)
                slow = max(passes, key=lambda s: s.get("end", 0.0)
                           - s.get("start", 0.0), default=None)
                if slow is not None:
                    recon = obstrace.reconstruct(span_path,
                                                 slow["trace_id"])
                    recon_names = recon["names"]
                    phases = [n for n in recon_names
                              if n in ctrlobs.PHASES]
                    checks["slow_pass_reconstructs_phases"] = (
                        ctrlobs.CTRL_PASS_SPAN in recon_names
                        and ctrlobs.PHASE_SNAPSHOT in phases
                        and ctrlobs.PHASE_PLAN in phases
                        and all(n in ctrlobs.PHASES
                                or n == ctrlobs.CTRL_PASS_SPAN
                                for n in recon_names))
                else:
                    checks["slow_pass_reconstructs_phases"] = False

                # (b) modeled audit overhead of a no-op pass: per-call
                # accounting cost (client note + server record, deltas
                # measured against the unwrapped inner call) times the
                # pass's request count, over the measured no-op latency
                probe = ctrlobs.AuditingKubeClient(cluster, "probe")
                node0 = node_names[0]
                M = 2000
                t0 = time.perf_counter()
                for _ in range(M):
                    probe.get("v1", "Node", "", node0)
                t_wrapped = time.perf_counter() - t0
                t0 = time.perf_counter()
                for _ in range(M):
                    cluster.get("v1", "Node", "", node0)
                t_inner = time.perf_counter() - t0
                get_delta = max(0.0, (t_wrapped - t_inner) / M)
                L = 200
                t0 = time.perf_counter()
                for _ in range(L):
                    probe.list("v1", "ConfigMap", "kubeflow")
                t_wrapped = time.perf_counter() - t0
                t0 = time.perf_counter()
                for _ in range(L):
                    cluster.list("v1", "ConfigMap", "kubeflow")
                t_inner = time.perf_counter() - t0
                list_delta = max(0.0, (t_wrapped - t_inner) / L)
                aud = ctrlobs.ServerAudit()
                t0 = time.perf_counter()
                for _ in range(M):
                    aud.record(ctrlobs.VERB_GET, "Node")
                record_cost = (time.perf_counter() - t0) / M
                # a no-op scheduler pass: 1 config get + 2 lists
                per_pass = get_delta + 2 * list_delta + 3 * record_cost
                overhead_fraction = per_pass / max(1e-9, noop_mean_s)
                checks["noop_audit_overhead_under_1pct"] = \
                    overhead_fraction < 0.01
            for c in mgr.controllers:
                c.stop()
    finally:
        os.environ.pop(obstrace.SPAN_PATH_ENV, None)
        obstrace.reset_default_tracers()
        obsreg.reset_default_registry()
        ctrlobs.reset_span_sampling()
        shutil.rmtree(span_dir, ignore_errors=True)

    assert all(checks.values()), {k: v for k, v in checks.items()
                                  if not v}
    top_row = rows[-1]
    return {
        "metric": "ctrl_scale_plan_pass_p99_s",
        "value": round(top_row["plan_pass_p99_ms"] / 1e3, 4),
        "unit": "seconds",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "checks": checks,
            "ladder": rows,
            "top_rung": {
                "jobs": top_row["jobs"], "nodes": top_row["nodes"],
                "noop_audit_overhead_fraction": round(
                    overhead_fraction, 6),
                "slow_pass_phases": recon_names,
            },
        },
        "_flops_per_chip": 0.0,
    }


def bench_goodput(t_start: float | None = None) -> dict:
    """Goodput ledger + flight recorder acceptance (ISSUE 10).

    Five parts, all over ONE shared span sink (the deployment shape —
    scheduler, operator, and in-process workers appending to one JSONL):

    1. **Chaos 5-fault soak** (cluster/chaos.py): pod kill, 5xx burst,
       watch drop, checkpoint truncation, hung chief — then the ledger
       reconstructed from the job's spans alone. Asserted: the
       categories sum to wall-clock within 2%, the span-derived
       restart-recompute STEPS equal the soak's known re-executed steps
       (executed minus final progress — ground truth the soak counts
       itself), and the hung-chief scenario left stall badput.
    2. **Preemption soak** (scheduler/soak.py): victim preempted at a
       checkpoint boundary re-binds and finishes; its ledger must show
       queue-wait badput from BOTH waits and ZERO recompute (the forced
       checkpoint means resume loses nothing).
    3. **Flight recorder under SIGTERM**: a real train() preempted
       mid-run by a timer-delivered SIGTERM; the signal handler must
       dump the step-time ring to the sink (reason=sigterm) before the
       graceful exit — the evidence path for workers the stall watchdog
       tears down.
    4. **Scrape + dashboard surfaces**: the chaos job's final ledger
       exported as kftpu_job_goodput_ratio / kftpu_job_badput_seconds_
       total, visible on a live /metrics; the dashboard's
       /api/obs/goodput endpoints serve the per-job decomposition and
       the cluster chip-hour rollup from the same sink.
    5. **Sim comparability** (scheduler/sim.py): the policy arms report
       goodput tables in the SAME category vocabulary, so a sim arm's
       decomposition reads against the real cluster's.

    Env knobs (goodput_bench_smoke shrinks the geometry):
    KFTPU_BENCH_GOODPUT_{SEEDS,JOBS,FLIGHT_STEPS}."""
    import os
    import shutil
    import signal
    import tempfile
    import threading
    import urllib.request

    t_start = time.perf_counter() if t_start is None else t_start
    from kubeflow_tpu.api import k8s as k8s_api
    from kubeflow_tpu.obs import goodput as gp
    from kubeflow_tpu.obs.trace import TRACE_ID_ANNOTATION, load_spans

    tmp = tempfile.mkdtemp(prefix="kftpu-goodput-")
    sink = os.path.join(tmp, "trace.jsonl")
    saved_env = {k: os.environ.get(k)
                 for k in ("KFTPU_SPAN_PATH", "KFTPU_TRACE_ID")}
    os.environ["KFTPU_SPAN_PATH"] = sink
    os.environ.pop("KFTPU_TRACE_ID", None)
    checks: dict = {}
    try:
        # -- 1) chaos 5-fault soak → ledger ------------------------------
        from kubeflow_tpu.cluster.chaos import ChaosSoak, SoakFault
        faults = [SoakFault(2, "pod-kill"), SoakFault(3, "api-burst"),
                  SoakFault(4, "watch-drop"), SoakFault(5, "truncate-ckpt"),
                  SoakFault(6, "hung-chief")]
        t0 = time.perf_counter()
        chaos_report = ChaosSoak(workdir=os.path.join(tmp, "chaos"),
                                 faults=faults, total_steps=8,
                                 checkpoint_every=2).run()
        chaos_ledger = gp.ledger_for(sink, chaos_report.get("trace_id", ""))
        chaos_known_re = chaos_report["executed_steps"] - \
            chaos_report["final_step"]
        chaos = {
            "outcome": chaos_report["outcome"],
            "ledger": chaos_ledger,
            "executed_steps": chaos_report["executed_steps"],
            "final_step": chaos_report["final_step"],
            "known_recomputed_steps": chaos_known_re,
            "soak_wall_s": round(time.perf_counter() - t0, 1),
        }
        checks["chaos_categories_sum_to_wall"] = \
            gp.categories_sum_ok(chaos_ledger)
        checks["chaos_recompute_matches_soak"] = bool(
            chaos_report["outcome"] == "succeeded"
            and chaos_ledger["stepsRecomputed"] == chaos_known_re)
        # the hung-chief fault must leave stall badput in the ledger
        checks["chaos_stall_badput_present"] = \
            chaos_ledger["badputSeconds"][gp.BADPUT_STALL] > 0

        # -- 2) preemption soak → ledger ---------------------------------
        from kubeflow_tpu.scheduler.soak import PreemptionSoak
        t0 = time.perf_counter()
        psoak = PreemptionSoak(workdir=os.path.join(tmp, "sched"))
        p_report = psoak.run()
        victim = p_report.get("victim_manifest") or {}
        victim_tid = k8s_api.annotations_of(victim).get(
            TRACE_ID_ANNOTATION, "")
        p_ledger = gp.ledger_for(sink, victim_tid)
        p_known_re = p_report.get("victim_executed_steps", 0) - \
            psoak.total_steps
        preempt = {
            "outcome": p_report["outcome"],
            "ledger": p_ledger,
            "victim_executed_steps":
                p_report.get("victim_executed_steps"),
            "known_recomputed_steps": p_known_re,
            "soak_wall_s": round(time.perf_counter() - t0, 1),
        }
        checks["preempt_categories_sum_to_wall"] = \
            gp.categories_sum_ok(p_ledger)
        checks["preempt_recompute_matches_soak"] = bool(
            p_report["outcome"] == "succeeded"
            and p_ledger["stepsRecomputed"] == max(0, p_known_re))
        checks["preempt_queue_wait_badput_present"] = \
            p_ledger["badputSeconds"][gp.BADPUT_QUEUE_WAIT] > 0

        # -- 3) flight recorder under SIGTERM ----------------------------
        from kubeflow_tpu.runtime.worker import train
        # a benign outer handler: if the timer's SIGTERM lands in the
        # sliver between train() restoring the previous handler and the
        # cancel below, it must not kill the bench process
        prev_handler = signal.signal(signal.SIGTERM, lambda *a: None)
        os.environ["KFTPU_TRACE_ID"] = "goodput-flight"
        flight_steps = _env_int("KFTPU_BENCH_GOODPUT_FLIGHT_STEPS", 50000)
        done = threading.Event()

        def kill_after_windows(min_step: int = 6,
                               deadline_s: float = 120.0) -> None:
            # preempt only once the ring HAS windows (watching the span
            # sink): a fixed timer lands inside the first compile and
            # dumps an empty ring — present but evidence-free
            end = time.monotonic() + deadline_s
            while time.monotonic() < end and not done.is_set():
                if any(s.get("name") == "window"
                       and (s.get("attrs") or {}).get("step", 0)
                       >= min_step
                       for s in load_spans(sink,
                                           trace_id="goodput-flight")):
                    break
                time.sleep(0.1)
            if not done.is_set():
                os.kill(os.getpid(), signal.SIGTERM)

        killer = threading.Thread(target=kill_after_windows, daemon=True)
        try:
            t0 = time.perf_counter()
            killer.start()
            fr_result = train(workload="transformer", steps=flight_steps,
                              global_batch=8, sync_every=1,
                              checkpoint_dir=os.path.join(tmp, "flight"),
                              checkpoint_every=1000,
                              handle_sigterm=True, workload_kwargs={})
        finally:
            done.set()
            signal.signal(signal.SIGTERM, prev_handler)
            os.environ.pop("KFTPU_TRACE_ID", None)
        dumps = [s for s in load_spans(sink, trace_id="goodput-flight")
                 if s.get("name") == "flight-record"]
        flight = {
            "preempted": fr_result.preempted,
            "steps_before_sigterm": fr_result.steps,
            "dumps": len(dumps),
            "dump_reason": dumps[0].get("attrs", {}).get("reason")
            if dumps else None,
            "ring_windows": len(dumps[0].get("attrs", {}).get(
                "records", [])) if dumps else 0,
            "in_progress_stage": dumps[0].get("attrs", {}).get(
                "inProgress", {}).get("stage") if dumps else None,
            "wall_s": round(time.perf_counter() - t0, 1),
        }
        checks["flight_record_dump_present"] = bool(
            dumps and flight["dump_reason"] == "sigterm"
            and fr_result.preempted)
        checks["flight_record_has_stage_breakdown"] = bool(
            dumps and flight["ring_windows"] > 0 and all(
                k in dumps[0]["attrs"]["records"][-1]
                for k in ("data_s", "h2d_s", "dispatch_s",
                          "device_wait_s")))

        # -- 4) gauges on /metrics + dashboard endpoints -----------------
        gp.export_job_ledger("kubeflow", "chaos-soak", chaos_ledger)
        from kubeflow_tpu.obs.http import ObsServer
        srv = ObsServer(host="127.0.0.1")
        port = srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                text = resp.read().decode()
        finally:
            srv.stop()
        checks["ledger_gauges_on_metrics"] = (
            "kftpu_job_goodput_ratio" in text
            and "kftpu_job_badput_seconds_total" in text)

        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        dash_ok = rollup = None
        if victim:
            manifest = {k: v for k, v in victim.items() if k != "status"}
            for stale in ("uid", "resourceVersion", "creationTimestamp"):
                manifest.get("metadata", {}).pop(stale, None)
            cluster = FakeCluster()
            cluster.create(manifest)
            app = build_dashboard_app(cluster)
            status, body = app.dispatch(
                "GET", f"/api/obs/goodput/{psoak.namespace}/victim", None)
            dash_ok = bool(
                status == 200 and "ledger" in body
                and set(body["ledger"]["badputSeconds"])
                == set(gp.BADPUT_CATEGORIES))
            status, rollup = app.dispatch("GET", "/api/obs/goodput", None)
            rollup = rollup.get("chipHours") if status == 200 else None
        checks["dashboard_endpoint_ok"] = bool(dash_ok)
        checks["cluster_rollup_ok"] = bool(
            rollup and rollup["total"] > 0)

        # -- 5) sim arms report the same vocabulary ----------------------
        from kubeflow_tpu.scheduler.sim import compare_policies
        seeds = list(range(_env_int("KFTPU_BENCH_GOODPUT_SEEDS", 3)))
        n_jobs = _env_int("KFTPU_BENCH_GOODPUT_JOBS", 16)
        t0 = time.perf_counter()
        sim_table = compare_policies(seeds, n_jobs=n_jobs)
        sim = {policy: {"goodput_fraction": row["goodput_fraction"],
                        "badput_chip_ticks": row["badput_chip_ticks"]}
               for policy, row in sim_table.items()}
        sim["sim_wall_s"] = round(time.perf_counter() - t0, 1)
        checks["sim_categories_match_ledger"] = all(
            set(row["badput_chip_ticks"]) == set(gp.BADPUT_CATEGORIES)
            for policy, row in sim_table.items())
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)

    return {
        "metric": "goodput_ledger_decomposition",
        "value": chaos_ledger["goodputRatio"],
        "unit": "chaos_soak_goodput_ratio",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "chaos": chaos,
            "preemption": preempt,
            "flight_recorder": flight,
            "sim": sim,
            **checks,
            "all_checks_ok": all(checks.values()),
        },
        "_flops_per_chip": 0.0,
    }


def bench_serving_obs(t_start: float | None = None) -> dict:
    """Serving request-observability acceptance (ISSUE 11).

    Five parts over one ModelServer with a span sink:

    1. **Open-loop heavy-tail load**: Poisson arrivals at each offered
       QPS level (open loop: requests fire on schedule regardless of
       completions), request batch sizes drawn Pareto-heavy-tailed —
       p50/p99/p99.9 vs offered QPS plus the mean batch fill, the
       baseline table the continuous-batching PR will be judged
       against (recorded in PERF.md).
    2. **Ledger partition**: every request's ``serving-request`` span
       carries its ledger (obs/goodput.py decompose_request); asserted:
       goodput + every serving badput category re-adds to the request's
       wall-clock, and the aggregate unattributed ``other`` residual
       stays ≤ 2% (reported, never absorbed).
    3. **Slow-request reconstruction**: the slowest SAMPLED request's
       timeline rebuilt from the JSONL alone must read accept → queue →
       batch-form → h2d → device → drain → respond, all stamped with
       the one request id.
    4. **Tracing overhead < 1% on the batcher hot path**: alternating-
       arm A/B (the PR 5 method) of direct MicroBatcher.predict with
       the request ctx on vs off; the asserted number is the MODELED
       per-request obs cost (measured begin→stages→finish micro-cost)
       over the measured request latency — the wall A/B ratio of a
       tens-of-µs effect sits inside host noise and is reported
       honestly beside it.
    5. **Bounded-queue shed**: a slow servable behind max_pending=2
       under a concurrent burst must shed with 429 + the request id
       echoed, the shed requests' ledgers landing in the sink as
       outcome=shed (queue badput, never dropped) and
       kftpu_serving_shed_total on /metrics.

    Env knobs (serving_obs_bench_smoke shrinks the geometry):
    KFTPU_BENCH_SOBS_{QPS,SECONDS,AB_REQS,REPEATS}."""
    import concurrent.futures
    import os
    import random
    import shutil
    import statistics
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import numpy as np

    from kubeflow_tpu.obs import goodput as gp
    from kubeflow_tpu.obs.trace import load_spans, reconstruct
    from kubeflow_tpu.serving.http_server import ModelServer
    from kubeflow_tpu.serving.replica_state import ModelSLO
    from kubeflow_tpu.serving.request_trace import ServingObs

    t_start = time.perf_counter() if t_start is None else t_start
    import jax
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        depth, image_size = 50, 224
        qps_levels = [int(x) for x in os.environ.get(
            "KFTPU_BENCH_SOBS_QPS", "20,60,120").split(",")]
    else:
        depth, image_size = 18, 32
        qps_levels = [int(x) for x in os.environ.get(
            "KFTPU_BENCH_SOBS_QPS", "6,12").split(",")]
    seconds = float(os.environ.get("KFTPU_BENCH_SOBS_SECONDS", "4"))
    ab_reqs = _env_int("KFTPU_BENCH_SOBS_AB_REQS", 40)
    repeats = _env_int("KFTPU_BENCH_SOBS_REPEATS", 2)
    model = f"resnet{depth}"

    tmp = tempfile.mkdtemp(prefix="kftpu-sobs-")
    sink = os.path.join(tmp, "serving.jsonl")
    checks: dict = {}
    server = None
    try:
        server = ModelServer(host="127.0.0.1", port=0, max_batch=8,
                             max_latency_ms=2.0, sample_every=4,
                             span_path=sink,
                             slos={model: ModelSLO(target_p99_ms=5000.0,
                                                   availability=0.99)})
        servable = server.repository.load(model, model, num_classes=100,
                                          image_size=image_size)
        servable.max_batch = 8
        servable.warmup()
        port = server.start()
        url = f"http://127.0.0.1:{port}/v1/models/{model}:predict"

        rng = np.random.default_rng(0)
        # pre-serialized bodies per batch size: the load loop times the
        # wire + server, not client JSON formatting
        bodies = {b: json.dumps(
            {"instances": rng.standard_normal(
                (b, image_size, image_size, 3)).astype(
                    np.float32).tolist(),
             "dtype": "float32"}).encode() for b in (1, 2, 4, 8)}

        def one_request(body: bytes, target_url: str = url) -> tuple:
            req = urllib.request.Request(
                target_url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=120.0) as resp:
                    resp.read()
                return time.perf_counter() - t0, True
            except urllib.error.HTTPError as e:
                e.read()
                return time.perf_counter() - t0, False

        def pct(sorted_lats, q):
            return sorted_lats[min(len(sorted_lats) - 1,
                                   int(len(sorted_lats) * q))]

        pool = concurrent.futures.ThreadPoolExecutor(max_workers=64)

        import gc

        def run_level(target_url: str, qps: int) -> tuple:
            """One open-loop Poisson pass at one offered QPS. Arrival
            times AND request sizes come off a per-pass seeded rng —
            every arm/round of the batching A/B sees the identical
            offered workload, so the comparison is scheduler vs
            scheduler, not luck vs luck. A full gc first: one pass's
            garbage must not be collected on a later pass's clock (a
            measured ~25 ms p50 skew in this process before the
            barrier went in)."""
            gc.collect()
            arr = random.Random(0)

            def arm_batch() -> int:
                size = int(arr.paretovariate(1.2))
                for b in (1, 2, 4, 8):
                    if size <= b:
                        return b
                return 8

            futures = []
            t0 = time.perf_counter()
            next_at = t0
            deadline = t0 + seconds
            while True:
                now = time.perf_counter()
                if now >= deadline:
                    break
                if now < next_at:
                    time.sleep(min(next_at - now, 0.02))
                    continue
                # open loop: fire on the Poisson schedule whether
                # or not earlier requests completed
                futures.append(pool.submit(
                    one_request, bodies[arm_batch()], target_url))
                next_at += arr.expovariate(qps)
            lats, errors = [], 0
            for f in futures:
                lat, ok = f.result()
                lats.append(lat)
                if not ok:
                    errors += 1
            lats.sort()
            return lats, errors, time.perf_counter() - t0

        def run_ladder(target_url: str) -> list:
            table = []
            for qps in qps_levels:
                lats, errors, wall = run_level(target_url, qps)
                table.append({
                    "offered_qps": qps,
                    "achieved_qps": round(len(lats) / wall, 1),
                    "requests": len(lats),
                    "p50_ms": round(pct(lats, 0.50) * 1e3, 2),
                    "p99_ms": round(pct(lats, 0.99) * 1e3, 2),
                    "p999_ms": round(pct(lats, 0.999) * 1e3, 2),
                    "errors": errors,
                })
            return table

        # -- 1b) fixed-window vs continuous A/B (ISSUE 18) ---------------
        # The PR 11 knee — p99 102→191 ms at 2× load under the fixed
        # window — is the number continuous batching exists to kill.
        # Same servable, same offered workload, second server in
        # batching="window" mode; its spans go to a side sink so the
        # ledger checks below read only the primary (continuous) arm.
        win_server = ModelServer(server.repository, host="127.0.0.1",
                                 port=0, max_batch=8, max_latency_ms=2.0,
                                 sample_every=0,
                                 span_path=os.path.join(tmp, "win.jsonl"),
                                 batching="window")
        win_port = win_server.start()
        win_url = (f"http://127.0.0.1:{win_port}"
                   f"/v1/models/{model}:predict")
        window_table = run_ladder(win_url)
        latency_table = run_ladder(url)

        # The asserted statistic pools several alternating rounds at
        # the top (2× baseline) load: one 3–4 s pass yields ~30
        # samples, whose "p99" is just the max — one host-noise
        # straggler on a 2-core box flips it. Pooling W/C/W/C rounds
        # (drift cancels) makes p99 a real percentile that sheds a
        # single straggler.
        top = max(qps_levels)
        ab_rounds = _env_int("KFTPU_BENCH_SOBS_AB_ROUNDS", 3)
        win_pool, cont_pool = [], []
        for _ in range(ab_rounds):
            win_pool.extend(run_level(win_url, top)[0])
            cont_pool.extend(run_level(url, top)[0])
        win_server.stop()
        pool.shutdown(wait=True)
        win_pool.sort()
        cont_pool.sort()
        win_p99_ms = round(pct(win_pool, 0.99) * 1e3, 2)
        cont_p99_ms = round(pct(cont_pool, 0.99) * 1e3, 2)

        # The acceptance bar (ISSUE 18): at 2× baseline load the
        # continuous arm's pooled p99 sits strictly below the recorded
        # PR 11 fixed-window knee — 190.8 ms on this CPU geometry
        # (PERF.md 'Serving request observability'). The in-run window
        # arm is reported beside it for the A/B table; on TPU (no
        # recorded baseline at that geometry) it IS the bar.
        pr11_knee_ms = 190.8
        knee_bar_ms = win_p99_ms if on_tpu else pr11_knee_ms
        checks["continuous_p99_below_window_knee_at_2x"] = bool(
            cont_p99_ms < knee_bar_ms)

        # -- 2) per-request ledgers sum to wall-clock --------------------
        spans = load_spans(sink)
        summaries = [s for s in spans
                     if s.get("name") == gp.SERVING_REQUEST_SPAN]
        other_s = wall_s = 0.0
        n_ok = 0
        worst_resid = 0.0
        for s in summaries:
            ledger = (s.get("attrs") or {}).get("ledger") or {}
            if gp.categories_sum_ok(ledger):
                n_ok += 1
            wall = ledger.get("wallSeconds", 0.0)
            wall_s += wall
            other_s += ledger.get("badputSeconds", {}).get(
                gp.BADPUT_OTHER, 0.0)
            total = ledger.get("goodputSeconds", 0.0) + \
                sum(ledger.get("badputSeconds", {}).values())
            if wall:
                worst_resid = max(worst_resid,
                                  abs(total - wall) / wall)
        checks["ledgers_sum_to_wall"] = bool(
            summaries and n_ok == len(summaries))
        other_frac = other_s / wall_s if wall_s else 1.0
        checks["other_residual_le_2pct"] = bool(other_frac <= 0.02)
        # the full vocabulary on every ledger (zeros, not omissions)
        checks["full_vocabulary"] = all(
            set((s.get("attrs") or {}).get("ledger", {})
                .get("badputSeconds", {}))
            == set(gp.SERVING_BADPUT_CATEGORIES) for s in summaries)

        # -- 3) one sampled slow request, stage-by-stage from JSONL ------
        staged_ids = {s.get("trace_id") for s in spans
                      if s.get("name") == "accept"}
        sampled = [s for s in summaries
                   if s.get("trace_id") in staged_ids]
        slow = max(sampled, key=lambda s: (s.get("attrs") or {})
                   .get("ledger", {}).get("wallSeconds", 0.0),
                   default=None)
        slow_report = {}
        if slow is not None:
            timeline = reconstruct(sink, slow["trace_id"])
            names = timeline["names"]

            def in_order(*want) -> bool:
                i = 0
                for nm in names:
                    if i < len(want) and nm == want[i]:
                        i += 1
                return i == len(want)

            slow_report = {
                "request_id": slow["trace_id"],
                "wall_ms": round((slow.get("attrs") or {})
                                 .get("ledger", {})
                                 .get("wallSeconds", 0.0) * 1e3, 2),
                "stages": names,
            }
            checks["slow_request_reconstructed"] = in_order(
                "accept", "queue", "batch-form", "h2d", "device",
                "drain", "respond")
        else:
            checks["slow_request_reconstructed"] = False

        # rollup: the dashboard's /api/obs/serving source, off the sink
        rollup = gp.serving_rollup(sink)
        primary = next((m for m in rollup["models"]
                        if m["model"] == model
                        and m["role"] == "primary"), {})
        checks["rollup_has_model_row"] = bool(primary)
        checks["rollup_slo_tracked"] = "slo" in primary

        # -- 4) batcher hot-path overhead A/B ----------------------------
        from kubeflow_tpu.serving.batcher import MicroBatcher
        from kubeflow_tpu.serving.replica_state import ReplicaState
        from kubeflow_tpu.obs.registry import Registry
        obs_on = ServingObs(replica=ReplicaState(Registry()),
                            span_path=os.path.join(tmp, "ab.jsonl"),
                            sample_every=16)
        batcher = MicroBatcher(servable, max_batch=8, max_latency_ms=0.0)
        x = rng.standard_normal(
            (2, image_size, image_size, 3)).astype(np.float32)
        batcher.predict(x)   # warm the bucket
        arm_times: dict = {"on": [], "off": []}
        for rep in range(repeats):
            for arm in (("off", "on"), ("on", "off"))[rep % 2]:
                t0 = time.perf_counter()
                for i in range(ab_reqs):
                    if arm == "on":
                        ctx = obs_on.begin(model)
                        batcher.predict(x, ctx=ctx)
                        ctx.finish("ok")
                    else:
                        batcher.predict(x)
                arm_times[arm].append(
                    (time.perf_counter() - t0) / ab_reqs)
        req_on = statistics.median(arm_times["on"])
        req_off = statistics.median(arm_times["off"])
        # modeled: the measured per-request obs cost (begin + ledger
        # accumulation + summary emit + replica observe, amortized
        # sampling included) with no device work at all
        n_micro = 2000
        t0 = time.perf_counter()
        for _ in range(n_micro):
            ctx = obs_on.begin(model)
            ctx.stage("queue", 0.0, 0.0, seconds=1e-6)
            ctx.device(0.0, 0.0, goodput_s=1e-6, pad_waste_s=0.0)
            ctx.finish("ok")
        per_req_obs_s = (time.perf_counter() - t0) / n_micro
        modeled_pct = 100.0 * per_req_obs_s / req_on if req_on else 0.0
        measured_pct = 100.0 * (req_on - req_off) / req_off \
            if req_off else 0.0
        checks["overhead_lt_1pct"] = bool(modeled_pct < 1.0)
        batcher.shutdown()

        # -- 5) bounded queue sheds with 429, recorded in the ledger -----
        class _SlowServable:
            """Duck-typed servable whose device is a host sleep — the
            queue backs up for real."""
            name = "slowpoke"
            start_kind = "cold"

            def predict(self, instances):
                time.sleep(0.15)
                return np.asarray(instances)

            def metadata(self):
                return {"stats": {"request_count": 0,
                                  "predict_seconds": 0.0}}

        shed_server = ModelServer(host="127.0.0.1", port=0,
                                  max_batch=1, max_latency_ms=0.0,
                                  max_pending=2, sample_every=0,
                                  span_path=sink)
        shed_server.repository.add(_SlowServable())
        shed_port = shed_server.start()
        shed_url = (f"http://127.0.0.1:{shed_port}"
                    f"/v1/models/slowpoke:predict")
        shed_body = json.dumps({"instances": [[1.0]]}).encode()

        codes: list = []
        rids: list = []

        def shed_request(i: int) -> None:
            req = urllib.request.Request(
                shed_url, data=shed_body, method="POST",
                headers={"Content-Type": "application/json",
                         "x-request-id": f"shedreq{i:02d}"})
            try:
                with urllib.request.urlopen(req, timeout=30.0) as resp:
                    codes.append(resp.status)
                    rids.append(resp.headers.get("x-request-id"))
            except urllib.error.HTTPError as e:
                e.read()
                codes.append(e.code)
                rids.append(e.headers.get("x-request-id"))

        threads = [threading.Thread(target=shed_request, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
            time.sleep(0.005)
        for t in threads:
            t.join()
        shed_count = codes.count(429)
        shed_spans = [s for s in load_spans(sink)
                      if s.get("name") == gp.SERVING_REQUEST_SPAN
                      and (s.get("attrs") or {}).get("outcome") == "shed"]
        metrics_text = shed_server.metrics_text()
        shed_server.stop()
        checks["shed_returns_429"] = bool(shed_count >= 1)
        checks["shed_recorded_in_ledger"] = bool(
            len(shed_spans) >= shed_count
            and all((s.get("attrs") or {}).get("ledger", {})
                    .get("wallSeconds", -1.0) >= 0.0
                    for s in shed_spans))
        checks["shed_request_id_echoed"] = all(
            r and r.startswith("shedreq") for r in rids)
        checks["shed_counter_on_metrics"] = \
            "kftpu_serving_shed_total" in metrics_text

        # replica health endpoint over live HTTP
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz?verbose=1",
                timeout=10.0) as resp:
            health = json.loads(resp.read())
        row = next((m for m in health.get("models", [])
                    if m["model"] == model), {})
        checks["healthz_verbose_serves_model"] = bool(
            row.get("requests", 0) > 0 and "p99Ms" in row
            and "queueDepth" in row and "burnRates" in row)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=10.0) as resp:
            mtext = resp.read().decode()
        checks["metrics_series_present"] = all(
            name in mtext for name in (
                "kftpu_serving_p99_seconds",
                "kftpu_serving_queue_depth",
                "kftpu_serving_oldest_wait_seconds",
                "kftpu_serving_badput_seconds_total",
                "kftpu_serving_slo_burn_rate",
                "kftpu_serving_batch_fill_ratio"))
    finally:
        if server is not None:
            server.stop()
        shutil.rmtree(tmp, ignore_errors=True)

    return {
        "metric": "serving_obs_overhead_modeled",
        "value": round(modeled_pct, 4),
        "unit": "pct_of_request_time",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "model": model,
            "image_size": image_size,
            "latency_vs_offered_qps": latency_table,
            "latency_vs_offered_qps_window": window_table,
            "batching_ab": {
                "top_offered_qps": top,
                "ab_rounds": ab_rounds,
                "samples_per_arm": len(cont_pool),
                "window_p99_ms": win_p99_ms,
                "continuous_p99_ms": cont_p99_ms,
                "window_p50_ms": round(pct(win_pool, 0.50) * 1e3, 2),
                "continuous_p50_ms": round(pct(cont_pool, 0.50) * 1e3,
                                           2),
                "pr11_window_knee_ms": pr11_knee_ms,
                "knee_bar_ms": knee_bar_ms,
            },
            "batch_fill_mean": primary.get("meanFill"),
            "traced_requests": len(summaries),
            "other_residual_pct": round(100.0 * other_frac, 3),
            "worst_request_residual_pct": round(
                100.0 * worst_resid, 3),
            "slow_request": slow_report,
            "modeled_overhead_pct": round(modeled_pct, 4),
            "measured_ab_overhead_pct": round(measured_pct, 2),
            "request_time_on_ms": round(req_on * 1e3, 3),
            "request_time_off_ms": round(req_off * 1e3, 3),
            "per_request_obs_us": round(per_req_obs_s * 1e6, 2),
            "shed": {"requests": len(codes), "shed_429": shed_count},
            "serving_badput_categories":
                list(gp.SERVING_BADPUT_CATEGORIES),
            **checks,
            "all_checks_ok": all(checks.values()),
        },
        "_flops_per_chip": 0.0,
    }


def bench_serving_fleet(t_start: float | None = None) -> dict:
    """Serving fleet-resilience acceptance (ISSUE 12): the 3-replica
    kill-one-of-N availability soak (cluster/chaos.py ServingSoak) —
    real in-process ModelServers behind the FleetRouter under scripted
    serving chaos. Asserted:

    1. **Kill one of N**: SIGKILL a replica mid-load (plus a 5xx burst
       on a survivor and the victim's cold-slow-start restart) — client
       success ≥ 99.9% with ZERO duplicate deliveries/side effects;
       the restarted victim is probationally re-admitted.
    2. **Graceful drain**: drain a replica mid-load — zero in-flight
       requests lost, the router saw `draining` and routed away.
    3. **Wedge**: an accepts-never-responds replica is ejected by its
       breaker and, after recovery, probationally re-admitted.
    4. **Hedge A/B**: on the per-replica pause heavy-tail load, tail
       hedging must cut p99.9 vs no-hedging (recorded in PERF.md
       against the PR 11 single-replica baseline), its duplicated work
       ledgered as hedge_waste.
    5. **Ledger audit**: every fleet-request ledger's wall partition
       holds (upstream + retry + other ≈ wall, residual ≤ 2%) — a
       hedged or retried request's extra work is NAMED badput.

    Env knobs (serving_fleet_bench_smoke shrinks the geometry):
    KFTPU_BENCH_FLEET_{SECONDS,THREADS,HEDGE_REQS,REPLICAS}."""
    import os
    import shutil
    import tempfile

    from kubeflow_tpu.cluster.chaos import ServingSoak
    from kubeflow_tpu.obs import goodput as gp

    t_start = time.perf_counter() if t_start is None else t_start
    seconds = float(os.environ.get("KFTPU_BENCH_FLEET_SECONDS", "3"))
    threads = _env_int("KFTPU_BENCH_FLEET_THREADS", 6)
    hedge_reqs = _env_int("KFTPU_BENCH_FLEET_HEDGE_REQS", 400)
    replicas = _env_int("KFTPU_BENCH_FLEET_REPLICAS", 3)

    tmp = tempfile.mkdtemp(prefix="kftpu-fleet-")
    sink = os.path.join(tmp, "fleet.jsonl")
    try:
        soak = ServingSoak(span_path=sink, replicas=replicas,
                           seconds=seconds, threads=threads,
                           hedge_requests=hedge_reqs)
        report = soak.run()
        kill, drain = report["kill"], report["drain"]
        wedge, hedge = report["wedge"], report["hedge_ab"]
        audit = report["audit"]
        checks = {
            # SIGKILL one of N: success ≥ 99.9%, at-most-once delivery
            "kill_success_ge_999": kill["success_pct"] >= 99.9,
            "kill_zero_duplicate_side_effects":
                audit["duplicate_side_effects"] == 0
                and audit["audited_server_completions"] > 0,
            "killed_replica_readmitted": bool(
                kill["victim_readmitted"]),
            # graceful drain: zero in-flight lost, router routed away
            "drain_zero_loss": drain["in_flight_lost"] == 0
                and drain["success_pct"] == 100.0,
            "drain_advertised": bool(drain["router_saw_draining"]),
            # wedged replica: breaker ejection + probation
            "wedge_ejected": bool(wedge["ejected"]),
            "wedge_readmitted": bool(wedge["readmitted"]),
            "wedge_success_ge_999": wedge["success_pct"] >= 99.9,
            # hedging measurably cuts the tail, waste is named
            "hedging_cuts_p999": bool(hedge["hedging_cuts_p999"]),
            "hedge_waste_ledgered": audit["hedge_waste_s"] > 0,
            "retry_badput_named": audit["retry_badput_s"] > 0,
            # ledgers sum to wall-clock (≤2% residual)
            "ledgers_sum_to_wall": bool(audit["ledger_sum_ok"]),
            "other_residual_le_2pct":
                audit["other_residual_pct"] <= 2.0,
        }
        rollup = gp.fleet_rollup(sink)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    worst_success = min(kill["success_pct"], drain["success_pct"],
                        wedge["success_pct"])
    return {
        "metric": "serving_fleet_kill_success_pct",
        "value": kill["success_pct"],
        "unit": "pct",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "replicas": replicas,
            "kill": {k: v for k, v in kill.items() if k != "fleet"},
            "drain": drain,
            "wedge": wedge,
            "hedge_ab": hedge,
            "audit": audit,
            "worst_scenario_success_pct": worst_success,
            "fleet_rollup": rollup,
            "fleet_badput_categories":
                list(gp.FLEET_BADPUT_CATEGORIES),
            **checks,
            "all_checks_ok": all(checks.values()),
        },
        "_flops_per_chip": 0.0,
    }


def bench_autoscaler(t_start: float | None = None) -> dict:
    """Serving autoscaler drill (ISSUE 18): a live FleetAutoscaler
    over in-process replicas (cluster/chaos.py ServingReplicaHarness)
    under a load step. Asserted:

    1. **Scale-up is fast and lands warm**: saturating load on the
       single seed replica pushes queue-depth/oldest-wait over the
       thresholds; the autoscaler launches a replica whose
       ``startKind`` reads warm (the PR 9 warm-pod contract) and whose
       FIRST inference completes within ~1–2 s of the scale decision —
       not a cold XLA compile away.
    2. **Scale-down is zero-loss**: after sustained idle the extra
       replica is gracefully drained (flushed cohort, zero in-flight
       lost — the drain report is kept on the scale event) before
       leaving the router.
    3. **Flap guard**: no two scale events land within the cooldown
       window, and continued idle inside the cooldown after the drain
       produces no further events — the policy never flaps against
       the drain it just started.

    Env knobs (autoscaler_bench_smoke shrinks the geometry):
    KFTPU_BENCH_AS_{SECONDS,QPS,COOLDOWN}."""
    import os
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from kubeflow_tpu.cluster.chaos import ServingReplicaHarness
    from kubeflow_tpu.controllers.autoscaler import (AutoscalerConfig,
                                                     FleetAutoscaler)
    from kubeflow_tpu.serving.fleet import FleetConfig, FleetRouter

    t_start = time.perf_counter() if t_start is None else t_start
    seconds = float(os.environ.get("KFTPU_BENCH_AS_SECONDS", "2.5"))
    qps = _env_int("KFTPU_BENCH_AS_QPS", 150)
    cooldown_s = float(os.environ.get("KFTPU_BENCH_AS_COOLDOWN", "1.5"))

    tmp = tempfile.mkdtemp(prefix="kftpu-as-")
    sink = os.path.join(tmp, "autoscaler.jsonl")
    os.environ["KFTPU_SPAN_PATH"] = sink
    harnesses: dict = {}
    router = None
    checks: dict = {}
    try:
        def launch(name: str) -> str:
            # predict is a 50 ms host sleep behind max_batch=2: one
            # replica's ceiling is ~40 rows/s, so the load step
            # saturates it and the queue gauges move for real
            h = ServingReplicaHarness(name, model="as", predict_s=0.05,
                                      max_batch=2, max_latency_ms=1.0)
            url = h.start()
            # the warm-pod contract (PR 9): a scaled-up replica comes
            # off the pool with its model loaded + executables cached
            h.servable.start_kind = "warm"
            h.server.replica.set_start_kind(h.model, "warm")
            harnesses[name] = h
            return url

        launched_at: dict = {}

        def launcher() -> tuple:
            name = f"as{len(harnesses)}"
            url = launch(name)
            launched_at[name] = time.perf_counter()
            return name, url

        def stopper(name: str) -> None:
            h = harnesses.pop(name, None)
            if h is not None:
                h.stop()

        seed_url = launch("as0")
        router = FleetRouter(config=FleetConfig(
            poll_interval_s=0.1, poll_timeout_s=1.0))
        router.add_replica("as0", seed_url)
        cfg = AutoscalerConfig(
            min_replicas=1, max_replicas=2,
            burn_up_threshold=1e9,      # this drill scales on the queue
            queue_up_threshold=5.0, oldest_wait_up_s=0.2,
            idle_down_s=0.6, cooldown_s=cooldown_s,
            poll_interval_s=0.05)
        scaler = FleetAutoscaler(router, launcher, stopper=stopper,
                                 config=cfg, fleet="bench")
        scaler.adopt("as0", seed_url)

        body = json.dumps({"instances": [[1.0]]}).encode()

        def fire(url: str, timeout: float = 30.0) -> bool:
            req = urllib.request.Request(
                f"{url}/v1/models/as:predict", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    r.read()
                return True
            except (urllib.error.URLError, OSError):
                return False

        # -- phase 1: load step onto the seed replica ------------------
        stop_load = threading.Event()

        def load_loop():
            import concurrent.futures
            pool = concurrent.futures.ThreadPoolExecutor(max_workers=32)
            next_at = time.perf_counter()
            while not stop_load.is_set():
                now = time.perf_counter()
                if now < next_at:
                    time.sleep(min(next_at - now, 0.01))
                    continue
                pool.submit(fire, seed_url)
                next_at += 1.0 / qps
            pool.shutdown(wait=False, cancel_futures=True)

        loader = threading.Thread(target=load_loop, daemon=True)
        loader.start()
        deadline = time.perf_counter() + seconds
        first_inference_s = None
        t_decision = None
        while time.perf_counter() < deadline:
            decision = scaler.step()
            if decision.direction == "up":
                t_decision = time.perf_counter()
                new = scaler.events[-1]["replica"]
                # the acceptance clock: scale decision → first
                # completed inference on the NEW replica
                ok = fire(scaler.replicas[new], timeout=10.0)
                if ok:
                    first_inference_s = \
                        time.perf_counter() - t_decision
                break
            time.sleep(0.05)
        stop_load.set()
        loader.join(timeout=5.0)

        up_events = [e for e in scaler.events if e["direction"] == "up"]
        checks["scale_up_fired"] = bool(up_events)
        new_name = up_events[0]["replica"] if up_events else None
        start_kind = ""
        if new_name and new_name in harnesses:
            snap = harnesses[new_name].server.replica.snapshot()
            rows = snap.get("models", [])
            start_kind = rows[0].get("startKind", "") if rows else ""
        checks["scale_up_landed_warm"] = start_kind in ("warm", "aot")
        checks["first_scaled_inference_le_2s"] = bool(
            first_inference_s is not None and first_inference_s <= 2.0)

        # -- phase 2: sustained idle → zero-loss graceful scale-down ----
        down_deadline = time.perf_counter() + max(4.0, 6 * cooldown_s)
        while time.perf_counter() < down_deadline:
            scaler.step()
            if any(e["direction"] == "down" for e in scaler.events):
                break
            time.sleep(0.05)
        down_events = [e for e in scaler.events
                       if e["direction"] == "down"]
        checks["scale_down_fired"] = bool(down_events)
        report = down_events[0].get("drain_report", {}) \
            if down_events else {}
        checks["scale_down_zero_loss"] = bool(
            down_events and report.get("failed", 1) == 0
            and report.get("inFlightRemaining", 1) == 0)
        checks["back_to_min_replicas"] = len(scaler.replicas) == 1

        # -- phase 3: flap guard ---------------------------------------
        # keep stepping inside the cooldown the scale-down opened: the
        # policy must hold, not oscillate add/drain
        n_events = len(scaler.events)
        flap_until = time.perf_counter() + 0.5 * cooldown_s
        while time.perf_counter() < flap_until:
            scaler.step()
            time.sleep(0.02)
        checks["no_event_inside_cooldown_window"] = \
            len(scaler.events) == n_events
        gaps_ok = all(
            b["t"] - a["t"] >= cooldown_s * 0.999
            for a, b in zip(scaler.events, scaler.events[1:]))
        checks["event_spacing_ge_cooldown"] = gaps_ok
    finally:
        if router is not None:
            router.close()
        for h in list(harnesses.values()):
            h.stop()
        os.environ.pop("KFTPU_SPAN_PATH", None)
        from kubeflow_tpu.obs.trace import load_spans, \
            reset_default_tracers
        reset_default_tracers()
        try:
            scale_spans = [s for s in load_spans(sink)
                           if s.get("component") == "autoscaler"]
        except OSError:
            scale_spans = []
        shutil.rmtree(tmp, ignore_errors=True)

    checks["scale_events_on_trace"] = len(scale_spans) >= 2

    return {
        "metric": "autoscaler_first_scaled_inference",
        "value": round(first_inference_s, 3)
        if first_inference_s is not None else None,
        "unit": "s_from_scale_decision",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "offered_qps": qps,
            "load_seconds": seconds,
            "cooldown_s": cooldown_s,
            "scale_events": [
                {k: (round(v, 3) if isinstance(v, float) else v)
                 for k, v in e.items() if k != "drain_report"}
                for e in scaler.events],
            "scale_up_start_kind": start_kind,
            "drain_report": report,
            "trace_scale_spans": len(scale_spans),
            **checks,
            "all_checks_ok": all(checks.values()),
        },
        "_flops_per_chip": 0.0,
    }


def bench_katib_child() -> dict:
    """ONE real hyperparameter trial, run in its own process (trial
    startup is a process property — exactly the warm-start child's
    framing): train a few steps of the small transformer at the lr the
    Experiment reconciler assigned, with the runtime-lr schedule on and
    the shared AOT volume mounted. Every trial after the first must load
    the SAME serialized executable (the compile-shape fingerprint drops
    runtime constants), which is the whole warm-start-fraction bar."""
    import os

    root = os.environ["KFTPU_KATIB_ROOT"]
    os.environ["KFTPU_COMPILE_CACHE_DIR"] = os.path.join(root, "cache")
    os.environ.setdefault("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
    from kubeflow_tpu.runtime.worker import train
    r = train(workload="transformer",
              steps=_env_int("KFTPU_BENCH_KATIB_STEPS", 4),
              global_batch=8, sync_every=2, seed=0,
              learning_rate=float(os.environ["KFTPU_KATIB_LR"]),
              runtime_schedule=True,
              aot=True, aot_dir=os.path.join(root, "aot"))
    return {
        "metric": "katib_trial", "value": r.time_to_first_step_s,
        "unit": "seconds", "vs_baseline": None, "mfu": None,
        "extras": {
            "start_kind": r.start_kind,
            "lr": float(os.environ["KFTPU_KATIB_LR"]),
            "loss": float(r.final_metrics.get("loss", 0.0)),
        },
        "_flops_per_chip": 0.0,
    }


def bench_katib(t_start: float | None = None) -> dict:
    """Hyperparameter-search acceptance (ISSUE 19) in four arms:

    1. **Burst vs sequential**: a 200-trial Experiment at parallelism 16
       driven through the real reconciler + operator on FakeCluster vs
       the same machinery at parallelism 1 — the burst must beat the
       sequential arm on trials/hour while never exceeding its
       parallelism bound.
    2. **Median early stopping**: a seeded bad trial (objective below
       the peer median at its window) must be killed mid-flight and its
       remaining chip-time ledgered as saved.
    3. **Warm-start fraction**: a REAL sequential search (each trial a
       fresh process running train() at its assigned lr, sharing one
       AOT volume) must report warmStartFraction >= 0.9 — every trial
       after the first loads the first trial's executable because the
       compile-shape key drops runtime constants.
    4. **Ledger honesty**: each real trial's goodput ledger categories
       must sum to its wall-clock within 2% (categories_sum_ok).

    The parent never imports jax: the sim arms are control-plane only
    and the real trials own the backend in child processes.

    Env knobs (katib_bench_smoke shrinks the geometry):
    KFTPU_BENCH_KATIB_{TRIALS,PARALLELISM,SEQ_TRIALS,REAL_TRIALS,STEPS}.
    """
    import json as _json
    import os
    import shutil
    import subprocess
    import tempfile

    from kubeflow_tpu.api import k8s
    from kubeflow_tpu.api.experiment import (EXPERIMENT_API_VERSION,
                                             EXPERIMENT_KIND)
    from kubeflow_tpu.cluster import FakeCluster
    from kubeflow_tpu.controllers.experiment import ExperimentReconciler
    from kubeflow_tpu.controllers.runtime import Manager
    from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
    from kubeflow_tpu.katib.studyjob import OBSERVATION_ANNOTATION
    from kubeflow_tpu.obs.goodput import categories_sum_ok, ledger_for
    from kubeflow_tpu.obs.trace import TRACE_ID_ANNOTATION

    t_start = time.perf_counter() if t_start is None else t_start
    n_burst = _env_int("KFTPU_BENCH_KATIB_TRIALS", 200)
    parallelism = _env_int("KFTPU_BENCH_KATIB_PARALLELISM", 16)
    n_seq = min(n_burst, _env_int("KFTPU_BENCH_KATIB_SEQ_TRIALS", 30))
    n_real = _env_int("KFTPU_BENCH_KATIB_REAL_TRIALS", 4)

    def experiment_manifest(name, n, par, **spec_extra):
        spec = {
            "objective": {"type": "maximize", "metric": "accuracy"},
            "algorithm": {"name": "random"},
            "parameters": [{"name": "--lr", "type": "double",
                            "min": 0.05, "max": 0.5}],
            "maxTrials": n, "parallelism": par,
            "trialTemplate": {
                "kind": "TPUJob",
                "spec": {"replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "train", "image": "trainer:v1"}]}},
                }}},
            },
        }
        spec.update(spec_extra)
        return {"apiVersion": EXPERIMENT_API_VERSION,
                "kind": EXPERIMENT_KIND,
                "metadata": {"name": name, "namespace": "kubeflow"},
                "spec": spec}

    def new_env(pools, span_path=None):
        cluster = FakeCluster()
        for i in range(pools):
            cluster.add_tpu_slice_nodes("v5e-8", pool=f"p{i}")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        mgr.add(ExperimentReconciler(seed=7, span_path=span_path))
        return cluster, mgr

    def drive_to_done(cluster, mgr, name, max_rounds=4000):
        for _ in range(max_rounds):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                              "kubeflow", name)
            if k8s.condition_true(exp, "Succeeded") or \
                    k8s.condition_true(exp, "Failed"):
                return exp
        return exp

    def trial_env(pod):
        return {e["name"]: e.get("value")
                for c in pod["spec"]["containers"]
                for e in c.get("env", [])}

    def sim_rate(name, n, par):
        """Drive n instantly-completing trials at the given parallelism
        through the real control plane; return (trials/hour, max
        in-flight, final status)."""
        cluster, mgr = new_env(pools=par)
        in_flight = [0]

        def on_running(pod):
            live = [j for j in cluster.list("tpu.kubeflow.org/v1alpha1",
                                            "TPUJob", "kubeflow")
                    if not (k8s.condition_true(j, "Succeeded") or
                            k8s.condition_true(j, "Failed"))]
            in_flight[0] = max(in_flight[0], len(live))
            trial = trial_env(pod).get("KFTPU_TRIAL")
            if trial:
                job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  "kubeflow", trial)
                job["metadata"].setdefault("annotations", {})[
                    OBSERVATION_ANNOTATION] = _json.dumps(
                        {"accuracy": 0.5})
                cluster.apply(job)
            cluster.set_pod_phase(k8s.namespace_of(pod, "default"),
                                  k8s.name_of(pod), "Succeeded")
        cluster.on_pod_running = on_running
        cluster.create(experiment_manifest(name, n, par))
        t0 = time.perf_counter()
        exp = drive_to_done(cluster, mgr, name)
        elapsed = max(time.perf_counter() - t0, 1e-9)
        st = exp.get("status") or {}
        done = st.get("trialsSucceeded", 0)
        return done / (elapsed / 3600.0), in_flight[0], st

    checks: dict = {}

    # -- arm 1: burst vs sequential ------------------------------------
    burst_rate, burst_peak, burst_st = sim_rate("burst", n_burst,
                                                parallelism)
    seq_rate, _, seq_st = sim_rate("seq", n_seq, 1)
    checks["burst_completed"] = \
        burst_st.get("trialsSucceeded", 0) == n_burst
    checks["parallelism_bounded"] = burst_peak <= parallelism
    checks["burst_beats_sequential"] = burst_rate > seq_rate

    # -- arm 2: median early stopping with saved chip-hours ------------
    stop_dir = tempfile.mkdtemp(prefix="kftpu-katib-stop-")
    stop_path = os.path.join(stop_dir, "spans.jsonl")
    try:
        cluster, mgr = new_env(pools=4, span_path=stop_path)
        cluster.on_pod_running = lambda pod: None
        cluster.create(experiment_manifest(
            "stopper", 4, 4,
            earlyStopping={"policy": "median", "minTrials": 2,
                           "startWindow": 2}))
        for _ in range(4):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
        exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                          "kubeflow", "stopper")
        trials = (exp.get("status") or {}).get("trials") or []

        def write_spans(tid, values, wall=None):
            with open(stop_path, "a") as f:
                if wall:
                    f.write(_json.dumps({
                        "trace_id": tid, "span_id": "w", "parent_id": "",
                        "name": "trial", "component": "bench",
                        "start": 0.0, "end": float(wall)}) + "\n")
                for w, v in enumerate(values):
                    f.write(_json.dumps({
                        "trace_id": tid, "span_id": f"s{w}",
                        "parent_id": "", "name": "objective",
                        "component": "worker", "start": float(w),
                        "end": float(w),
                        "attrs": {"step": w * 10, "window": w,
                                  "accuracy": v}}) + "\n")

        # two trials finish at wall=60s; of the two still running, the
        # seeded bad one trails the peer median and must die
        good, bad = [0.6, 0.7, 0.8], [0.2, 0.15, 0.1]
        for i, t in enumerate(trials):
            if i < 2:
                write_spans(t["traceId"], good, wall=60.0)
            else:
                write_spans(t["traceId"], good if i == 2 else bad)
        for i, t in enumerate(trials[:2]):
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              "kubeflow", t["name"])
            job["metadata"].setdefault("annotations", {})[
                OBSERVATION_ANNOTATION] = _json.dumps({"accuracy": 0.8})
            cluster.apply(job)
            for pod in cluster.list("v1", "Pod", "kubeflow"):
                if k8s.name_of(pod).startswith(t["name"]):
                    cluster.set_pod_phase("kubeflow", k8s.name_of(pod),
                                          "Succeeded")
        mgr.run_pending()
        recon = next(c.reconciler for c in mgr.controllers
                     if isinstance(c.reconciler, ExperimentReconciler))
        recon.reconcile(cluster, ("kubeflow", "stopper"))
        exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                          "kubeflow", "stopper")
        st = exp.get("status") or {}
        stopped = [t for t in (st.get("trials") or [])
                   if t.get("stoppedEarly")]
        checks["early_stopped_a_seeded_bad_trial"] = len(stopped) >= 1
        checks["stopped_chip_hours_ledgered_as_saved"] = bool(
            stopped and stopped[0].get("chipSecondsSaved", 0) > 0 and
            (st.get("chipHours") or {}).get("saved", 0) > 0)
        stop_extras = {
            "trials_stopped": len(stopped),
            "chip_hours_saved": (st.get("chipHours") or {}).get("saved"),
        }
    finally:
        shutil.rmtree(stop_dir, ignore_errors=True)

    # -- arms 3+4: real trials — warm-start fraction + ledger honesty --
    real_dir = tempfile.mkdtemp(prefix="kftpu-katib-real-")
    real_path = os.path.join(real_dir, "spans.jsonl")
    trial_rows: list = []
    try:
        cluster, mgr = new_env(pools=1, span_path=real_path)

        started: set = set()

        def on_running(pod):
            envm = trial_env(pod)
            trial = envm.get("KFTPU_TRIAL")
            # one training child per TRIAL, not per gang pod (a v5e-8
            # gang runs two hosts; the trial is still one program)
            if trial and trial not in started:
                started.add(trial)
                job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  "kubeflow", trial)
                trace = k8s.annotations_of(job).get(TRACE_ID_ANNOTATION)
                args = [a for c in pod["spec"]["containers"]
                        for a in c.get("args", [])]
                lr = next(a.split("=", 1)[1] for a in args
                          if a.startswith("--lr="))
                env = {**os.environ, "KFTPU_BENCH_SUBBENCH": "1",
                       "KFTPU_KATIB_ROOT": real_dir,
                       "KFTPU_KATIB_LR": lr,
                       "KFTPU_SPAN_PATH": real_path,
                       "KFTPU_TRACE_ID": trace or ""}
                res = subprocess.run(
                    [sys.executable, __file__, "--mode", "katib-child"],
                    env=env, capture_output=True, text=True, timeout=900)
                row = None
                for line in reversed(res.stdout.splitlines()):
                    if line.strip().startswith("{"):
                        row = _json.loads(line)
                        break
                if row is None:
                    raise RuntimeError(
                        f"katib trial child emitted no JSON "
                        f"(rc={res.returncode}): {res.stderr[-2000:]}")
                trial_rows.append({"trial": trial, "trace": trace,
                                   "first_step_s": row["value"],
                                   **row["extras"]})
            cluster.set_pod_phase(k8s.namespace_of(pod, "default"),
                                  k8s.name_of(pod), "Succeeded")
        cluster.on_pod_running = on_running
        m = experiment_manifest("real", n_real, 1)
        m["spec"]["objective"] = {"type": "minimize", "metric": "loss"}
        m["spec"]["algorithm"] = {"name": "grid",
                                  "settings": {"DefaultGrid": n_real}}
        cluster.create(m)
        exp = drive_to_done(cluster, mgr, "real", max_rounds=200)
        st = exp.get("status") or {}
        warm_fraction = st.get("warmStartFraction")
        kinds = [t.get("startKind") for t in (st.get("trials") or [])]
        ledger_ok = []
        for t in (st.get("trials") or []):
            ledger = ledger_for(real_path, t.get("traceId") or "")
            if ledger.get("wallSeconds"):
                ledger_ok.append(categories_sum_ok(ledger,
                                                   tolerance=0.02))
        checks["real_search_succeeded"] = bool(
            k8s.condition_true(exp, "Succeeded") and
            st.get("trialsSucceeded", 0) == n_real)
        checks["warm_start_fraction_ok"] = bool(
            warm_fraction is not None and warm_fraction >= 0.9)
        checks["ledger_categories_sum_to_wall"] = bool(
            ledger_ok and len(ledger_ok) == n_real and all(ledger_ok))
    finally:
        shutil.rmtree(real_dir, ignore_errors=True)

    return {
        "metric": "katib_burst_trials_per_hour",
        "value": round(burst_rate, 1),
        "unit": "trials/hour",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "burst_trials": n_burst,
            "parallelism": parallelism,
            "burst_peak_in_flight": burst_peak,
            "sequential_trials": n_seq,
            "sequential_trials_per_hour": round(seq_rate, 1),
            "speedup_vs_sequential": round(burst_rate / max(seq_rate,
                                                            1e-9), 2),
            "burst_chip_hours": burst_st.get("chipHours"),
            **stop_extras,
            "real_trials": trial_rows,
            "real_warm_start_fraction": warm_fraction,
            "real_start_kinds": kinds,
            "real_best": st.get("bestTrial"),
            **checks,
            "all_checks_ok": all(checks.values()),
            "bench_wall_s": round(time.perf_counter() - t_start, 1),
        },
        "_flops_per_chip": 0.0,
    }


def bench_warmstart_child() -> dict:
    """One warm-start arm, run in its OWN process (the whole point is
    process-fresh startup): train a few steps of the small transformer
    and report startup→first-step plus the compile evidence. The parent
    (bench_warmstart) owns the cache/AOT dirs; the arm name only flips
    the AOT knob — warmth comes from whatever the dirs already hold."""
    import os

    arm = os.environ["KFTPU_WARMSTART_ARM"]
    root = os.environ["KFTPU_WARMSTART_ROOT"]
    os.environ["KFTPU_COMPILE_CACHE_DIR"] = os.path.join(root, "cache")
    # tiny CPU models compile in under the persistence threshold; pin it
    # so the cold arm actually populates the cache
    os.environ.setdefault("KFTPU_COMPILE_CACHE_MIN_SECS", "0")
    from kubeflow_tpu.runtime.compile_cache import compile_stats
    from kubeflow_tpu.runtime.worker import train
    steps = _env_int("KFTPU_BENCH_WARMSTART_STEPS", 3)
    r = train(workload="transformer", steps=steps, global_batch=8,
              sync_every=2, seed=0,
              aot=(arm != "warm"),
              aot_dir=os.path.join(root, "aot"))
    s = compile_stats()
    return {
        "metric": "warmstart_child", "value": r.time_to_first_step_s,
        "unit": "seconds", "vs_baseline": None, "mfu": None,
        "extras": {
            "arm": arm,
            "start_kind": r.start_kind,
            "xla_backend_compiles": s["xla_backend_compiles"],
            "cache_hits": s["cache_hits"],
            "loss": float(r.final_metrics.get("loss", 0.0)),
        },
        "_flops_per_chip": 0.0,
    }


def bench_warmstart(t_start: float | None = None) -> dict:
    """Time-to-first-step cold vs cache-warm vs AOT on the SAME config
    (ISSUE 9 acceptance): each arm is a fresh process (startup is a
    process property) sharing one cache/AOT volume —

    - **cold**: empty cache, AOT export ON (the first-bind path: full
      XLA compile + executable export);
    - **warm**: populated persistent cache, AOT OFF (trace + lower +
      cache load — the pre-AOT warm restart);
    - **aot**: serialized executable loaded (no trace, no lower, no
      XLA — runtime/aot.py).

    Asserted in extras: AOT ≤ warm ≤ cold on medians, the AOT arm
    loaded a serialized executable (start_kind == "aot") with ZERO XLA
    backend compiles observed (cache requests minus hits — see
    runtime/compile_cache.compile_stats), and loss parity across arms.
    Then the sched/elastic A/B re-runs with the MEASURED restart costs
    (scheduler/sim.py compare_restart_costs): restarts were modeled
    free in every previously published table, so extras.sim_restart_
    costs is the honest version — and the warm/aot arms are what the
    warm-start stack buys back. The parent never imports jax: children
    own the backend, so this mode works on a single exclusive TPU too.

    Env knobs (warmstart_bench_smoke shrinks the geometry):
    KFTPU_BENCH_WARMSTART_{STEPS,REPEATS,SEEDS,JOBS,TICK_SECONDS}."""
    import os
    import shutil
    import statistics
    import subprocess
    import tempfile

    t_start = time.perf_counter() if t_start is None else t_start
    repeats = _env_int("KFTPU_BENCH_WARMSTART_REPEATS", 3)
    root = tempfile.mkdtemp(prefix="kftpu-warmstart-")

    def run_arm(arm: str, arm_root: str) -> dict:
        env = {**os.environ, "KFTPU_WARMSTART_ARM": arm,
               "KFTPU_WARMSTART_ROOT": arm_root,
               "KFTPU_BENCH_SUBBENCH": "1"}
        res = subprocess.run(
            [sys.executable, __file__, "--mode", "warmstart-child"],
            env=env, capture_output=True, text=True, timeout=600)
        for line in reversed(res.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                row = json.loads(line)
                return {"first_step_s": row["value"], **row["extras"]}
        raise RuntimeError(f"warmstart arm {arm!r} emitted no JSON "
                           f"(rc={res.returncode}): {res.stderr[-2000:]}")

    arms: dict = {"cold": [], "warm": [], "aot": []}
    try:
        main_root = os.path.join(root, "main")
        os.makedirs(main_root)
        # cold arms each get a FRESH volume; the first one doubles as
        # the populator of the shared volume the warm/aot arms read
        arms["cold"].append(run_arm("cold", main_root))
        for i in range(1, repeats):
            fresh = os.path.join(root, f"cold-{i}")
            os.makedirs(fresh)
            arms["cold"].append(run_arm("cold", fresh))
            shutil.rmtree(fresh, ignore_errors=True)
        # unmeasured priming run: the cold (AOT-on) arm cached the
        # NON-donating step program (trainstep.build_compiled), so the
        # first AOT-off restart still compiles the donating variant
        # once — prime it out so the warm arm measures the steady-state
        # cache-warm restart every subsequent gang restart actually pays
        run_arm("warm", main_root)
        for _ in range(repeats):
            arms["warm"].append(run_arm("warm", main_root))
        for _ in range(repeats):
            arms["aot"].append(run_arm("aot", main_root))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    med = {a: statistics.median(r["first_step_s"] for r in rows)
           for a, rows in arms.items()}
    aot_rows, warm_rows = arms["aot"], arms["warm"]
    losses = {round(r["loss"], 6) for rows in arms.values()
              for r in rows}
    checks = {
        "aot_loaded_serialized_executable": all(
            r["start_kind"] == "aot" for r in aot_rows),
        "aot_no_xla_compile": all(
            r["xla_backend_compiles"] == 0 for r in aot_rows),
        "warm_no_xla_compile": all(
            r["xla_backend_compiles"] == 0 for r in warm_rows),
        "ordering_aot_le_warm_le_cold": bool(
            med["aot"] <= med["warm"] <= med["cold"]),
        "loss_parity_across_arms": len(losses) == 1,
    }

    # the sched/elastic A/B, re-run with the measured costs mapped to
    # device ticks (one tick ~ tick_seconds of device time — the sim's
    # abstract unit; 20s ≈ a checkpoint interval at the bench cadence)
    tick_s = float(os.environ.get("KFTPU_BENCH_WARMSTART_TICK_SECONDS",
                                  "20"))
    from kubeflow_tpu.scheduler.sim import compare_restart_costs
    seeds = list(range(_env_int("KFTPU_BENCH_WARMSTART_SEEDS", 3)))
    n_jobs = _env_int("KFTPU_BENCH_WARMSTART_JOBS", 16)
    costs = {"free": 0.0,
             **{a: round(med[a] / tick_s, 4) for a in med}}
    t0 = time.perf_counter()
    sim = compare_restart_costs(seeds, costs, n_jobs=n_jobs)
    sim_s = time.perf_counter() - t0

    return {
        "metric": "warmstart_time_to_first_step",
        "value": round(med["cold"] / med["aot"], 3)
        if med["aot"] else None,
        "unit": "cold_over_aot_first_step",
        "vs_baseline": None,
        "mfu": None,
        "extras": {
            "first_step_s": {a: round(v, 3) for a, v in med.items()},
            "repeats": repeats,
            "arms": arms,
            **checks,
            "all_checks_ok": all(checks.values()),
            "sim_restart_costs": {
                "tick_seconds": tick_s,
                "costs_ticks": costs,
                "seeds": len(seeds),
                "jobs_per_seed": n_jobs,
                "table": sim,
                "sim_wall_s": round(sim_s, 1),
            },
        },
        "_flops_per_chip": 0.0,
    }


def _run_sub_bench(mode: str, budget_s: float) -> dict:
    """Run ``bench.py --mode <mode>`` as a subprocess with a hard
    wall-clock budget and return its JSON row. The child inherits the
    environment, so the CPU-fallback marker (KFTPU_BENCH_BACKEND_ERROR)
    and JAX_PLATFORMS pins propagate without re-probing the backend."""
    import os
    import subprocess
    res = subprocess.run([sys.executable, __file__, "--mode", mode],
                         env={**os.environ, "KFTPU_BENCH_SUBBENCH": "1"},
                         capture_output=True, text=True, timeout=budget_s)
    for line in reversed(res.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"sub-bench {mode} emitted no JSON row "
                       f"(rc={res.returncode})")


def main(argv=None) -> int:
    t_start = time.perf_counter()
    import argparse
    import os

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode", default="all",
                   choices=["all", "resnet", "resnet-fused", "lm",
                            "lm-long", "serving", "serving-obs",
                            "serving-fleet", "autoscaler",
                            "fused-blocks",
                            "weight-update", "kernels", "chaos",
                            "ctrl-chaos", "ctrl-scale", "sentinel",
                            "input", "sched",
                            "health", "obs", "goodput", "comm",
                            "multislice",
                            "warmstart", "warmstart-child",
                            "katib", "katib-child"])
    p.add_argument("--routing-out",
                   default="bench-matrix/fused_routing_measured.json",
                   help="where --mode fused-blocks writes the measured "
                        "routing table (TPU runs only)")
    args = p.parse_args(argv)

    if args.mode == "warmstart":
        # the PARENT must never touch jax: each arm child owns the
        # backend (a parent-held TPU would starve every child), and the
        # sim side is jax-free — so this dispatch precedes the probe
        row = bench_warmstart(t_start=t_start)
        print(json.dumps(row))
        print(f"# mode=warmstart extras={row['extras']}",
              file=sys.stderr, flush=True)
        return 0

    if args.mode == "katib":
        # same contract as warmstart: the parent is jax-free (the sim
        # arms are control-plane only, the real trials own the backend
        # in child processes), so this dispatch precedes the probe too
        row = bench_katib(t_start=t_start)
        print(json.dumps(row))
        print(f"# mode=katib extras={row['extras']}",
              file=sys.stderr, flush=True)
        return 0

    if args.mode == "ctrl-scale":
        # control-plane only (FakeCluster + the real controllers):
        # jax-free by construction, so it precedes the probe too
        row = bench_ctrl_scale(t_start=t_start)
        print(json.dumps(row))
        print(f"# mode=ctrl-scale extras={row['extras']}",
              file=sys.stderr, flush=True)
        return 0

    # the fallback child carries this marker: never probe/respawn again
    # (a second failure must end the chain, not fork a grandchild)
    backend_ok = bool(os.environ.get("KFTPU_BENCH_BACKEND_ERROR")) or \
        _probe_backend()
    if not backend_ok:
        # the probe thread is stuck inside backend init; a fresh
        # CPU-pinned process is the only clean escape
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PALLAS_AXON_POOL_IPS": "",
               "KFTPU_BENCH_BACKEND_ERROR": "tpu backend unreachable"}
        import subprocess
        return subprocess.call([sys.executable, __file__] +
                               (argv or sys.argv[1:]), env=env)
    import jax

    from kubeflow_tpu.runtime.compile_cache import enable_compilation_cache

    # opt-in persistent compile cache (KFTPU_COMPILE_CACHE_DIR): makes the
    # startup_first_step_s extra a WARM number — recorded so the artifact
    # is never misread as a cold measurement
    cache_dir = enable_compilation_cache()

    dev = jax.devices()[0]
    platform = dev.platform
    on_tpu = platform == "tpu"

    if args.mode == "resnet-fused":
        row = bench_resnet(fused=True, t_start=t_start)
    elif args.mode == "lm":
        row = bench_lm(t_start=t_start)
    elif args.mode == "lm-long":
        row = bench_lm(t_start=t_start, long_context=True)
    elif args.mode == "serving":
        row = bench_serving(t_start=t_start)
    elif args.mode == "serving-obs":
        row = bench_serving_obs(t_start=t_start)
    elif args.mode == "serving-fleet":
        row = bench_serving_fleet(t_start=t_start)
    elif args.mode == "autoscaler":
        row = bench_autoscaler(t_start=t_start)
    elif args.mode == "fused-blocks":
        row = bench_fused_blocks(t_start=t_start,
                                 routing_out=args.routing_out)
    elif args.mode == "weight-update":
        row = bench_weight_update(t_start=t_start)
    elif args.mode == "kernels":
        row = bench_kernels(t_start=t_start)
    elif args.mode == "chaos":
        row = bench_chaos(t_start=t_start)
    elif args.mode == "ctrl-chaos":
        row = bench_ctrl_chaos(t_start=t_start)
    elif args.mode == "sentinel":
        row = bench_sentinel(t_start=t_start)
    elif args.mode == "input":
        row = bench_input(t_start=t_start)
    elif args.mode == "sched":
        row = bench_sched(t_start=t_start)
    elif args.mode == "health":
        row = bench_health(t_start=t_start)
    elif args.mode == "obs":
        row = bench_obs(t_start=t_start)
    elif args.mode == "goodput":
        row = bench_goodput(t_start=t_start)
    elif args.mode == "comm":
        row = bench_comm(t_start=t_start)
    elif args.mode == "multislice":
        row = bench_multislice(t_start=t_start)
    elif args.mode == "warmstart-child":
        row = bench_warmstart_child()
    elif args.mode == "katib-child":
        row = bench_katib_child()
    else:
        row = bench_resnet(fused=False, t_start=t_start)

    if cache_dir:
        row["extras"]["compile_cache"] = cache_dir
    backend_error = os.environ.get("KFTPU_BENCH_BACKEND_ERROR")
    if backend_error:
        # this run is the CPU-fallback child: record WHY the number is not
        # a TPU measurement so the artifact is never silently misread
        row["extras"]["error"] = backend_error
        # ... and carry the newest real hardware rows (timestamped, from
        # the newest measurement-session log) so a dead tunnel at capture
        # time does not erase the round's silicon evidence from the
        # artifact. Top-level run only: sub-bench children would embed
        # copies the parent discards anyway.
        if not os.environ.get("KFTPU_BENCH_SUBBENCH"):
            import glob
            logs = glob.glob(os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "bench-matrix", "r*_tpu_session*.jsonl"))
            # newest by mtime, NOT lexically: "r9_..." sorts after
            # "r10_..." so a lexical [-1] pick would embed a stale
            # session's rows once round numbers reach double digits
            newest = max(logs, key=os.path.getmtime) if logs else None
            rows = []
            for line in _read_lines(newest) if newest else []:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass   # a truncated tail line must not cost the row
            if rows:
                row["extras"]["last_tpu_session"] = {
                    "note": "prior measured TPU rows (NOT this run)",
                    "source": os.path.basename(newest),
                    "rows": rows,
                }
    flops_per_chip = row.pop("_flops_per_chip")
    if on_tpu:
        achievable = measure_achievable_tflops()
        row["extras"]["achievable_matmul_tflops"] = round(achievable, 1)
        row["extras"]["mfu_vs_achievable"] = round(
            flops_per_chip / (achievable * 1e12), 3)

    if args.mode == "all":
        # the headline measurement is DONE — flush it before the
        # sub-benches so a hang there (first Mosaic compile of the fused
        # kernels, a wedged sub-bench) can never cost the primary
        # artifact to a driver timeout; the enriched line replaces it
        # below when everything completes (last JSON line wins)
        print(json.dumps(row), flush=True)
        # fold the sub-benchmarks into the primary artifact. On TPU they
        # run in-process (the parent owns the chip; libtpu's per-process
        # lock would leave a subprocess CPU-bound and mislabeled). On the
        # CPU-fallback path each runs as its OWN subprocess under a
        # wall-clock budget: a sub-bench that hangs or crawls (e.g. 16
        # interpret-mode Pallas kernels) is killed and recorded as an
        # error — it can never cost the headline line to a driver timeout
        in_process = {"resnet-fused": lambda: bench_resnet(fused=True),
                      "lm": bench_lm,
                      "lm-long": lambda: bench_lm(long_context=True),
                      "serving": bench_serving,
                      "fused-blocks": lambda: bench_fused_blocks(
                          routing_out=args.routing_out),
                      "weight-update": bench_weight_update,
                      "input": bench_input,
                      "sched": bench_sched,
                      "health": bench_health}
        for key, mode in (("fused", "resnet-fused"), ("lm", "lm"),
                          ("lm_long", "lm-long"),
                          ("serving", "serving"),
                          ("weight_update", "weight-update"),
                          ("input", "input"),
                          ("sched", "sched"),
                          ("health", "health"),
                          ("fused_blocks", "fused-blocks")):
            if mode == "fused-blocks" and not on_tpu:
                # per-block attribution is the most expensive extra (10
                # jit'd block microbenches): never on CPU (interpret
                # mode would crawl), and only inside a driver-timeout
                # budget — recording WHY, like every absent number
                row["extras"][key] = {
                    "error": "skipped: CPU (interpret mode too slow)"}
            elif mode == "fused-blocks" and \
                    time.perf_counter() - t_start > 2400:
                # a TPU mode=all run spends ~15-20 min on the earlier
                # sub-benches (first-compile costs), so the gate must
                # leave room — and it can afford to: every earlier
                # result is already flushed, so a driver timeout during
                # the microbench loses nothing but the microbench
                row["extras"][key] = {
                    "error": "skipped: elapsed budget (2400s) reached"}
            else:
                try:
                    # the input A/B pays ~6 paired pipeline runs; the
                    # wider budget still fits because its primary cost
                    # is timed sleep, not compute
                    sub = in_process[mode]() if on_tpu else \
                        _run_sub_bench(mode, budget_s=420.0 if
                                       mode in ("input", "sched",
                                                "health")
                                       else 240.0)
                    row["extras"][key] = {
                        "metric": sub["metric"], "value": sub["value"],
                        "unit": sub["unit"], "mfu": sub["mfu"],
                        **{k: sub["extras"][k] for k in
                           ("model_tflops", "loss", "latency",
                            "cold_first_request_s", "warmup_s",
                            "fused_routing", "blocks", "weight_update",
                            "routing_table_written", "stages_img_s",
                            "serial_img_s", "overlapped_img_s",
                            "simulated_step_ms", "input_workers",
                            "input_only_speedup", "policies",
                            "dominates_fifo", "parity", "sim", "soak",
                            "quarantine_strictly_reduces_recompute",
                            "error")
                           if k in sub["extras"]},
                    }
                except Exception as e:  # noqa: BLE001 — artifact lands
                    row["extras"][key] = {
                        "error": f"{type(e).__name__}: {e}"}
            # flush the enriched row after EVERY sub-bench (including
            # recorded skips): a hard crash in a later in-process TPU
            # sub-bench (e.g. a Mosaic segfault) must not cost the
            # measurements already taken — drivers take the last line
            print(json.dumps(row), flush=True)
    else:
        print(json.dumps(row))
    print(f"# platform={platform} chips={len(jax.devices())} "
          f"mode={args.mode} extras={row['extras']}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
