"""Benchmark: ResNet-50 synthetic-ImageNet training throughput on TPU.

The vehicle matches the reference's headline benchmark machinery — the
tf_cnn_benchmarks ResNet-50 TFJob (tf-controller-examples/tf-cnn/;
kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet runs it with synthetic
data). The reference publishes no numbers (BASELINE.md), so the baseline is
our own recorded first-light figure; vs_baseline = value / BASELINE_IMG_S.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

# First-light measurement on one TPU v5e chip (bf16, batch 256, synthetic
# data, this repo @ milestone 3). Later rounds must beat it.
BASELINE_IMG_S = 1000.0


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    import optax

    from kubeflow_tpu.models import resnet as R
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

    n_chips = len(jax.devices())
    if on_tpu:
        # batch 128/chip measured fastest on v5e (128: ~2600, 256: ~2500,
        # 512: ~2360, 1024: ~2020 img/s) — larger batches lose to HBM
        # pressure on this model
        batch_per_chip, image_size, steps, warmup = 128, 224, 20, 4
    else:  # CPU smoke mode so the script stays runnable anywhere
        batch_per_chip, image_size, steps, warmup = 8, 64, 4, 1
    global_batch = batch_per_chip * n_chips

    model = R.resnet50(num_classes=1000)
    builder = TrainStepBuilder(
        mesh=build_mesh(),
        loss_fn=R.make_loss_fn(model),
        optimizer=optax.chain(optax.clip_by_global_norm(1.0),
                              optax.sgd(0.1, momentum=0.9)),
    )
    state = builder.init(R.init_fn(model, image_size=image_size),
                         jax.random.PRNGKey(0))
    step_fn = builder.build()
    batch = builder.place_batch(
        R.synthetic_batch(jax.random.PRNGKey(1), global_batch, image_size))

    # sync via host transfer (float()), not block_until_ready: on the
    # tunneled axon platform block_until_ready returns before the compute
    # finishes, which inflated throughput ~70x; a device->host fetch of the
    # last step's loss is a hard barrier everywhere
    for _ in range(warmup):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    img_s = global_batch * steps / dt
    img_s_chip = img_s / n_chips
    print(json.dumps({
        "metric": "resnet50_synthetic_imagenet_train_throughput",
        "value": round(img_s_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 3),
    }))
    print(f"# platform={platform} chips={n_chips} batch={global_batch} "
          f"image={image_size} steps={steps} wall={dt:.2f}s "
          f"loss={float(metrics['loss']):.3f}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
