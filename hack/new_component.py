"""Scaffold a new manifest component (the kubeflow/new-package-stub
analog: README + newpackage.libsonnet + prototypes/newpackage.jsonnet,
translated to this repo's builder-module shape).

    python hack/new_component.py my-component --module mygroup

writes kubeflow_tpu/manifests/<module>.py with a registered builder stub
plus tests/test_<module>.py with a golden-shape test, and prints the two
follow-ups the reference README gives (import it from manifests/__init__,
add params).
"""

from __future__ import annotations

import argparse
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

MODULE_TEMPLATE = '''"""{title} manifest package.

Reference analog: kubeflow/new-package-stub (parts.yaml +
prototypes/newpackage.jsonnet) — replace this docstring with the real
package description and the reference file:line it mirrors.
"""

from __future__ import annotations

from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("{name}", "{title} (describe the component)")
def {fn}(namespace: str = "kubeflow", replicas: int = 1) -> list[dict]:
    """Build the component's manifests. Parameters become the
    component's prototype params (surface them in docs/components)."""
    dep = H.deployment("{name}", namespace,
                       f"{{IMG}}/{name}:{{VERSION}}",
                       replicas=replicas, port=8080)
    svc = H.service("{name}", namespace, port=8080)
    return [dep, svc]
'''

TEST_TEMPLATE = '''"""Golden-shape test for the {name} package (replace with
behavior tests as the component grows)."""

from kubeflow_tpu.manifests import build_component


def test_{fn}_builds():
    objs = build_component("{name}")
    kinds = sorted(o["kind"] for o in objs)
    assert kinds == ["Deployment", "Service"]
    for o in objs:
        assert o["metadata"]["namespace"] == "kubeflow"
'''


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("name", help="component name, e.g. my-component")
    p.add_argument("--module", default=None,
                   help="manifests module filename (default: name with "
                        "dashes → underscores)")
    args = p.parse_args(argv)
    if not re.fullmatch(r"[a-z][a-z0-9-]*", args.name):
        p.error("name must be lowercase-dashed and start with a letter "
                "(it becomes a Python identifier)")
    module = args.module or args.name.replace("-", "_")
    fn = args.name.replace("-", "_")
    title = args.name.replace("-", " ").title()

    mod_path = os.path.join(REPO, "kubeflow_tpu", "manifests",
                            f"{module}.py")
    test_path = os.path.join(REPO, "tests", f"test_{module}.py")
    for path in (mod_path, test_path):
        if os.path.exists(path):
            print(f"refusing to overwrite {path}", file=sys.stderr)
            return 1
    with open(mod_path, "w") as f:
        f.write(MODULE_TEMPLATE.format(name=args.name, fn=fn, title=title))
    with open(test_path, "w") as f:
        f.write(TEST_TEMPLATE.format(name=args.name, fn=fn))
    print(f"wrote {mod_path}")
    print(f"wrote {test_path}")
    print("next: import the module from kubeflow_tpu/manifests/__init__.py "
          "so the registry sees it, then run the test.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
