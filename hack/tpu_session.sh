#!/bin/bash
# TPU measurement session: run the full round-5 measurement list in
# priority order the moment the axon tunnel answers (PERF.md round 5;
# r4 verdict items 1/2/4/8). Each step has its own wall budget so one
# wedged stage cannot eat the session. Artifacts land in
# bench-results/ (JSON per step) + refreshed bench-matrix/ CSVs.
#
#   bash hack/tpu_session.sh [results_dir]
#
# Priority:
#   0. probe (bounded) — abort early if the tunnel is dead
#   1. resnet baseline re-confirmation        (north-star #1)
#   2. resnet fused (first Mosaic compile of both kernel variants)
#   3. transformer LM MFU                     (verdict item 2)
#   4. serving data plane p50/p99             (verdict item 4)
#   5. compile-cache warm start (cold vs warm resnet startup)
#   6. kubebench matrix refresh               (verdict item 8)
set -u
cd "$(dirname "$0")/.." || exit 1

# KFTPU_SESSION_REHEARSAL=1: full dry run of this script's plumbing on
# CPU (run with JAX_PLATFORMS=cpu) — results default to a separate dir,
# the matrix + routing-table outputs stay inside it, and nothing is
# auto-committed, so a rehearsal can never clobber or pollute real
# measurement artifacts.
REHEARSAL="${KFTPU_SESSION_REHEARSAL:-}"
if [ -n "$REHEARSAL" ]; then
  RESULTS="${1:-rehearsal-results}"
else
  RESULTS="${1:-bench-results}"
fi
mkdir -p "$RESULTS"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
log() { echo "[tpu-session $(date -u +%T)] $*"; }

MATRIX_DIR="bench-matrix"
ROUTING_TABLE="bench-matrix/fused_routing_measured.json"
if [ -n "$REHEARSAL" ]; then
  MATRIX_DIR="$RESULTS/matrix"
  ROUTING_TABLE="$RESULTS/fused_routing_measured.json"
  log "REHEARSAL mode: results -> $RESULTS, no artifact commit"
fi

log "probing backend (300s budget)"
if ! timeout -k 60 300 python -c "import jax; jax.devices()" \
    >/dev/null 2>&1; then
  log "tunnel dead — aborting (nothing written)"
  exit 1
fi
log "tunnel UP"

run_step() {  # name, budget_s, cmd...
  local name="$1" budget="$2"; shift 2
  log "step $name (budget ${budget}s)"
  # -k: a worker stuck in native XLA code defers SIGTERM indefinitely
  # (observed in the CPU rehearsal) — escalate to SIGKILL so one wedged
  # step can never absorb the rest of the tunnel window
  if timeout -k 60 "$budget" "$@" > "$RESULTS/$name-$STAMP.out" 2> \
      "$RESULTS/$name-$STAMP.err"; then
    grep -E '^\{' "$RESULTS/$name-$STAMP.out" | tail -1 \
      > "$RESULTS/$name-$STAMP.json" || true
    # a mid-session tunnel drop makes bench.py respawn its CPU-fallback
    # child (exit 0, extras.error set): that is NOT a TPU measurement —
    # abort instead of burning the remaining window on CPU numbers
    if grep -q '"error": "tpu backend unreachable' \
        "$RESULTS/$name-$STAMP.json" 2>/dev/null; then
      log "step $name fell back to CPU (tunnel dropped mid-session) — aborting"
      exit 2
    fi
    log "step $name OK: $(cut -c1-120 "$RESULTS/$name-$STAMP.json")"
  else
    log "step $name FAILED/timeout (see $RESULTS/$name-$STAMP.err)"
  fi
}

run_step resnet   900 python bench.py --mode resnet
run_step fused    1500 python bench.py --mode resnet-fused
if [ ! -s "$RESULTS/fused-$STAMP.json" ]; then
  # first Mosaic compile of the spatial kernels may fail: retry with
  # the spatial kill-switch so a stage-3/4-only fused number still lands
  log "fused step produced no artifact — retrying with spatial disabled"
  KFTPU_FUSED_DISABLE_SPATIAL=1 run_step fused-nospatial 1200 \
    python bench.py --mode resnet-fused
fi
run_step lm       900 python bench.py --mode lm
if [ ! -s "$RESULTS/lm-$STAMP.json" ]; then
  # first Mosaic compile of the flash kernel may fail: a measured
  # einsum-attention LM line still answers the MFU question
  log "lm step produced no artifact — retrying with einsum attention"
  KFTPU_LM_ATTENTION=einsum run_step lm-einsum 900 python bench.py --mode lm
fi
run_step lm-long  900 python bench.py --mode lm-long
run_step serving  1200 python bench.py --mode serving
# per-block kernel attribution for the fused path's measured 0.53x —
# writes the routing table fused_train_apply consumes via
# KFTPU_FUSED_ROUTING_TABLE, then re-measures end-to-end with measured
# routing. Remove any prior session's table first: the -s gate below
# must see THIS session's measurements or nothing.
rm -f "$ROUTING_TABLE"
run_step fused-blocks 1800 python bench.py --mode fused-blocks \
  --routing-out "$ROUTING_TABLE"
if [ -s "$ROUTING_TABLE" ]; then
  KFTPU_FUSED_ROUTING_TABLE="$ROUTING_TABLE" \
    run_step fused-measured-routing 1200 python bench.py --mode resnet-fused
fi

# compile-cache warm start: cold vs warm startup_first_step_s
CACHE=$(mktemp -d /tmp/kftpu-cache.XXXX)
KFTPU_COMPILE_CACHE_DIR="$CACHE" run_step cache-cold 900 \
  python bench.py --mode resnet
KFTPU_COMPILE_CACHE_DIR="$CACHE" run_step cache-warm 900 \
  python bench.py --mode resnet

# several training configs + first-compile costs: needs the largest budget
run_step matrix 2700 python -m kubeflow_tpu.workflows.kubebench matrix \
  --out-dir "$MATRIX_DIR" --steps 40 --global-batch 128

log "session done; artifacts in $RESULTS/ and bench-matrix/"

# land the evidence: a session can finish minutes before the round ends,
# so the artifacts must not sit uncommitted in the working tree
if [ -z "$REHEARSAL" ] && \
    git -C "$(pwd)" rev-parse --is-inside-work-tree >/dev/null 2>&1; then
  git add "$RESULTS" bench-matrix 2>/dev/null
  git commit -q -m "TPU measurement session artifacts ($STAMP)

Raw step outputs and JSON rows from hack/tpu_session.sh; see
$RESULTS/session.log for the step-by-step record.

No-Verification-Needed: measurement artifacts only" 2>/dev/null \
    && log "artifacts committed" || log "nothing new to commit"
fi
