#!/bin/bash
# TPU measurement session: run the full round-5 measurement list in
# priority order the moment the axon tunnel answers (PERF.md round 5;
# r4 verdict items 1/2/4/8). Each step has its own wall budget so one
# wedged stage cannot eat the session. Artifacts land in
# bench-results/ (JSON per step) + refreshed bench-matrix/ CSVs.
#
#   bash hack/tpu_session.sh [results_dir]
#
# Priority:
#   0. probe (bounded) — abort early if the tunnel is dead
#   1. resnet baseline re-confirmation        (north-star #1)
#   2. resnet fused (first Mosaic compile of both kernel variants)
#   3. transformer LM MFU                     (verdict item 2)
#   4. serving data plane p50/p99             (verdict item 4)
#   5. compile-cache warm start (cold vs warm resnet startup)
#   6. kubebench matrix refresh               (verdict item 8)
set -u
cd "$(dirname "$0")/.." || exit 1

# KFTPU_SESSION_REHEARSAL=1: full dry run of this script's plumbing on
# CPU (run with JAX_PLATFORMS=cpu) — results default to a separate dir,
# the matrix + routing-table outputs stay inside it, and nothing is
# auto-committed, so a rehearsal can never clobber or pollute real
# measurement artifacts.
REHEARSAL="${KFTPU_SESSION_REHEARSAL:-}"
if [ -n "$REHEARSAL" ]; then
  RESULTS="${1:-rehearsal-results}"
else
  RESULTS="${1:-bench-results}"
fi
mkdir -p "$RESULTS"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
# mtime anchor for artifact freshness checks: the matrix writes
# fixed-name CSVs, so "exists" can be satisfied by a PRIOR session's
# committed files — only files newer than this session count
SESSION_START_MARK="$RESULTS/.session-start-$STAMP"
touch "$SESSION_START_MARK"
log() { echo "[tpu-session $(date -u +%T)] $*"; }

MATRIX_DIR="bench-matrix"
ROUTING_TABLE="bench-matrix/fused_routing_measured.json"
if [ -n "$REHEARSAL" ]; then
  MATRIX_DIR="$RESULTS/matrix"
  ROUTING_TABLE="$RESULTS/fused_routing_measured.json"
  log "REHEARSAL mode: results -> $RESULTS, no artifact commit"
fi

log "probing backend (300s budget)"
if ! timeout -k 60 300 python -c "import jax; jax.devices()" \
    >/dev/null 2>&1; then
  log "tunnel dead — aborting (nothing written)"
  exit 1
fi
log "tunnel UP"

# Per-step failure ledger: a session whose steps FAILED/timed out must
# NOT exit 0 — the auto-launcher (.tpu_probe.sh) gates .session_done on
# our exit code, and a half-failed session that retires the launcher
# silently forfeits every remaining tunnel window (ADVICE.md round 5).
FAILED_STEPS=""

run_step() {  # [--no-json] name, budget_s, cmd...
  # --no-json: steps like the kubebench matrix write CSVs, not a bench
  # JSON row — success for them is exit 0, and the ledger must not
  # report a healthy run as "(no-artifact)"
  local expect_json=1
  if [ "$1" = "--no-json" ]; then expect_json=0; shift; fi
  local name="$1" budget="$2"; shift 2
  log "step $name (budget ${budget}s)"
  # -k: a worker stuck in native XLA code defers SIGTERM indefinitely
  # (observed in the CPU rehearsal) — escalate to SIGKILL so one wedged
  # step can never absorb the rest of the tunnel window
  if timeout -k 60 "$budget" "$@" > "$RESULTS/$name-$STAMP.out" 2> \
      "$RESULTS/$name-$STAMP.err"; then
    grep -E '^\{' "$RESULTS/$name-$STAMP.out" | tail -1 \
      > "$RESULTS/$name-$STAMP.json" || true
    # a mid-session tunnel drop makes bench.py respawn its CPU-fallback
    # child (exit 0, extras.error set): that is NOT a TPU measurement —
    # abort instead of burning the remaining window on CPU numbers
    if grep -q '"error": "tpu backend unreachable' \
        "$RESULTS/$name-$STAMP.json" 2>/dev/null; then
      log "step $name fell back to CPU (tunnel dropped mid-session) — aborting"
      exit 2
    fi
    if [ "$expect_json" -eq 0 ]; then
      log "step $name OK (CSV/log artifacts)"
    elif [ ! -s "$RESULTS/$name-$STAMP.json" ]; then
      # exit 0 with no JSON row is still a failed measurement
      FAILED_STEPS="$FAILED_STEPS $name(no-artifact)"
      log "step $name exited 0 but produced no JSON artifact"
    else
      log "step $name OK: $(cut -c1-120 "$RESULTS/$name-$STAMP.json")"
    fi
  else
    FAILED_STEPS="$FAILED_STEPS $name"
    log "step $name FAILED/timeout (see $RESULTS/$name-$STAMP.err)"
  fi
}

# key_artifact name [fallback...]: true when any named step produced a
# non-empty JSON row this session — kill-switch retries count (a
# measured einsum LM line still answers the MFU question)
key_artifact() {
  local name
  for name in "$@"; do
    [ -s "$RESULTS/$name-$STAMP.json" ] && return 0
  done
  return 1
}

run_step resnet   900 python bench.py --mode resnet
run_step fused    1500 python bench.py --mode resnet-fused
if [ ! -s "$RESULTS/fused-$STAMP.json" ]; then
  # first Mosaic compile of the spatial kernels may fail: retry with
  # the spatial kill-switch so a stage-3/4-only fused number still lands
  log "fused step produced no artifact — retrying with spatial disabled"
  KFTPU_FUSED_DISABLE_SPATIAL=1 run_step fused-nospatial 1200 \
    python bench.py --mode resnet-fused
fi
run_step lm       900 python bench.py --mode lm
if [ ! -s "$RESULTS/lm-$STAMP.json" ]; then
  # first Mosaic compile of the flash kernel may fail: a measured
  # einsum-attention LM line still answers the MFU question
  log "lm step produced no artifact — retrying with einsum attention"
  KFTPU_LM_ATTENTION=einsum run_step lm-einsum 900 python bench.py --mode lm
fi
run_step lm-long  900 python bench.py --mode lm-long
run_step serving  1200 python bench.py --mode serving
# per-block kernel attribution for the fused path's measured 0.53x —
# writes the routing table fused_train_apply consumes via
# KFTPU_FUSED_ROUTING_TABLE, then re-measures end-to-end with measured
# routing. Remove any prior session's table first: the -s gate below
# must see THIS session's measurements or nothing.
rm -f "$ROUTING_TABLE"
run_step fused-blocks 1800 python bench.py --mode fused-blocks \
  --routing-out "$ROUTING_TABLE"
if [ -s "$ROUTING_TABLE" ]; then
  KFTPU_FUSED_ROUTING_TABLE="$ROUTING_TABLE" \
    run_step fused-measured-routing 1200 python bench.py --mode resnet-fused
fi

# compile-cache warm start: cold vs warm startup_first_step_s
CACHE=$(mktemp -d /tmp/kftpu-cache.XXXX)
KFTPU_COMPILE_CACHE_DIR="$CACHE" run_step cache-cold 900 \
  python bench.py --mode resnet
KFTPU_COMPILE_CACHE_DIR="$CACHE" run_step cache-warm 900 \
  python bench.py --mode resnet

# several training configs + first-compile costs: needs the largest budget
run_step --no-json matrix 2700 python -m kubeflow_tpu.workflows.kubebench \
  matrix --out-dir "$MATRIX_DIR" --steps 40 --global-batch 128

# the matrix writes CSVs, not a JSON row: gate on CSVs written by THIS
# session (stale committed bench-matrix/ files must not vouch for a
# failed/timed-out matrix step)
MATRIX_OK=0
if find "$MATRIX_DIR" -name '*.csv' -newer "$SESSION_START_MARK" \
    2>/dev/null | grep -q .; then
  MATRIX_OK=1
fi

# Session verdict: exit 0 ONLY when every key measurement landed (with
# its kill-switch fallback counting), so the launcher's
# `rc==0 -> .session_done` gate retires the session on evidence, not on
# the script merely reaching its last line.
SESSION_RC=0
MISSING=""
key_artifact resnet || MISSING="$MISSING resnet"
key_artifact fused fused-nospatial || MISSING="$MISSING fused"
key_artifact lm lm-einsum || MISSING="$MISSING lm"
key_artifact lm-long || MISSING="$MISSING lm-long"
key_artifact serving || MISSING="$MISSING serving"
[ "$MATRIX_OK" -eq 1 ] || MISSING="$MISSING matrix"
if [ -n "$MISSING" ]; then
  SESSION_RC=3
  log "key artifacts MISSING:$MISSING"
fi
if [ -n "$FAILED_STEPS" ]; then
  log "steps that failed/timed out:$FAILED_STEPS"
  # failed OPTIONAL steps (cache A/B, per-block attribution) don't block
  # retirement by themselves, but a failed KEY step already set rc above
fi
log "session done (rc=$SESSION_RC); artifacts in $RESULTS/ and bench-matrix/"

# land the evidence: a session can finish minutes before the round ends,
# so the artifacts must not sit uncommitted in the working tree
if [ -z "$REHEARSAL" ] && \
    git -C "$(pwd)" rev-parse --is-inside-work-tree >/dev/null 2>&1; then
  git add "$RESULTS" bench-matrix 2>/dev/null
  git commit -q -m "TPU measurement session artifacts ($STAMP)

Raw step outputs and JSON rows from hack/tpu_session.sh; see
$RESULTS/session.log for the step-by-step record.

No-Verification-Needed: measurement artifacts only" 2>/dev/null \
    && log "artifacts committed" || log "nothing new to commit"
fi

exit "$SESSION_RC"
