// Host-side data pipeline core: sharded record reading with threaded
// prefetch into a bounded ring of batch buffers.
//
// Why native: the TPU input pipeline is host-CPU work that competes with
// nothing on the chip — the reference delegates it to the frameworks it
// launches (tf.data inside tf_cnn_benchmarks; the PS role's host side,
// SURVEY.md §2.5 row 1). Python-level file reading stalls the step loop on
// the GIL at high batch rates; this core keeps N reader threads filling
// fixed-size batch buffers while the trainer thread drains them via ctypes
// (kubeflow_tpu/data/native.py).
//
// Model: records are fixed-size byte blobs packed back-to-back in files
// ("record files"). An epoch = a seeded Fisher-Yates shuffle of the global
// record index space, sharded round-robin across worker processes. Readers
// claim batch slots, pread() their records, and publish; the consumer
// blocks on the next sequential batch (batches are delivered in order so
// training stays deterministic for a given seed).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct FileSpan {
  std::string path;
  int fd = -1;
  int64_t records = 0;   // record count in this file
  int64_t first = 0;     // global index of this file's first record
};

// Slot lifecycle: FREE -> CLAIMED (producer filling) -> READY (published)
// -> FREE (consumed). CLAIMED must be distinct from FREE: a producer for
// round b+depth observing the round-b producer's claim as "free" would
// steal the slot and deadlock the in-order consumer.
enum SlotState : int8_t { kFree = 0, kClaimed = 1, kReady = 2 };

struct Slot {
  std::vector<uint8_t> buf;
  int64_t batch_index = -1;   // which sequential batch last claimed the slot
  int32_t records = 0;        // records actually filled (tail batch)
  SlotState state = kFree;
};

}  // namespace

struct dp_pipeline {
  // config
  int64_t record_bytes = 0;
  int32_t batch_records = 0;
  int32_t queue_depth = 0;
  bool drop_remainder = true;

  std::vector<FileSpan> files;
  int64_t total_records = 0;

  // epoch state
  std::vector<int64_t> order;      // shuffled global record indices
  int64_t num_batches = 0;

  // ring
  std::vector<Slot> slots;
  std::atomic<int64_t> next_claim{0};   // next batch index to be claimed
  int64_t next_deliver = 0;             // next batch index to hand out
  std::mutex mu;
  std::condition_variable cv_ready;     // consumer waits for its batch
  std::condition_variable cv_free;      // producers wait for a free slot

  std::vector<std::thread> threads;
  std::atomic<bool> stop{false};
  std::string error;
  std::mutex err_mu;

  ~dp_pipeline() {
    shutdown();  // join readers BEFORE closing their fds
    for (auto& f : files)
      if (f.fd >= 0) close(f.fd);
  }

  void set_error(const std::string& e) {
    std::lock_guard<std::mutex> l(err_mu);
    if (error.empty()) error = e;
    cv_ready.notify_all();
    cv_free.notify_all();
  }

  bool failed() {
    std::lock_guard<std::mutex> l(err_mu);
    return !error.empty();
  }

  // splitmix64 Fisher-Yates: bit-for-bit reproducible in the pure-Python
  // fallback (data/pipeline.py epoch_order), unlike std::uniform_int_
  // distribution whose mapping is implementation-defined
  static uint64_t splitmix64(uint64_t* state) {
    uint64_t z = (*state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }

  void shuffle(uint64_t seed) {
    order.resize(static_cast<size_t>(total_records));
    for (int64_t i = 0; i < total_records; ++i) order[i] = i;
    uint64_t state = seed;
    for (int64_t i = total_records - 1; i > 0; --i) {
      int64_t j = static_cast<int64_t>(
          splitmix64(&state) % static_cast<uint64_t>(i + 1));
      std::swap(order[i], order[j]);
    }
    num_batches = drop_remainder
                      ? total_records / batch_records
                      : (total_records + batch_records - 1) / batch_records;
  }

  // locate global record -> (file, offset) by linear scan over files
  // (file count is small; records within a file are contiguous)
  bool read_record(int64_t global_idx, uint8_t* dst) {
    for (const auto& f : files) {
      if (global_idx >= f.first && global_idx < f.first + f.records) {
        int64_t off = (global_idx - f.first) * record_bytes;
        int64_t done = 0;
        while (done < record_bytes) {
          ssize_t n = pread(f.fd, dst + done, record_bytes - done, off + done);
          if (n <= 0) return false;
          done += n;
        }
        return true;
      }
    }
    return false;
  }

  void reader_loop() {
    while (!stop.load()) {
      int64_t b = next_claim.fetch_add(1);
      if (b >= num_batches) return;
      Slot* slot = nullptr;
      {
        // Wait until the slot's PREVIOUS round was consumed. The predicate
        // must be exact (batch_index == b - depth), not `< b`: with both
        // round-b and round-(b+depth) producers waiting, a `<` check would
        // admit the later one while the earlier round is still unwritten,
        // corrupting the slot and deadlocking the in-order consumer.
        int64_t prev = b - static_cast<int64_t>(slots.size());
        std::unique_lock<std::mutex> l(mu);
        Slot& s = slots[b % slots.size()];
        cv_free.wait(l, [&] {
          return stop.load() || failed() ||
                 (s.state == kFree &&
                  s.batch_index == (prev < 0 ? -1 : prev));
        });
        if (stop.load() || failed()) return;
        s.batch_index = b;
        s.state = kClaimed;
        slot = &s;
      }
      int64_t start = b * static_cast<int64_t>(batch_records);
      int64_t end = std::min(start + batch_records, total_records);
      int32_t n = static_cast<int32_t>(end - start);
      for (int32_t i = 0; i < n; ++i) {
        if (!read_record(order[static_cast<size_t>(start + i)],
                         slot->buf.data() + static_cast<int64_t>(i) * record_bytes)) {
          set_error("pread failed for record " +
                    std::to_string(order[static_cast<size_t>(start + i)]));
          return;
        }
      }
      {
        std::lock_guard<std::mutex> l(mu);
        slot->records = n;
        slot->state = kReady;
      }
      cv_ready.notify_all();
    }
  }

  void start(int n_threads) {
    stop.store(false);
    next_claim.store(0);
    next_deliver = 0;
    for (auto& s : slots) {
      s.state = kFree;
      s.batch_index = -1;
      s.records = 0;
    }
    for (int i = 0; i < n_threads; ++i)
      threads.emplace_back([this] { reader_loop(); });
  }

  void shutdown() {
    stop.store(true);
    cv_ready.notify_all();
    cv_free.notify_all();
    for (auto& t : threads)
      if (t.joinable()) t.join();
    threads.clear();
  }
};

extern "C" {

dp_pipeline* dp_create(const char** paths, int32_t n_paths,
                       int64_t record_bytes, int32_t batch_records,
                       int32_t queue_depth, int32_t n_threads,
                       uint64_t seed, int32_t drop_remainder) {
  if (record_bytes <= 0 || batch_records <= 0 || n_paths <= 0) return nullptr;
  auto* p = new dp_pipeline();
  p->record_bytes = record_bytes;
  p->batch_records = batch_records;
  p->queue_depth = queue_depth < 2 ? 2 : queue_depth;
  p->drop_remainder = drop_remainder != 0;

  int64_t cursor = 0;
  for (int32_t i = 0; i < n_paths; ++i) {
    FileSpan f;
    f.path = paths[i];
    f.fd = open(f.path.c_str(), O_RDONLY);
    struct stat st;
    if (f.fd < 0 || fstat(f.fd, &st) != 0) {
      p->set_error("cannot open " + f.path);
      if (f.fd >= 0) close(f.fd);  // not yet owned by p->files
      delete p;                    // dtor closes earlier files' fds
      return nullptr;
    }
    f.records = st.st_size / record_bytes;
    f.first = cursor;
    cursor += f.records;
    p->files.push_back(f);
  }
  p->total_records = cursor;
  p->shuffle(seed);

  p->slots.resize(static_cast<size_t>(p->queue_depth));
  for (auto& s : p->slots)
    s.buf.resize(static_cast<size_t>(record_bytes) * batch_records);

  int threads = n_threads < 1 ? 1 : n_threads;
  p->start(threads);
  return p;
}

// Blocks until the next in-order batch is ready and copies it to out.
// Returns records copied (0 = epoch done, -1 = error).
int32_t dp_next(dp_pipeline* p, uint8_t* out, int64_t out_bytes) {
  if (p == nullptr) return -1;
  if (p->next_deliver >= p->num_batches) return 0;
  int64_t want = p->next_deliver;
  Slot& s = p->slots[want % p->slots.size()];
  std::unique_lock<std::mutex> l(p->mu);
  p->cv_ready.wait(l, [&] {
    return p->stop.load() || p->failed() ||
           (s.state == kReady && s.batch_index == want);
  });
  if (p->stop.load() || p->failed()) return -1;
  int64_t bytes = static_cast<int64_t>(s.records) * p->record_bytes;
  if (bytes > out_bytes) return -1;
  std::memcpy(out, s.buf.data(), static_cast<size_t>(bytes));
  int32_t n = s.records;
  s.state = kFree;           // slot free for batch want + queue_depth
  p->next_deliver = want + 1;
  l.unlock();
  p->cv_free.notify_all();
  return n;
}

// Start a new epoch with a fresh shuffle (blocks until readers quiesce).
void dp_reset(dp_pipeline* p, uint64_t seed) {
  if (p == nullptr) return;
  int n_threads = static_cast<int>(p->threads.size());
  p->shutdown();
  {
    std::lock_guard<std::mutex> l(p->err_mu);
    p->error.clear();
  }
  p->shuffle(seed);
  p->start(n_threads == 0 ? 1 : n_threads);
}

int64_t dp_total_records(dp_pipeline* p) {
  return p == nullptr ? -1 : p->total_records;
}

int64_t dp_num_batches(dp_pipeline* p) {
  return p == nullptr ? -1 : p->num_batches;
}

const char* dp_last_error(dp_pipeline* p) {
  if (p == nullptr) return "null pipeline";
  std::lock_guard<std::mutex> l(p->err_mu);
  return p->error.c_str();
}

void dp_destroy(dp_pipeline* p) {
  delete p;  // dtor joins readers, then closes fds
}

}  // extern "C"
