// Host-side image augmentation: flip + reflect-pad random crop +
// ImageNet normalization, fused into one pass over the batch.
//
// The data-loader hot path the Python fallback (data/imagenet.py) does in
// several numpy passes (plus a per-image crop loop); here it is one
// multithreaded C++ pass from uint8 records to the float32 feed buffer.
// Augment parameters derive from splitmix64 exactly like the shuffle
// (datapipe.cc / data/pipeline.py), and data/imagenet.py implements the
// SAME derivation in numpy — the executable spec the tests pin
// bit-identically across both paths.
//
// C ABI (ctypes, see kubeflow_tpu/data/native.py):
//   kf_augment(in, out, n, h, w, pad, base_state, mean, std,
//              do_flip, do_crop, num_threads)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t* state) {
  *state += 0x9E3779B97F4A7C15ULL;
  uint64_t z = *state;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

struct AugParams {
  bool flip;
  int32_t dy;
  int32_t dx;
};

// Per-record parameter derivation — mirrored in
// data/imagenet.py::augment_params (keep in sync!).
inline AugParams params_for(uint64_t base, int64_t index, int32_t pad) {
  uint64_t state = base + static_cast<uint64_t>(index + 1) *
                              0x9E3779B97F4A7C15ULL;
  uint64_t z1 = splitmix64(&state);
  uint64_t z2 = splitmix64(&state);
  AugParams p;
  p.flip = (z1 & 1ULL) != 0;
  uint32_t span = static_cast<uint32_t>(2 * pad + 1);
  p.dy = span ? static_cast<int32_t>((z2 >> 1) % span) : 0;
  p.dx = span ? static_cast<int32_t>((z2 >> 33) % span) : 0;
  return p;
}

// reflect-pad index: maps a padded coordinate back into [0, size)
inline int32_t reflect(int32_t v, int32_t size) {
  if (v < 0) v = -v;                 // numpy 'reflect' (no edge repeat)
  if (v >= size) v = 2 * size - 2 - v;
  return v;
}

template <typename Out>
void augment_range(const uint8_t* in, Out* out, int64_t lo, int64_t hi,
                   int32_t h, int32_t w, int32_t pad, uint64_t base,
                   const float* mean, const float* stddev, bool do_flip,
                   bool do_crop, bool normalize) {
  const int64_t img_elems = static_cast<int64_t>(h) * w * 3;
  float scale[3] = {1, 1, 1}, shift[3] = {0, 0, 0};
  if (normalize) {
    for (int c = 0; c < 3; ++c) {
      scale[c] = 1.0f / (255.0f * stddev[c]);
      shift[c] = mean[c] / stddev[c];
    }
  }
  for (int64_t i = lo; i < hi; ++i) {
    AugParams p = params_for(base, i, pad);
    if (!do_flip) p.flip = false;
    if (!do_crop) { p.dy = pad; p.dx = pad; }  // centered = identity
    const uint8_t* src = in + i * img_elems;
    Out* dst = out + i * img_elems;
    for (int32_t y = 0; y < h; ++y) {
      // crop offset within the virtually padded image, reflected back
      int32_t sy = reflect(y + p.dy - pad, h);
      const uint8_t* row = src + static_cast<int64_t>(sy) * w * 3;
      Out* drow = dst + static_cast<int64_t>(y) * w * 3;
      for (int32_t x = 0; x < w; ++x) {
        int32_t sx = reflect(x + p.dx - pad, w);
        if (p.flip) sx = w - 1 - sx;
        const uint8_t* px = row + static_cast<int64_t>(sx) * 3;
        Out* dpx = drow + static_cast<int64_t>(x) * 3;
        if (normalize) {
          dpx[0] = static_cast<Out>(
              static_cast<float>(px[0]) * scale[0] - shift[0]);
          dpx[1] = static_cast<Out>(
              static_cast<float>(px[1]) * scale[1] - shift[1]);
          dpx[2] = static_cast<Out>(
              static_cast<float>(px[2]) * scale[2] - shift[2]);
        } else {
          dpx[0] = static_cast<Out>(px[0]);
          dpx[1] = static_cast<Out>(px[1]);
          dpx[2] = static_cast<Out>(px[2]);
        }
      }
    }
  }
}

template <typename Out>
void run_augment(const uint8_t* in, Out* out, int64_t n, int32_t h,
                 int32_t w, int32_t pad, uint64_t base,
                 const float* mean, const float* stddev, bool do_flip,
                 bool do_crop, bool normalize, int32_t num_threads) {
  if (n <= 0) return;
  int32_t workers = num_threads < 1 ? 1 : num_threads;
  if (workers > n) workers = static_cast<int32_t>(n);
  if (workers == 1) {
    augment_range<Out>(in, out, 0, n, h, w, pad, base, mean, stddev,
                       do_flip, do_crop, normalize);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + workers - 1) / workers;
  for (int32_t t = 0; t < workers; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(augment_range<Out>, in, out, lo, hi, h, w, pad,
                      base, mean, stddev, do_flip, do_crop, normalize);
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// in:  n * h * w * 3 uint8 (decoded records)
// out: n * h * w * 3 float32 (normalized, augmented feed buffer)
void kf_augment(const uint8_t* in, float* out, int64_t n, int32_t h,
                int32_t w, int32_t pad, uint64_t base_state,
                const float* mean, const float* stddev, int32_t do_flip,
                int32_t do_crop, int32_t num_threads) {
  run_augment<float>(in, out, n, h, w, pad, base_state, mean, stddev,
                     do_flip != 0, do_crop != 0, /*normalize=*/true,
                     num_threads);
}

// uint8 variant: augment only, NO normalization — the device-normalize
// input mode (ship 1/4 the bytes host→device; normalization runs inside
// the jitted step). Same augment parameters as kf_augment.
void kf_augment_u8(const uint8_t* in, uint8_t* out, int64_t n, int32_t h,
                   int32_t w, int32_t pad, uint64_t base_state,
                   int32_t do_flip, int32_t do_crop, int32_t num_threads) {
  run_augment<uint8_t>(in, out, n, h, w, pad, base_state, nullptr, nullptr,
                       do_flip != 0, do_crop != 0, /*normalize=*/false,
                       num_threads);
}

}  // extern "C"
