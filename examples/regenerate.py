"""Regenerate the example YAMLs from the component builders (the YAMLs in
this directory are render OUTPUTS — the builders in kubeflow_tpu/manifests
are the source of truth; tests/test_examples.py keeps them in sync)."""

import os

from kubeflow_tpu.manifests import build_component
from kubeflow_tpu.utils.yamlio import dump_all

HERE = os.path.dirname(os.path.abspath(__file__))

EXAMPLES = [
    ("tpu-job-simple", "tpu-job-simple.yaml", {"topology": "v5e-32"}),
    ("tpu-job-simple", "tpu-job-fused.yaml",
     {"name": "tpu-job-fused", "topology": "v5e-32",
      "fused_blocks": True}),
    ("tpu-job-simple", "tpu-job-queued.yaml",
     {"name": "tpu-job-queued", "topology": "v5e-8",
      "queue": "research", "priority": 1, "preemptible": True}),
    ("tpu-scheduler", "tpu-scheduler.yaml", {}),
    ("tf-job-simple", "tf-job-simple.yaml", {}),
    ("tpu-serving-simple", "tpu-serving-simple.yaml", {}),
    ("katib-studyjob-example", "katib-studyjob-example.yaml", {}),
    ("tpu-experiment-example", "tpu-experiment-example.yaml", {}),
    ("deploy-prober", "deploy-prober.yaml", {}),
]


def render(component: str, params: dict) -> str:
    header = (f"# Rendered from the {component!r} component "
              f"(kubeflow_tpu/manifests) — regenerate with\n"
              f"#   python examples/regenerate.py\n")
    return header + dump_all(build_component(component, params))


def main() -> int:
    for component, fname, params in EXAMPLES:
        with open(os.path.join(HERE, fname), "w") as f:
            f.write(render(component, params))
        print("wrote", fname)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
