"""Author a pipeline in Python and submit it (the kfp.dsl-style surface).

Prep step → gang-scheduled TPUJob → report step, with a run parameter.
Compile to YAML for kubectl, submit directly, or schedule it nightly.

Run against the dev cluster:
    python examples/pipeline_example.py          # prints the Workflow YAML
"""

import yaml

from kubeflow_tpu.pipelines import Pipeline


def build() -> Pipeline:
    p = Pipeline("train-and-report", namespace="kubeflow",
                 parameters={"steps": "1000"})
    prep = p.container(
        "prep", image="busybox",
        command=["sh", "-c", "echo fetching shards"])
    train = p.launch(
        "train",
        manifest={
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            # $(workflow.name) keeps the name run-unique so the pipeline
            # can also be scheduled (p.schedule("0 2 * * *"))
            "metadata": {"name": "job-$(workflow.name)",
                         "namespace": "kubeflow"},
            "spec": {
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [{
                        "name": "worker",
                        "image": "ghcr.io/kubeflow-tpu/worker:v0.1.0",
                        "command": ["python", "-m",
                                    "kubeflow_tpu.runtime.worker",
                                    "--workload", "resnet50",
                                    "--steps",
                                    "$(workflow.parameters.steps)"],
                    }]}},
                }},
                "checkpointDir": "/ckpt/$(workflow.name)",
            },
        },
        after=[prep])
    p.container("report", image="busybox",
                command=["sh", "-c", "echo run $(workflow.name) done"],
                after=[train])
    return p


if __name__ == "__main__":
    print(yaml.safe_dump(build().compile(), sort_keys=False))
