"""Goodput ledger + step-time flight recorder (ISSUE 10): span-stream
decomposition semantics, ledger reconstruction from real soak streams,
the flight recorder's ring/dump paths, the on-demand profiler trigger,
span-sink rotation, the sim's shared-vocabulary goodput tables, and the
dashboard/operator export surfaces."""

import json
import os
import signal
import urllib.request

import pytest

from kubeflow_tpu.obs.goodput import (BADPUT_CATEGORIES, BADPUT_CHECKPOINT,
                                      BADPUT_COMPILE, BADPUT_OTHER,
                                      BADPUT_QUEUE_WAIT, BADPUT_RECOMPUTE,
                                      BADPUT_RESIZE, BADPUT_STALL,
                                      BADPUT_STARTUP, GOODPUT_ANNOTATION,
                                      categories_sum_ok, cluster_rollup,
                                      decompose, export_job_ledger,
                                      ledger_for)
from kubeflow_tpu.obs.registry import Registry
from kubeflow_tpu.obs.trace import (SPAN_MAX_BYTES_ENV, SPAN_PATH_ENV,
                                    TRACE_ID_ANNOTATION, SpanWriter)

pytestmark = pytest.mark.goodput


def _span(name, start, end=None, trace_id="t", component="test", **attrs):
    rec = {"trace_id": trace_id, "span_id": "s", "parent_id": "",
           "name": name, "component": component, "start": float(start),
           "end": float(end if end is not None else start)}
    if attrs:
        rec["attrs"] = attrs
    return rec


def _sum(ledger) -> float:
    return ledger["goodputSeconds"] + sum(ledger["badputSeconds"].values())


class TestDecompose:
    def test_empty_stream(self):
        led = decompose([])
        assert led["wallSeconds"] == 0.0
        assert set(led["badputSeconds"]) == set(BADPUT_CATEGORIES)
        assert categories_sum_ok(led)

    def test_queue_wait_from_queued_bound_pairs(self):
        led = decompose([
            _span("queued", 0.0, chips=8),
            _span("bound", 10.0, chips=8),
            _span("window", 10.0, 20.0, step=10, steps=10),
        ])
        assert led["badputSeconds"][BADPUT_QUEUE_WAIT] == pytest.approx(10.0)
        assert led["goodputSeconds"] == pytest.approx(10.0)
        assert led["chips"] == 8
        assert categories_sum_ok(led)

    def test_never_bound_job_is_all_queue_wait(self):
        led = decompose([_span("queued", 0.0, chips=4),
                         _span("queued-heartbeat", 30.0)])
        assert led["badputSeconds"][BADPUT_QUEUE_WAIT] == pytest.approx(30.0)
        assert led["goodputSeconds"] == 0.0
        assert categories_sum_ok(led)

    def test_high_water_splits_replayed_windows(self):
        # trained to step 4, restarted from the step-2 checkpoint, and
        # re-ran 3..4 before new ground 5..6: the replay is recompute
        led = decompose([
            _span("window", 0.0, 4.0, step=4, steps=4),
            _span("window", 10.0, 14.0, step=6, steps=4),  # 3,4 replayed
        ])
        assert led["steps"] == 6
        assert led["stepsRecomputed"] == 2
        assert led["badputSeconds"][BADPUT_RECOMPUTE] == pytest.approx(2.0)
        assert led["goodputSeconds"] == pytest.approx(6.0)
        assert categories_sum_ok(led)

    def test_compile_outranks_first_window_and_splits_by_kind(self):
        # the first window CONTAINS the first step's compile: those
        # seconds are startup cost, not training
        led = decompose([
            _span("train-start", 0.0),
            _span("window", 0.0, 5.0, step=1, steps=1),
            _span("first-step", 4.0, start_kind="warm", seconds=4.0,
                  step=1),
        ])
        assert led["badputSeconds"][BADPUT_COMPILE] == pytest.approx(4.0)
        assert led["compileByStartKind"] == {"warm": 4.0}
        assert led["goodputSeconds"] == pytest.approx(1.0)
        assert categories_sum_ok(led)

    def test_compile_interval_clipped_to_stream(self):
        # the seconds attr measures from train() entry, which can
        # predate the job's first span — never invent pre-stream time
        led = decompose([
            _span("train-start", 0.0),
            _span("first-step", 2.0, start_kind="cold", seconds=10.0),
            _span("window", 2.0, 3.0, step=1, steps=1),
        ])
        assert led["wallSeconds"] == pytest.approx(3.0)
        assert led["badputSeconds"][BADPUT_COMPILE] == pytest.approx(2.0)
        assert led["compileByStartKind"]["cold"] == pytest.approx(2.0)
        assert categories_sum_ok(led)

    def test_checkpoint_spans_counted(self):
        led = decompose([
            _span("window", 0.0, 4.0, step=4, steps=4),
            _span("ckpt-save", 4.0, 5.5, step=4),
            _span("ckpt-restore", 6.0, 6.5, step=4),
            _span("window", 6.5, 8.5, step=6, steps=2),
        ])
        assert led["badputSeconds"][BADPUT_CHECKPOINT] == pytest.approx(2.0)
        assert categories_sum_ok(led)

    def test_stall_and_restart_downtime(self):
        # last activity at t=4; watchdog tears down at t=34; the gang's
        # next sign of life at t=40 — wedged stretch is stall, the
        # restart stretch startup
        led = decompose([
            _span("window", 0.0, 4.0, step=4, steps=4),
            _span("restarting", 34.0, reason="StallTimeout"),
            _span("train-start", 40.0),
            _span("window", 40.0, 42.0, step=6, steps=2),
        ])
        assert led["badputSeconds"][BADPUT_STALL] == pytest.approx(30.0)
        assert led["badputSeconds"][BADPUT_STARTUP] == pytest.approx(6.0)
        assert categories_sum_ok(led)

    def test_resize_downtime(self):
        led = decompose([
            _span("window", 0.0, 4.0, step=4, steps=4),
            _span("resized", 4.0, direction="shrink"),
            _span("train-start", 9.0),
            _span("window", 9.0, 10.0, step=5, steps=1),
        ])
        assert led["badputSeconds"][BADPUT_RESIZE] == pytest.approx(5.0)
        assert categories_sum_ok(led)

    def test_unattributed_residual_lands_in_other(self):
        led = decompose([
            _span("window", 0.0, 1.0, step=1, steps=1),
            _span("train-done", 11.0),
        ])
        assert led["badputSeconds"][BADPUT_OTHER] == pytest.approx(10.0)
        assert categories_sum_ok(led)

    def test_partition_is_exact_on_rich_stream(self):
        led = decompose([
            _span("queued", 0.0, chips=8),
            _span("bound", 5.0, chips=8),
            _span("train-start", 7.0),
            _span("window", 7.0, 12.0, step=2, steps=2),
            _span("first-step", 11.0, start_kind="cold", seconds=4.0,
                  step=1),
            _span("ckpt-save", 12.0, 12.5, step=2),
            _span("preempted", 13.0),
            _span("queued", 13.0, chips=8),
            _span("bound", 20.0, chips=8),
            _span("window", 22.0, 24.0, step=4, steps=2),
            _span("succeeded", 24.5),
        ])
        assert _sum(led) == pytest.approx(led["wallSeconds"], abs=1e-6)
        assert led["badputSeconds"][BADPUT_QUEUE_WAIT] == \
            pytest.approx(12.0)
        assert categories_sum_ok(led)


class TestExportAndRollup:
    def test_export_job_ledger_gauges(self):
        reg = Registry()
        led = decompose([_span("queued", 0.0), _span("bound", 2.0,
                                                     chips=8),
                         _span("window", 2.0, 4.0, step=2, steps=2)])
        export_job_ledger("ns1", "job1", led, registry=reg)
        text = reg.render()
        assert 'kftpu_job_goodput_ratio{namespace="ns1",name="job1"}' \
            in text
        # _total series keeps the Prometheus counter convention (the
        # registry's snapshot-bridge set())
        assert "# TYPE kftpu_job_badput_seconds_total counter" in text
        for cat in BADPUT_CATEGORIES:
            assert f'category="{cat}"' in text

    def test_remove_job_ledger_drops_series(self):
        from kubeflow_tpu.obs.goodput import remove_job_ledger
        reg = Registry()
        led = decompose([_span("bound", 0.0, chips=8),
                         _span("window", 0.0, 2.0, step=2, steps=2)])
        export_job_ledger("ns1", "gone", led, registry=reg)
        export_job_ledger("ns1", "kept", led, registry=reg)
        remove_job_ledger("ns1", "gone", registry=reg)
        text = reg.render()
        assert 'name="gone"' not in text
        assert 'name="kept"' in text

    def test_cluster_rollup_weights_by_chips(self, tmp_path):
        sink = str(tmp_path / "s.jsonl")
        with open(sink, "w") as f:
            for rec in (
                    _span("bound", 0.0, trace_id="a", chips=8),
                    _span("window", 0.0, 10.0, trace_id="a", step=10,
                          steps=10),
                    _span("queued", 0.0, trace_id="b", chips=4),
                    _span("queued-end", 5.0, trace_id="b")):
                f.write(json.dumps(rec) + "\n")
        roll = cluster_rollup(sink)
        assert len(roll["jobs"]) == 2
        assert roll["jobsNeverBound"] == 1
        # job a: 10s goodput on 8 chips = 80 chip-seconds (rollup
        # rounds to 6 decimals)
        assert roll["chipHours"]["goodput"] == \
            pytest.approx(80 / 3600.0, abs=1e-6)
        assert roll["goodputRatio"] == pytest.approx(1.0)

    def test_ledger_for_missing_sink(self, tmp_path):
        led = ledger_for(str(tmp_path / "missing.jsonl"), "t")
        assert led["wallSeconds"] == 0.0


class TestFlightRecorder:
    def _recorder(self, windows=4):
        from kubeflow_tpu.runtime.metrics import FlightRecorder
        return FlightRecorder(windows=windows)

    def test_ring_is_bounded(self):
        rec = self._recorder(windows=3)
        for i in range(6):
            rec.note_step(data_s=0.01, dispatch_s=0.02)
            rec.close_window(i + 1, 1, 0.05)
        snap = rec.snapshot()
        assert len(snap["records"]) == 3
        assert [r["step"] for r in snap["records"]] == [4, 5, 6]

    def test_window_record_stage_breakdown(self):
        rec = self._recorder()
        rec.note_step(data_s=0.01, h2d_s=0.005, dispatch_s=0.002)
        rec.note_step(data_s=0.01, h2d_s=0.005, dispatch_s=0.002)
        rec.close_window(2, 2, 0.1, drain_s=0.01)
        r = rec.snapshot()["records"][0]
        assert r["steps"] == 2
        assert r["data_s"] == pytest.approx(0.02)
        assert r["h2d_s"] == pytest.approx(0.01)
        assert r["dispatch_s"] == pytest.approx(0.004)
        # residual: wall + drain minus explained host time
        assert r["device_wait_s"] == pytest.approx(0.11 - 0.034)
        assert "input_batches" in r

    def test_dump_emits_span_with_in_progress_state(self, tmp_path):
        rec = self._recorder()
        rec.note_step(data_s=0.01)
        rec.close_window(1, 1, 0.05)
        rec.mark("step", 2)
        w = SpanWriter(str(tmp_path / "s.jsonl"), "worker", trace_id="t")
        assert rec.dump(w, "sigterm", extra="x") is not None
        w.close()
        recs = [json.loads(line)
                for line in open(tmp_path / "s.jsonl")]
        assert len(recs) == 1
        attrs = recs[0]["attrs"]
        assert recs[0]["name"] == "flight-record"
        assert attrs["reason"] == "sigterm"
        assert attrs["inProgress"]["stage"] == "step"
        assert attrs["inProgress"]["step"] == 2
        assert len(attrs["records"]) == 1

    def test_first_step_compile_not_charged_to_dispatch(self):
        rec = self._recorder()
        rec.note_step(data_s=0.001, dispatch_s=0.0, first_step_s=3.0)
        rec.note_step(data_s=0.001, dispatch_s=0.002)
        rec.close_window(2, 2, 3.1)
        r = rec.snapshot()["records"][0]
        assert r["dispatch_s"] == pytest.approx(0.002)
        assert r["first_step_s"] == pytest.approx(3.0)
        # the compile does not masquerade as device wait either
        assert r["device_wait_s"] == pytest.approx(3.1 - 3.004)

    def test_dump_without_tracer_or_disabled_is_noop(self):
        rec = self._recorder()
        assert rec.dump(None, "crash") is None
        off = self._recorder(windows=0)
        assert off.dump(object(), "crash") is None

    def test_sigterm_handler_dumps(self, tmp_path):
        # the teardown evidence path: PreemptionGuard's SIGTERM handler
        # both sets the stop flag AND dumps the ring
        from kubeflow_tpu.runtime.worker import PreemptionGuard
        rec = self._recorder()
        rec.close_window(1, 1, 0.05)
        w = SpanWriter(str(tmp_path / "s.jsonl"), "worker", trace_id="t")
        guard = PreemptionGuard(
            install=True, on_term=lambda: rec.dump(w, "sigterm"))
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        finally:
            guard.uninstall()
        w.close()
        assert guard.stop is True
        recs = [json.loads(line) for line in open(tmp_path / "s.jsonl")]
        assert recs and recs[0]["name"] == "flight-record"


class TestProfileArm:
    def _arm(self, tmp_path, calls):
        from kubeflow_tpu.runtime.metrics import ProfileArm
        return ProfileArm(
            base_dir=str(tmp_path),
            start_fn=lambda d: calls.append(("start", d)),
            stop_fn=lambda: calls.append(("stop",)))

    def test_arm_capture_stop_cycle(self, tmp_path):
        calls = []
        arm = self._arm(tmp_path, calls)
        code, body = arm.request(2)
        assert code == 200 and body["armed"] and body["steps"] == 2
        arm.on_step_start()
        assert calls and calls[0][0] == "start"
        assert calls[0][1] == body["dir"]
        arm.on_step_end(1)
        assert len(calls) == 1        # still one step to go
        arm.on_step_start()           # no second start while active
        arm.on_step_end(2)
        assert calls[-1] == ("stop",)
        # a finished capture can be re-armed
        code, _ = arm.request(1)
        assert code == 200

    def test_overlapping_request_rejected(self, tmp_path):
        arm = self._arm(tmp_path, [])
        assert arm.request(3)[0] == 200
        code, body = arm.request(1)
        assert code == 409 and "error" in body

    def test_bad_steps_rejected(self, tmp_path):
        arm = self._arm(tmp_path, [])
        assert arm.request("nope")[0] == 400
        assert arm.request(0)[0] == 400

    def test_obs_server_mounts_profile_and_flightrecorder(self, tmp_path):
        from kubeflow_tpu.obs.http import ObsServer
        from kubeflow_tpu.runtime.metrics import FlightRecorder
        calls = []
        arm = self._arm(tmp_path, calls)
        rec = FlightRecorder(windows=2)
        rec.close_window(1, 1, 0.1)
        srv = ObsServer(Registry(), host="127.0.0.1", handlers={
            ("POST", "/profile"):
                lambda q: arm.request(q.get("steps", 0)),
            ("GET", "/flightrecorder"): lambda q: (200, rec.snapshot()),
        })
        port = srv.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/profile?steps=3", data=b"",
                method="POST")
            with urllib.request.urlopen(req) as resp:
                body = json.loads(resp.read())
            assert body["armed"] and body["steps"] == 3
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/flightrecorder") as resp:
                snap = json.loads(resp.read())
            assert len(snap["records"]) == 1
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/nope", data=b"",
                    method="POST"))
        finally:
            srv.stop()


class TestSpanRotation:
    def test_rotation_at_cap(self, tmp_path):
        path = str(tmp_path / "s.jsonl")
        w = SpanWriter(path, "test", trace_id="t", max_bytes=600)
        for i in range(40):
            w.emit("window", start=float(i), end=float(i) + 1, step=i,
                   steps=1)
        w.close()
        assert os.path.exists(path + ".1")
        assert os.path.getsize(path) <= 600
        # BOTH generations parse as clean JSONL (no torn lines)
        for p in (path, path + ".1"):
            for line in open(p):
                json.loads(line)

    def test_rotation_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv(SPAN_MAX_BYTES_ENV, "500")
        path = str(tmp_path / "s.jsonl")
        w = SpanWriter(path, "test", trace_id="t")
        assert w.max_bytes == 500
        for i in range(30):
            w.event("queued", step=i)
        w.close()
        assert os.path.exists(path + ".1")

    def test_two_writers_share_a_rotating_sink_without_loss(self,
                                                            tmp_path):
        # the deployed shape: several writers (operator, scheduler,
        # worker + its dump writer) append to ONE capped sink. A writer
        # holding a handle onto a file a sibling already rotated must
        # re-open, not keep feeding the stale inode — and must never
        # clobber the sibling's fresh active file over the prior
        # generation. Total volume stays under 2x the cap, so every
        # record must survive across active + .1.
        path = str(tmp_path / "s.jsonl")
        a = SpanWriter(path, "op", trace_id="t", max_bytes=2000)
        b = SpanWriter(path, "wk", trace_id="t", max_bytes=2000)
        n = 0
        for i in range(12):
            a.emit("window", start=float(i), end=float(i) + 1, step=i,
                   steps=1)
            b.emit("window", start=float(i), end=float(i) + 1, step=i,
                   steps=1)
            n += 2
        a.close()
        b.close()
        survived = 0
        for p in (path, path + ".1"):
            if os.path.exists(p):
                for line in open(p):
                    json.loads(line)
                    survived += 1
        assert survived == n, f"lost {n - survived} spans to rotation"

    def test_no_cap_never_rotates(self, tmp_path):
        path = str(tmp_path / "s.jsonl")
        w = SpanWriter(path, "test", trace_id="t")
        for i in range(50):
            w.event("queued", step=i)
        w.close()
        assert not os.path.exists(path + ".1")

    def test_operator_manifest_renders_cap(self):
        from kubeflow_tpu.manifests.training import tpu_job_operator
        dep = next(o for o in tpu_job_operator(span_max_bytes=1048576)
                   if o["kind"] == "Deployment")
        env = {e["name"]: e["value"] for e in
               dep["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert env[SPAN_MAX_BYTES_ENV] == "1048576"
        # knob off: no env block entry
        dep = next(o for o in tpu_job_operator()
                   if o["kind"] == "Deployment")
        env = {e["name"]: e["value"] for e in
               (dep["spec"]["template"]["spec"]["containers"][0]
                .get("env") or [])}
        assert SPAN_MAX_BYTES_ENV not in env

    def test_operator_forwards_cap_to_workers(self, tmp_path,
                                              monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        monkeypatch.setenv(SPAN_PATH_ENV, str(tmp_path / "s.jsonl"))
        monkeypatch.setenv(SPAN_MAX_BYTES_ENV, "2048")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        try:
            cluster.create(_job_manifest(name="cap-job"))
            for _ in range(3):
                mgr.run_pending()
                cluster.tick()
            pod = cluster.get("v1", "Pod", "kubeflow",
                              "cap-job-worker-0-0")
            env = {e["name"]: e.get("value", "") for e in
                   pod["spec"]["containers"][0].get("env", [])}
            assert env[SPAN_MAX_BYTES_ENV] == "2048"
        finally:
            for c in mgr.controllers:
                c.stop()


class TestSimGoodput:
    def test_simulate_reports_shared_vocabulary(self):
        from kubeflow_tpu.scheduler.sim import make_workload, simulate
        r = simulate(make_workload(0, n_jobs=12), pools=("v5e-16",),
                     policy="preempt")
        table = r["goodput"]
        assert set(table["badput"]) == set(BADPUT_CATEGORIES)
        assert 0.0 <= table["goodput_fraction"] <= 1.0
        # contention on one small pool must show queue-wait badput
        assert table["badput"][BADPUT_QUEUE_WAIT] > 0

    def test_restart_cost_shows_as_startup_and_resize(self):
        from kubeflow_tpu.scheduler.sim import make_workload, simulate
        jobs = make_workload(1, n_jobs=12, elastic_frac=1.0)
        r = simulate(jobs, pools=("v5e-16",), policy="elastic",
                     restart_ticks=1.0)
        bad = r["goodput"]["badput"]
        assert bad[BADPUT_STARTUP] > 0
        if r["resizes"]:
            assert bad[BADPUT_RESIZE] > 0

    def test_compare_policies_aggregates_goodput(self):
        from kubeflow_tpu.scheduler.sim import compare_policies
        table = compare_policies([0], n_jobs=8, pools=("v5e-16",))
        for row in table.values():
            assert set(row["badput_chip_ticks"]) == set(BADPUT_CATEGORIES)
            assert "goodput_fraction" in row


def _job_manifest(name="gp-job", scheduled=False) -> dict:
    spec: dict = {"replicaSpecs": {"TPU": {
        "tpuTopology": "v5e-8",
        "template": {"spec": {"containers": [
            {"name": "jax", "image": "trainer:v1"}]}}}}}
    if scheduled:
        spec["schedulingPolicy"] = {"queue": "research", "priority": 1}
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow"},
            "spec": spec}


class TestDashboardEndpoints:
    def _sink_with_trace(self, tmp_path, trace_id):
        sink = str(tmp_path / "spans.jsonl")
        with open(sink, "w") as f:
            for rec in (_span("queued", 0.0, trace_id=trace_id, chips=8),
                        _span("bound", 4.0, trace_id=trace_id, chips=8),
                        _span("window", 6.0, 10.0, trace_id=trace_id,
                              step=4, steps=4)):
                f.write(json.dumps(rec) + "\n")
        return sink

    def test_job_goodput_endpoint(self, tmp_path, monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        sink = self._sink_with_trace(tmp_path, "dash1")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        cluster = FakeCluster()
        manifest = _job_manifest()
        manifest["metadata"]["annotations"] = {TRACE_ID_ANNOTATION:
                                               "dash1"}
        cluster.create(manifest)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch(
            "GET", "/api/obs/goodput/kubeflow/gp-job", None)
        assert status == 200 and body["source"] == "spans"
        led = body["ledger"]
        assert set(led["badputSeconds"]) == set(BADPUT_CATEGORIES)
        assert led["badputSeconds"][BADPUT_QUEUE_WAIT] == \
            pytest.approx(4.0)
        # cluster rollup from the same sink
        status, roll = app.dispatch("GET", "/api/obs/goodput", None)
        assert status == 200 and roll["chipHours"]["total"] > 0

    def test_annotation_fallback_when_spans_gone(self, tmp_path,
                                                 monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        monkeypatch.setenv(SPAN_PATH_ENV,
                           str(tmp_path / "empty.jsonl"))
        cluster = FakeCluster()
        manifest = _job_manifest()
        manifest["metadata"]["annotations"] = {
            TRACE_ID_ANNOTATION: "rotated-away",
            GOODPUT_ANNOTATION: json.dumps({"goodputRatio": 0.8}),
        }
        cluster.create(manifest)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch(
            "GET", "/api/obs/goodput/kubeflow/gp-job", None)
        assert status == 200 and body["source"] == "annotation"
        assert body["ledger"]["goodputRatio"] == 0.8

    def test_unknown_job_404(self, tmp_path, monkeypatch):
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        app = build_dashboard_app(FakeCluster())
        status, _ = app.dispatch(
            "GET", "/api/obs/goodput/kubeflow/ghost", None)
        assert status == 404


class TestOperatorFinalLedger:
    def test_completion_stamps_annotation_and_gauges(self, tmp_path,
                                                     monkeypatch):
        from kubeflow_tpu.api import k8s
        from kubeflow_tpu.cluster.fake import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        from kubeflow_tpu.obs.registry import (default_registry,
                                               reset_default_registry)
        from kubeflow_tpu.scheduler.core import SliceScheduler

        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        from kubeflow_tpu.obs.trace import reset_default_tracers
        reset_default_tracers()
        reset_default_registry()
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(SliceScheduler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        try:
            cluster.create(_job_manifest(name="done-job", scheduled=True))
            for _ in range(3):
                mgr.run_pending()
                cluster.tick()
            mgr.run_pending()
            cluster.set_pod_phase("kubeflow", "done-job-worker-0-0",
                                  "Succeeded")
            for _ in range(3):
                mgr.run_pending()
                cluster.tick()
            mgr.run_pending()
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              "kubeflow", "done-job")
            assert k8s.condition_true(job, "Succeeded")
            final = k8s.annotations_of(job).get(GOODPUT_ANNOTATION)
            assert final, "no final ledger stamped on completion"
            payload = json.loads(final)
            assert set(payload["badputSeconds"]) == set(BADPUT_CATEGORIES)
            assert payload["wallSeconds"] > 0
            text = default_registry().render()
            assert 'kftpu_job_goodput_ratio{namespace="kubeflow",' \
                   'name="done-job"}' in text
            assert "kftpu_job_badput_seconds_total" in text
        finally:
            for c in mgr.controllers:
                c.stop()
            reset_default_tracers()
            reset_default_registry()


@pytest.mark.compute
class TestWorkerLedgerIntegration:
    def test_train_stream_decomposes_and_sums(self, tmp_path,
                                              monkeypatch):
        from kubeflow_tpu.obs.trace import load_spans
        from kubeflow_tpu.runtime.worker import train
        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        monkeypatch.setenv("KFTPU_TRACE_ID", "wk1")
        train(workload="transformer", steps=4, global_batch=8,
              sync_every=2, checkpoint_dir=str(tmp_path / "ckpt"),
              checkpoint_every=2, workload_kwargs={})
        spans = load_spans(sink, trace_id="wk1")
        names = {s["name"] for s in spans}
        assert {"train-start", "first-step", "window", "ckpt-save",
                "train-done"} <= names
        led = decompose(spans)
        assert led["steps"] == 4 and led["stepsRecomputed"] == 0
        assert led["badputSeconds"][BADPUT_CHECKPOINT] > 0
        assert led["badputSeconds"][BADPUT_COMPILE] > 0
        assert categories_sum_ok(led)

    def test_resume_replay_shows_as_recompute(self, tmp_path,
                                              monkeypatch):
        from kubeflow_tpu.obs.trace import load_spans
        from kubeflow_tpu.runtime.worker import train
        sink = str(tmp_path / "spans.jsonl")
        ckpt = str(tmp_path / "ckpt")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        monkeypatch.setenv("KFTPU_TRACE_ID", "wk2")
        # run to 3, then lose the forced final save (the crash-between-
        # save-and-exit shape): the restart resumes at the step-2
        # checkpoint and replays step 3 — one recomputed step
        import shutil
        train(workload="transformer", steps=3, global_batch=8,
              sync_every=1, checkpoint_dir=ckpt, checkpoint_every=2,
              workload_kwargs={})
        shutil.rmtree(os.path.join(ckpt, "3"))
        r = train(workload="transformer", steps=5, global_batch=8,
                  sync_every=1, checkpoint_dir=ckpt, checkpoint_every=2,
                  workload_kwargs={})
        led = decompose(load_spans(sink, trace_id="wk2"))
        executed = 3 + r.steps
        assert led["steps"] == 5
        assert led["stepsRecomputed"] == executed - 5 == 1
        assert led["badputSeconds"][BADPUT_RECOMPUTE] > 0
        assert categories_sum_ok(led)


@pytest.mark.slow
class TestSoakLedgers:
    """Ledger reconstruction from REAL soak span streams (the
    acceptance shape bench.py --mode goodput reruns): categories sum to
    wall-clock, and restart-recompute matches the soak's own count of
    re-executed steps."""

    def test_chaos_soak_ledger(self, tmp_path, monkeypatch):
        from kubeflow_tpu.cluster.chaos import ChaosSoak, SoakFault
        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        faults = [SoakFault(2, "pod-kill"), SoakFault(3, "api-burst"),
                  SoakFault(4, "watch-drop"),
                  SoakFault(5, "truncate-ckpt"),
                  SoakFault(6, "hung-chief")]
        report = ChaosSoak(workdir=str(tmp_path / "soak"), faults=faults,
                           total_steps=8, checkpoint_every=2).run()
        assert report["outcome"] == "succeeded"
        led = ledger_for(sink, report["trace_id"])
        assert categories_sum_ok(led)
        known = report["executed_steps"] - report["final_step"]
        assert led["stepsRecomputed"] == known
        assert led["steps"] == report["final_step"]
        # the hung-chief fault must surface as stall badput
        assert led["badputSeconds"][BADPUT_STALL] > 0
        assert led["badputSeconds"][BADPUT_CHECKPOINT] > 0

    def test_preemption_soak_ledger(self, tmp_path, monkeypatch):
        from kubeflow_tpu.api import k8s
        from kubeflow_tpu.scheduler.soak import PreemptionSoak
        sink = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, sink)
        soak = PreemptionSoak(workdir=str(tmp_path / "soak"))
        report = soak.run()
        assert report["outcome"] == "succeeded"
        tid = k8s.annotations_of(report["victim_manifest"]).get(
            TRACE_ID_ANNOTATION)
        led = ledger_for(sink, tid)
        assert categories_sum_ok(led)
        # preempted AT a checkpoint boundary: resume loses zero steps,
        # and the ledger must agree with the soak's executed-step count
        known = report["victim_executed_steps"] - soak.total_steps
        assert led["stepsRecomputed"] == known == 0
        # two queue waits (admission + requeue after preemption)
        assert led["badputSeconds"][BADPUT_QUEUE_WAIT] > 0
        assert led["chips"] == 8
