"""Katib-equivalent tests: suggestion algorithms + StudyJob controller E2E.

The reference exercised katib only E2E on a real cluster
(testing/katib_studyjob_test.py:42-119 polls StudyJob conditions); here the
same loop runs against the in-memory apiserver with the real training-job
operator creating the trial gangs (SURVEY.md §4 envtest tier).
"""

import json

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.katib.studyjob import StudyJobReconciler
from kubeflow_tpu.katib.suggestion import (ParameterConfig,
                                           make_suggestion,
                                           parse_parameter_configs)
from kubeflow_tpu.katib.vizier import VizierDB, VizierService, report_observation


PARAM_CONFIGS = [
    {"name": "--lr", "parametertype": "double",
     "feasible": {"min": "0.01", "max": "0.05"}},
    {"name": "--num-layers", "parametertype": "int",
     "feasible": {"min": "2", "max": "5"}},
    {"name": "--optimizer", "parametertype": "categorical",
     "feasible": {"list": ["sgd", "adam", "ftrl"]}},
]


class TestSuggestions:
    def test_random_within_bounds(self):
        params = parse_parameter_configs(PARAM_CONFIGS)
        engine = make_suggestion("random", params, seed=7)
        for t in engine.suggest(20):
            assert 0.01 <= t["--lr"] <= 0.05
            assert 2 <= t["--num-layers"] <= 5
            assert t["--optimizer"] in ("sgd", "adam", "ftrl")

    def test_grid_exhaustive_product(self):
        params = parse_parameter_configs(PARAM_CONFIGS)
        engine = make_suggestion("grid", params, settings={"DefaultGrid": 2})
        seen = []
        while not engine.exhausted():
            batch = engine.suggest(4)
            assert batch
            seen.extend(json.dumps(t, sort_keys=True) for t in batch)
        # 2 lr x 2 layers x 3 optimizers (categorical always full list)
        assert len(seen) == len(set(seen)) == 2 * 2 * 3
        assert engine.suggest(4) == []

    def test_grid_int_grid_respects_integrality(self):
        p = ParameterConfig(name="n", parametertype="int", min=2, max=5)
        assert p.grid(10) == [2, 3, 4, 5]

    def test_hyperband_successive_halving(self):
        params = parse_parameter_configs([PARAM_CONFIGS[0]])
        engine = make_suggestion(
            "hyperband", params,
            settings={"eta": 3, "r_l": 9, "resourceName": "--epochs"})
        rounds = 0
        total = 0
        while not engine.exhausted() and rounds < 50:
            batch = engine.suggest(100)
            if not batch:
                break
            budgets = {t["--epochs"] for t in batch}
            assert len(budgets) == 1  # one budget per round
            for t in batch:
                # better lr (closer to max) scores higher
                engine.observe(t, t["--lr"])
            total += len(batch)
            rounds += 1
        assert engine.exhausted()
        assert total >= 6  # brackets s=2,1,0 for R=9, eta=3

    def test_bayesian_opt_improves_over_burn_in(self):
        params = parse_parameter_configs([
            {"name": "x", "parametertype": "double",
             "feasible": {"min": "0", "max": "1"}}])
        engine = make_suggestion("bayesianoptimization", params, seed=3,
                                 settings={"burn_in": 4})
        best_x = None
        best_v = -1e9
        for _ in range(20):
            (t,) = engine.suggest(1)
            v = -(t["x"] - 0.3) ** 2
            engine.observe(t, v)
            if v > best_v:
                best_v, best_x = v, t["x"]
        assert abs(best_x - 0.3) < 0.15

    def test_hyperband_drains_on_trial_failure(self):
        params = parse_parameter_configs([PARAM_CONFIGS[0]])
        engine = make_suggestion(
            "hyperband", params,
            settings={"eta": 3, "r_l": 9, "resourceName": "--epochs"})
        # every trial fails; the schedule must still drain to exhaustion
        # instead of re-suggesting the same configs forever
        for _ in range(200):
            if engine.exhausted():
                break
            batch = engine.suggest(100)
            if not batch:
                break
            for t in batch:
                engine.observe_failure(t)
        assert engine.exhausted()

    def test_unknown_algorithm_rejected(self):
        with pytest.raises(ValueError, match="unknown suggestion"):
            make_suggestion("tpe", [], seed=0)

    def test_invalid_parameter_config_rejected(self):
        with pytest.raises(ValueError, match="feasible"):
            parse_parameter_configs([
                {"name": "x", "parametertype": "double", "feasible": {}}])


class TestVizier:
    def test_objective_and_best_trial(self):
        db = VizierDB()
        db.create_study("s", objective_name="accuracy",
                        optimization_type="maximize")
        for trial, acc in [("t0", 0.7), ("t1", 0.9), ("t2", 0.8)]:
            db.register_trial("s", trial, {"lr": 0.1})
            db.report("s", trial, "accuracy", acc)
            db.set_trial_status("s", trial, "Succeeded")
            db.get_study("s").trials[trial].objective = acc
        assert db.objective_of("s", "t1") == 0.9
        assert db.best_trial("s").name == "t1"

    def test_latest_step_wins(self):
        db = VizierDB()
        db.create_study("s", objective_name="loss")
        db.report("s", "t", "loss", 2.0, step=1)
        db.report("s", "t", "loss", 0.5, step=10)
        assert db.objective_of("s", "t") == 0.5

    def test_snapshot_roundtrip(self):
        db = VizierDB()
        db.create_study("s", "loss", "minimize")
        db.register_trial("s", "t", {"lr": 0.1})
        db.report("s", "t", "loss", 1.5)
        db2 = VizierDB.from_snapshot(db.to_snapshot())
        assert db2.objective_of("s", "t") == 1.5
        assert db2.get_study("s").trials["t"].parameters == {"lr": 0.1}

    def test_http_service_report_and_query(self):
        svc = VizierService()
        svc.db.create_study("s", objective_name="loss")
        port = svc.start()
        try:
            ok = report_observation("loss", 0.25, step=3,
                                    url=f"http://127.0.0.1:{port}",
                                    study="s", trial="t0")
            assert ok
            assert svc.db.objective_of("s", "t0") == 0.25
            import urllib.request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/v1/studies/s") as r:
                body = json.loads(r.read())
            assert body["objectiveName"] == "loss"
        finally:
            svc.stop()


def studyjob_manifest(name="study", algorithm="grid", request_number=3,
                      **spec_extra):
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "StudyJob",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "studyName": name,
            "owner": "crd",
            "optimizationtype": "maximize",
            "objectivevaluename": "accuracy",
            "parameterconfigs": [
                {"name": "--lr", "parametertype": "double",
                 "feasible": {"min": "0.1", "max": "0.9"}},
            ],
            "suggestionSpec": {"suggestionAlgorithm": algorithm,
                               "requestNumber": request_number,
                               "suggestionParameters": [
                                   {"name": "DefaultGrid", "value": 3}]},
            "workerSpec": {"template": {
                "kind": "TPUJob",
                "spec": {"replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "train", "image": "trainer:v1",
                         "args": ["--model=resnet50"]}]}},
                }}},
            }},
            **spec_extra,
        },
    }


@pytest.fixture
def env():
    cluster = FakeCluster()
    for i in range(4):  # one slice pool per concurrent trial
        cluster.add_tpu_slice_nodes("v5e-8", pool=f"tpu-pool-{i}")
    vizier = VizierDB()
    mgr = Manager(cluster)
    mgr.add(TrainingJobReconciler("TPUJob"))
    study_ctrl = StudyJobReconciler(vizier=vizier, seed=11)
    mgr.add(study_ctrl)
    return cluster, mgr, vizier


def run_trials_to_completion(cluster, mgr, vizier, objective_fn,
                             max_rounds=60):
    """Drive controllers + scheduler; whenever a trial pod runs, report the
    objective (simulating the workload's report_observation call) and finish
    the pod."""
    def on_running(pod):
        env_map = {e["name"]: e.get("value")
                   for c in pod["spec"]["containers"]
                   for e in c.get("env", [])}
        trial = env_map.get("KFTPU_TRIAL")
        study = env_map.get("KFTPU_STUDY")
        if trial and study:
            args = [a for c in pod["spec"]["containers"]
                    for a in c.get("args", [])]
            lr = next((float(a.split("=", 1)[1]) for a in args
                       if a.startswith("--lr=")), 0.0)
            vizier.report(study, trial, "accuracy", objective_fn(lr))
        ns, name = (k8s.namespace_of(pod, "default"), k8s.name_of(pod))
        cluster.set_pod_phase(ns, name, "Succeeded")

    cluster.on_pod_running = on_running
    for _ in range(max_rounds):
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        study = cluster.list("kubeflow.org/v1alpha1", "StudyJob", "kubeflow")
        if study and (k8s.condition_true(study[0], "Succeeded") or
                      k8s.condition_true(study[0], "Failed")):
            return study[0]
    return cluster.list("kubeflow.org/v1alpha1", "StudyJob", "kubeflow")[0]


class TestStudyJobController:
    def test_grid_study_runs_all_trials_and_picks_best(self, env):
        cluster, mgr, vizier = env
        cluster.create(studyjob_manifest())
        study = run_trials_to_completion(
            cluster, mgr, vizier, objective_fn=lambda lr: 1.0 - (lr - 0.5) ** 2)
        assert k8s.condition_true(study, "Succeeded"), study.get("status")
        st = study["status"]
        assert st["trialsTotal"] == 3  # grid of 3 lr points
        assert st["trialsSucceeded"] == 3
        # grid points are 0.1, 0.5, 0.9 — best is lr=0.5
        assert abs(st["bestTrial"]["parameters"]["--lr"] - 0.5) < 1e-9
        # trial jobs carried the hyperparameter as a CLI flag
        trial_name = st["bestTrial"]["name"]
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                          trial_name)
        args = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
            "containers"][0]["args"]
        assert any(a.startswith("--lr=") for a in args)
        assert "--model=resnet50" in args

    def test_random_study_respects_max_trials(self, env):
        cluster, mgr, vizier = env
        cluster.create(studyjob_manifest(algorithm="random", request_number=2,
                                         maxTrials=4))
        study = run_trials_to_completion(
            cluster, mgr, vizier, objective_fn=lambda lr: lr)
        assert k8s.condition_true(study, "Succeeded")
        assert study["status"]["trialsTotal"] == 4

    def test_trials_are_owned_and_cascade_deleted(self, env):
        cluster, mgr, vizier = env
        cluster.create(studyjob_manifest())
        cluster.on_pod_running = lambda pod: None
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        jobs = cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow")
        assert jobs, "first trial round should exist"
        for j in jobs:
            refs = j["metadata"]["ownerReferences"]
            assert refs[0]["kind"] == "StudyJob"
        cluster.delete("kubeflow.org/v1alpha1", "StudyJob", "kubeflow", "study")
        assert cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                            "kubeflow") == []

    def test_metrics_via_configmap_collector_path(self, env):
        cluster, mgr, vizier = env
        cluster.create(studyjob_manifest(algorithm="random", request_number=1,
                                         maxTrials=1))

        def on_running(pod):
            env_map = {e["name"]: e.get("value")
                       for c in pod["spec"]["containers"]
                       for e in c.get("env", [])}
            trial = env_map.get("KFTPU_TRIAL")
            if trial:  # workload writes its metrics ConfigMap, no vizier URL
                cluster.apply({
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": f"{trial}-metrics",
                                 "namespace": "kubeflow"},
                    "data": {"accuracy": "0.91"}})
            cluster.set_pod_phase(k8s.namespace_of(pod, "default"),
                                  k8s.name_of(pod), "Succeeded")

        cluster.on_pod_running = on_running
        study = None
        for _ in range(40):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            study = cluster.get("kubeflow.org/v1alpha1", "StudyJob",
                                "kubeflow", "study")
            if k8s.condition_true(study, "Succeeded"):
                break
        assert k8s.condition_true(study, "Succeeded"), study.get("status")
        assert study["status"]["bestTrial"]["objective"] == 0.91

    def test_example_prototype_end_to_end(self, env):
        """The shipped katib-studyjob-example prototype runs to completion
        unmodified through the real controllers (SURVEY §2.3 hard part d:
        katib works against the TPU replica spec)."""
        from kubeflow_tpu.manifests import build_component
        cluster, mgr, vizier = env
        study_manifest = build_component(
            "katib-studyjob-example",
            {"namespace": "kubeflow", "name": "study",
             "max_trials": 4, "request_number": 2})[0]
        cluster.create(study_manifest)
        study = run_trials_to_completion(
            cluster, mgr, vizier, objective_fn=lambda lr: 0.9)
        assert k8s.condition_true(study, "Succeeded"), study.get("status")
        assert study["status"]["trialsTotal"] == 4
        best = study["status"]["bestTrial"]["name"]
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                          best)
        args = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
            "containers"][0]["args"]
        assert any(a.startswith("--learning-rate=") for a in args)
        assert any(a.startswith("--global-batch=") for a in args)

    def test_missing_worker_template_fails_study(self, env):
        cluster, mgr, _ = env
        m = studyjob_manifest()
        del m["spec"]["workerSpec"]["template"]
        cluster.create(m)
        mgr.run_pending()
        study = cluster.get("kubeflow.org/v1alpha1", "StudyJob", "kubeflow",
                            "study")
        assert k8s.condition_true(study, "Failed")

    def test_failed_trials_fail_study_past_threshold(self, env):
        cluster, mgr, vizier = env
        cluster.create(studyjob_manifest(algorithm="random", request_number=1,
                                         maxTrials=3, maxFailedTrials=0))
        # every trial pod fails → gang restarts exhaust backoff → job Failed
        cluster.on_pod_running = lambda pod: cluster.fail_pod(
            k8s.namespace_of(pod, "default"), k8s.name_of(pod))
        study = None
        for _ in range(60):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            study = cluster.get("kubeflow.org/v1alpha1", "StudyJob",
                                "kubeflow", "study")
            if k8s.condition_true(study, "Failed"):
                break
        assert k8s.condition_true(study, "Failed"), study.get("status")
