"""Hyperparameter-search tests: suggestion engines, the Experiment
reconciler E2E, StudyJob compat conversion, and the 200-trial
scheduler-burst coverage (ISSUE 19).

The reference exercised katib only E2E on a real cluster
(testing/katib_studyjob_test.py:42-119 polls StudyJob conditions); here
the same loop runs against the in-memory apiserver with the real
training-job operator creating the trial gangs (SURVEY.md §4 envtest
tier). The search object is the Experiment CRD (api/experiment.py);
legacy StudyJobs convert through katib/studyjob.py.
"""

import json
import time

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.experiment import (EXPERIMENT_API_VERSION,
                                         EXPERIMENT_KIND, Experiment)
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.experiment import ExperimentReconciler
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.katib.studyjob import (OBSERVATION_ANNOTATION,
                                         StudyJobCompatReconciler,
                                         studyjob_to_experiment)
from kubeflow_tpu.katib.suggestion import (ParameterConfig,
                                           make_suggestion,
                                           parse_parameter_configs)
from kubeflow_tpu.katib.vizier import VizierDB, VizierService, report_observation

pytestmark = pytest.mark.katib


PARAM_CONFIGS = [
    {"name": "--lr", "parametertype": "double",
     "feasible": {"min": "0.01", "max": "0.05"}},
    {"name": "--num-layers", "parametertype": "int",
     "feasible": {"min": "2", "max": "5"}},
    {"name": "--optimizer", "parametertype": "categorical",
     "feasible": {"list": ["sgd", "adam", "ftrl"]}},
]


class TestSuggestions:
    def test_random_within_bounds(self):
        params = parse_parameter_configs(PARAM_CONFIGS)
        engine = make_suggestion("random", params, seed=7)
        for t in engine.suggest(20):
            assert 0.01 <= t["--lr"] <= 0.05
            assert 2 <= t["--num-layers"] <= 5
            assert t["--optimizer"] in ("sgd", "adam", "ftrl")

    def test_grid_exhaustive_product(self):
        params = parse_parameter_configs(PARAM_CONFIGS)
        engine = make_suggestion("grid", params, settings={"DefaultGrid": 2})
        seen = []
        while not engine.exhausted():
            batch = engine.suggest(4)
            assert batch
            seen.extend(json.dumps(t, sort_keys=True) for t in batch)
        # 2 lr x 2 layers x 3 optimizers (categorical always full list)
        assert len(seen) == len(set(seen)) == 2 * 2 * 3
        assert engine.suggest(4) == []

    def test_grid_int_grid_respects_integrality(self):
        p = ParameterConfig(name="n", parametertype="int", min=2, max=5)
        assert p.grid(10) == [2, 3, 4, 5]

    def test_hyperband_successive_halving(self):
        params = parse_parameter_configs([PARAM_CONFIGS[0]])
        engine = make_suggestion(
            "hyperband", params,
            settings={"eta": 3, "r_l": 9, "resourceName": "--epochs"})
        rounds = 0
        total = 0
        while not engine.exhausted() and rounds < 50:
            batch = engine.suggest(100)
            if not batch:
                break
            budgets = {t["--epochs"] for t in batch}
            assert len(budgets) == 1  # one budget per round
            for t in batch:
                # better lr (closer to max) scores higher
                engine.observe(t, t["--lr"])
            total += len(batch)
            rounds += 1
        assert engine.exhausted()
        assert total >= 6  # brackets s=2,1,0 for R=9, eta=3

    def test_bayesian_opt_improves_over_burn_in(self):
        params = parse_parameter_configs([
            {"name": "x", "parametertype": "double",
             "feasible": {"min": "0", "max": "1"}}])
        engine = make_suggestion("bayesianoptimization", params, seed=3,
                                 settings={"burn_in": 4})
        best_x = None
        best_v = -1e9
        for _ in range(20):
            (t,) = engine.suggest(1)
            v = -(t["x"] - 0.3) ** 2
            engine.observe(t, v)
            if v > best_v:
                best_v, best_x = v, t["x"]
        assert abs(best_x - 0.3) < 0.15

    def test_hyperband_drains_on_trial_failure(self):
        params = parse_parameter_configs([PARAM_CONFIGS[0]])
        engine = make_suggestion(
            "hyperband", params,
            settings={"eta": 3, "r_l": 9, "resourceName": "--epochs"})
        # every trial fails; the schedule must still drain to exhaustion
        # instead of re-suggesting the same configs forever
        for _ in range(200):
            if engine.exhausted():
                break
            batch = engine.suggest(100)
            if not batch:
                break
            for t in batch:
                engine.observe_failure(t)
        assert engine.exhausted()

    def test_unknown_algorithm_rejected(self):
        with pytest.raises(ValueError, match="unknown suggestion"):
            make_suggestion("tpe", [], seed=0)

    def test_invalid_parameter_config_rejected(self):
        with pytest.raises(ValueError, match="feasible"):
            parse_parameter_configs([
                {"name": "x", "parametertype": "double", "feasible": {}}])


class TestVizier:
    def test_objective_and_best_trial(self):
        db = VizierDB()
        db.create_study("s", objective_name="accuracy",
                        optimization_type="maximize")
        for trial, acc in [("t0", 0.7), ("t1", 0.9), ("t2", 0.8)]:
            db.register_trial("s", trial, {"lr": 0.1})
            db.report("s", trial, "accuracy", acc)
            db.set_trial_status("s", trial, "Succeeded")
            db.get_study("s").trials[trial].objective = acc
        assert db.objective_of("s", "t1") == 0.9
        assert db.best_trial("s").name == "t1"

    def test_latest_step_wins(self):
        db = VizierDB()
        db.create_study("s", objective_name="loss")
        db.report("s", "t", "loss", 2.0, step=1)
        db.report("s", "t", "loss", 0.5, step=10)
        assert db.objective_of("s", "t") == 0.5

    def test_snapshot_roundtrip(self):
        db = VizierDB()
        db.create_study("s", "loss", "minimize")
        db.register_trial("s", "t", {"lr": 0.1})
        db.report("s", "t", "loss", 1.5)
        db2 = VizierDB.from_snapshot(db.to_snapshot())
        assert db2.objective_of("s", "t") == 1.5
        assert db2.get_study("s").trials["t"].parameters == {"lr": 0.1}

    def test_http_service_report_and_query(self):
        svc = VizierService()
        svc.db.create_study("s", objective_name="loss")
        port = svc.start()
        try:
            ok = report_observation("loss", 0.25, step=3,
                                    url=f"http://127.0.0.1:{port}",
                                    study="s", trial="t0")
            assert ok
            assert svc.db.objective_of("s", "t0") == 0.25
            import urllib.request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/v1/studies/s") as r:
                body = json.loads(r.read())
            assert body["objectiveName"] == "loss"
        finally:
            svc.stop()


# ------------------------------------------------------- experiment spec


def trial_template(topo="v5e-8", **spec_extra):
    spec = {"replicaSpecs": {"TPU": {
        "tpuTopology": topo,
        "template": {"spec": {"containers": [
            {"name": "train", "image": "trainer:v1",
             "args": ["--model=resnet50"]}]}},
    }}}
    spec.update(spec_extra)
    return {"kind": "TPUJob", "spec": spec}


def experiment_manifest(name="exp", ns="kubeflow", algorithm=None,
                        parameters=None, template=None, **spec_extra):
    spec = {
        "objective": {"type": "maximize", "metric": "accuracy"},
        "algorithm": algorithm or {"name": "grid",
                                   "settings": {"DefaultGrid": 3}},
        "parameters": parameters or [
            {"name": "--lr", "type": "double", "min": 0.1, "max": 0.9}],
        "maxTrials": 3,
        "parallelism": 2,
        "trialTemplate": template or trial_template(),
    }
    spec.update(spec_extra)
    return {"apiVersion": EXPERIMENT_API_VERSION, "kind": EXPERIMENT_KIND,
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


class TestExperimentSpec:
    def test_roundtrip(self):
        exp = Experiment.from_manifest(experiment_manifest())
        again = Experiment.from_manifest(exp.to_manifest())
        assert again.objective_metric == "accuracy"
        assert again.algorithm == "grid"
        assert again.parameters[0].name == "--lr"
        assert again.max_trials == 3 and again.parallelism == 2

    def test_unknown_spec_field_rejected(self):
        m = experiment_manifest()
        m["spec"]["maxTrails"] = 5  # the classic typo
        with pytest.raises(ValueError, match="maxTrails"):
            Experiment.from_manifest(m)

    def test_pbt_and_early_stopping_mutually_exclusive(self):
        m = experiment_manifest(
            algorithm="pbt", pbt={"truncation": 0.5},
            earlyStopping={"policy": "median"})
        with pytest.raises(ValueError, match="mutually exclusive"):
            Experiment.from_manifest(m)

    def test_pbt_needs_a_numeric_parameter(self):
        m = experiment_manifest(
            algorithm="pbt",
            parameters=[{"name": "--opt", "type": "categorical",
                         "values": ["sgd", "adam"]}])
        with pytest.raises(ValueError, match="numeric parameter"):
            Experiment.from_manifest(m)

    def test_bad_algorithm_and_template_kind_rejected(self):
        with pytest.raises(ValueError, match="algorithm"):
            Experiment.from_manifest(
                experiment_manifest(algorithm="tpe"))
        m = experiment_manifest()
        m["spec"]["trialTemplate"]["kind"] = "Deployment"
        with pytest.raises(ValueError, match="Deployment"):
            Experiment.from_manifest(m)

    def test_goal_and_better_follow_direction(self):
        exp = Experiment.from_manifest(experiment_manifest())
        m = experiment_manifest()
        m["spec"]["objective"] = {"type": "minimize", "metric": "loss",
                                  "goal": 0.1}
        lo = Experiment.from_manifest(m)
        assert exp.better(0.9, 0.5) and not exp.better(0.5, 0.9)
        assert lo.better(0.05, 0.2)
        assert lo.goal_reached(0.05) and not lo.goal_reached(0.2)


# --------------------------------------------------- studyjob conversion


def studyjob_manifest(name="study", algorithm="grid", request_number=3,
                      **spec_extra):
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "StudyJob",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "studyName": name,
            "owner": "crd",
            "optimizationtype": "maximize",
            "objectivevaluename": "accuracy",
            "parameterconfigs": [
                {"name": "--lr", "parametertype": "double",
                 "feasible": {"min": "0.1", "max": "0.9"}},
            ],
            "suggestionSpec": {"suggestionAlgorithm": algorithm,
                               "requestNumber": request_number,
                               "suggestionParameters": [
                                   {"name": "DefaultGrid", "value": 3}]},
            "workerSpec": {"template": trial_template()},
            **spec_extra,
        },
    }


class TestStudyJobConversion:
    def test_field_mapping_admits(self):
        m = studyjob_to_experiment(studyjob_manifest())
        exp = Experiment.from_manifest(m)
        assert exp.objective_type == "maximize"
        assert exp.objective_metric == "accuracy"
        assert exp.algorithm == "grid"
        assert exp.algorithm_settings == {"DefaultGrid": 3}
        assert exp.parameters[0].name == "--lr"
        assert exp.parameters[0].min == 0.1 and exp.parameters[0].max == 0.9
        assert exp.parallelism == 3
        assert exp.trial_template["kind"] == "TPUJob"

    def test_unsupported_algorithm_degrades_to_random(self):
        m = studyjob_to_experiment(
            studyjob_manifest(algorithm="bayesianoptimization"))
        assert m["spec"]["algorithm"]["name"] == "random"

    def test_trial_budget_defaults(self):
        # explicit maxTrials wins; grid gets a generous cap (engine
        # exhausts first); open-ended samplers keep 4 x requestNumber
        assert studyjob_to_experiment(studyjob_manifest(
            maxTrials=7))["spec"]["maxTrials"] == 7
        assert studyjob_to_experiment(studyjob_manifest())[
            "spec"]["maxTrials"] == 1 << 10
        assert studyjob_to_experiment(studyjob_manifest(
            algorithm="random", request_number=2))["spec"]["maxTrials"] == 8

    def test_missing_template_rejected(self):
        m = studyjob_manifest()
        del m["spec"]["workerSpec"]["template"]
        with pytest.raises(ValueError, match="template"):
            studyjob_to_experiment(m)


# ----------------------------------------------------- reconciler E2E


@pytest.fixture
def env():
    cluster = FakeCluster()
    for i in range(4):  # one slice pool per concurrent trial
        cluster.add_tpu_slice_nodes("v5e-8", pool=f"tpu-pool-{i}")
    mgr = Manager(cluster)
    mgr.add(TrainingJobReconciler("TPUJob"))
    mgr.add(ExperimentReconciler(seed=11))
    mgr.add(StudyJobCompatReconciler())
    yield cluster, mgr
    for c in mgr.controllers:
        c.stop()


def report_and_succeed(cluster, objective_fn):
    """Pod hook: report the objective through the observation annotation
    (the jax-free out-of-band path) and finish the pod."""
    def on_running(pod):
        env_map = {e["name"]: e.get("value")
                   for c in pod["spec"]["containers"]
                   for e in c.get("env", [])}
        trial = env_map.get("KFTPU_TRIAL")
        if trial:
            args = [a for c in pod["spec"]["containers"]
                    for a in c.get("args", [])]
            lr = next((float(a.split("=", 1)[1]) for a in args
                       if a.startswith("--lr=")), 0.0)
            ns = k8s.namespace_of(pod, "default")
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", ns,
                              trial)
            job["metadata"].setdefault("annotations", {})[
                OBSERVATION_ANNOTATION] = json.dumps(
                    {"accuracy": objective_fn(lr)})
            cluster.apply(job)
        cluster.set_pod_phase(k8s.namespace_of(pod, "default"),
                              k8s.name_of(pod), "Succeeded")
    return on_running


def run_to_completion(cluster, mgr, kind=EXPERIMENT_KIND,
                      api=EXPERIMENT_API_VERSION, name="exp",
                      max_rounds=80):
    obj = None
    for _ in range(max_rounds):
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        obj = cluster.get(api, kind, "kubeflow", name)
        if k8s.condition_true(obj, "Succeeded") or \
                k8s.condition_true(obj, "Failed"):
            return obj
    return obj


class TestExperimentController:
    def test_grid_runs_all_trials_and_picks_best(self, env):
        cluster, mgr = env
        cluster.create(experiment_manifest())
        cluster.on_pod_running = report_and_succeed(
            cluster, lambda lr: 1.0 - (lr - 0.5) ** 2)
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded"), exp.get("status")
        st = exp["status"]
        assert st["trialsTotal"] == 3  # grid of 3 lr points
        assert st["trialsSucceeded"] == 3
        # grid points are 0.1, 0.5, 0.9 — best is lr=0.5
        assert abs(st["bestTrial"]["parameters"]["--lr"] - 0.5) < 1e-9
        assert st["trialsPerHour"] > 0
        # the trial job carried the hyperparameter as a CLI flag and the
        # warm-start env (runtime schedule on)
        trial_name = st["bestTrial"]["name"]
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", trial_name)
        c0 = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
            "containers"][0]
        assert any(a.startswith("--lr=") for a in c0["args"])
        assert "--model=resnet50" in c0["args"]
        env_map = {e["name"]: e.get("value") for e in c0["env"]}
        assert env_map["KFTPU_RUNTIME_SCHEDULE"] == "1"
        assert env_map["KFTPU_EXPERIMENT"] == "exp"

    def test_parallelism_bounds_trials_in_flight(self, env):
        cluster, mgr = env
        cluster.create(experiment_manifest(algorithm="random", maxTrials=6,
                                           parallelism=2))
        seen_in_flight = []

        def on_running(pod):
            jobs = cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                "kubeflow")
            live = [j for j in jobs
                    if not (k8s.condition_true(j, "Succeeded") or
                            k8s.condition_true(j, "Failed"))]
            seen_in_flight.append(len(live))
            report_and_succeed(cluster, lambda lr: lr)(pod)
        cluster.on_pod_running = on_running
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded"), exp.get("status")
        assert exp["status"]["trialsTotal"] == 6
        assert seen_in_flight and max(seen_in_flight) <= 2

    def test_random_respects_max_trials(self, env):
        cluster, mgr = env
        cluster.create(experiment_manifest(
            algorithm="random", maxTrials=4))
        cluster.on_pod_running = report_and_succeed(cluster,
                                                    lambda lr: lr)
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded")
        assert exp["status"]["trialsTotal"] == 4

    def test_goal_reached_stops_spawning(self, env):
        cluster, mgr = env
        m = experiment_manifest(algorithm="random", maxTrials=10,
                                parallelism=1)
        m["spec"]["objective"]["goal"] = 0.5
        cluster.create(m)
        cluster.on_pod_running = report_and_succeed(cluster,
                                                    lambda lr: 0.9)
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded"), exp.get("status")
        # first trial hit the goal; no further budget spent
        assert exp["status"]["trialsTotal"] == 1
        msgs = " ".join(c.get("message", "")
                        for c in exp["status"].get("conditions", []))
        assert "goal reached" in msgs

    def test_trials_are_owned_and_cascade_deleted(self, env):
        cluster, mgr = env
        cluster.create(experiment_manifest())
        cluster.on_pod_running = lambda pod: None
        mgr.run_pending()
        cluster.tick()
        mgr.run_pending()
        jobs = cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                            "kubeflow")
        assert jobs, "first trials should exist"
        for j in jobs:
            refs = j["metadata"]["ownerReferences"]
            assert refs[0]["kind"] == EXPERIMENT_KIND
        cluster.delete(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                       "kubeflow", "exp")
        assert cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                            "kubeflow") == []

    def test_metrics_via_configmap_collector_path(self, env):
        cluster, mgr = env
        cluster.create(experiment_manifest(algorithm="random",
                                           maxTrials=1, parallelism=1))

        def on_running(pod):
            env_map = {e["name"]: e.get("value")
                       for c in pod["spec"]["containers"]
                       for e in c.get("env", [])}
            trial = env_map.get("KFTPU_TRIAL")
            if trial:  # workload writes its metrics ConfigMap
                cluster.apply({
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": f"{trial}-metrics",
                                 "namespace": "kubeflow"},
                    "data": {"accuracy": "0.91"}})
            cluster.set_pod_phase(k8s.namespace_of(pod, "default"),
                                  k8s.name_of(pod), "Succeeded")
        cluster.on_pod_running = on_running
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded"), exp.get("status")
        assert exp["status"]["bestTrial"]["objective"] == 0.91

    def test_running_trials_without_stopping_policy_reconcile_clean(
            self, env):
        """Regression: a pass over RUNNING trials with no earlyStopping
        spec must not crash in the stopping-poll tail (controller retry
        used to swallow the AttributeError silently)."""
        cluster, mgr = env
        cluster.create(experiment_manifest())
        cluster.on_pod_running = lambda pod: None
        for _ in range(4):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
        exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                          "kubeflow", "exp")
        assert any(t["status"] == "Running"
                   for t in exp["status"]["trials"])
        recon = next(c.reconciler for c in mgr.controllers
                     if isinstance(c.reconciler, ExperimentReconciler))
        res = recon.reconcile(cluster, ("kubeflow", "exp"))  # no raise
        assert res.requeue_after == 0

    def test_invalid_spec_fails_experiment(self, env):
        cluster, mgr = env
        m = experiment_manifest()
        del m["spec"]["trialTemplate"]
        cluster.create(m)
        mgr.run_pending()
        exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                          "kubeflow", "exp")
        assert k8s.condition_true(exp, "Failed")

    def test_failed_trials_fail_experiment_past_threshold(self, env):
        cluster, mgr = env
        cluster.create(experiment_manifest(
            algorithm="random", maxTrials=3, parallelism=1,
            maxFailedTrials=0))
        cluster.on_pod_running = lambda pod: cluster.fail_pod(
            k8s.namespace_of(pod, "default"), k8s.name_of(pod))
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Failed"), exp.get("status")

    def test_median_early_stopping_kills_seeded_bad_trial(self, env,
                                                          tmp_path):
        """Three trials report per-window objective spans; the seeded
        bad one (objective below the peer median at its window) is
        deleted mid-flight, recorded Stopped with stoppedEarly, and the
        experiment still completes off the survivors."""
        cluster, mgr = env
        span_path = str(tmp_path / "spans.jsonl")
        recon = next(c.reconciler for c in mgr.controllers
                     if isinstance(c.reconciler, ExperimentReconciler))
        recon._span_path = span_path
        cluster.create(experiment_manifest(
            parallelism=3,
            earlyStopping={"policy": "median", "minTrials": 2,
                           "startWindow": 2}))

        def write_windows(tid, values):
            with open(span_path, "a") as f:
                for w, v in enumerate(values):
                    f.write(json.dumps({
                        "trace_id": tid, "span_id": f"s{w}",
                        "parent_id": "", "name": "objective",
                        "component": "worker", "start": float(w),
                        "end": float(w),
                        "attrs": {"step": w * 10, "window": w,
                                  "accuracy": v}}) + "\n")

        # let the trials spawn and reach Running (pods stay up)
        cluster.on_pod_running = lambda pod: None
        for _ in range(4):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
        exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                          "kubeflow", "exp")
        trials = exp["status"]["trials"]
        assert len(trials) == 3
        by_lr = {t["parameters"]["--lr"]: t for t in trials}
        # lr=0.1 is the seeded bad trial; the others track high accuracy
        write_windows(by_lr[0.1]["traceId"], [0.2, 0.15, 0.1])
        write_windows(by_lr[0.5]["traceId"], [0.6, 0.7, 0.8])
        write_windows(by_lr[0.9]["traceId"], [0.5, 0.6, 0.7])
        # new windows arrive out-of-band — drive the stopping poll
        recon.reconcile(cluster, ("kubeflow", "exp"))
        mgr.run_pending()
        exp = cluster.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                          "kubeflow", "exp")
        stopped = [t for t in exp["status"]["trials"]
                   if t["status"] == "Stopped"]
        assert len(stopped) == 1
        assert stopped[0]["parameters"]["--lr"] == 0.1
        assert stopped[0]["stoppedEarly"] is True
        # its best-so-far stands as the result
        assert stopped[0]["objective"] == 0.2
        # the trial job is gone; survivors still run
        assert cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                   "kubeflow", stopped[0]["name"]) is None
        # finish the survivors through the annotation path
        cluster.on_pod_running = report_and_succeed(
            cluster, lambda lr: lr)
        for t in exp["status"]["trials"]:
            if t["status"] != "Stopped":
                job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1",
                                          "TPUJob", "kubeflow", t["name"])
                if job is not None:
                    job["metadata"].setdefault("annotations", {})[
                        OBSERVATION_ANNOTATION] = json.dumps(
                            {"accuracy": t["parameters"]["--lr"]})
                    cluster.apply(job)
        for pod in cluster.list("v1", "Pod", "kubeflow"):
            cluster.set_pod_phase("kubeflow", k8s.name_of(pod),
                                  "Succeeded")
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded"), exp.get("status")
        st = exp["status"]
        assert st["trialsStopped"] == 1 and st["trialsSucceeded"] == 2
        # the span sink is the source of truth for the final objective
        # too: lr=0.5 peaked at 0.8 in its last window
        assert st["bestTrial"]["parameters"]["--lr"] == 0.5
        assert st["bestTrial"]["objective"] == 0.8

    def test_pbt_generations_clone_from_winner_checkpoint(self, env):
        cluster, mgr = env
        template = trial_template(checkpointDir="/ckpt/$(trialName)")
        cluster.create(experiment_manifest(
            algorithm="pbt", parameters=[
                {"name": "--lr", "type": "double",
                 "min": 0.05, "max": 1.0}],
            template=template, maxTrials=4, parallelism=2,
            pbt={"truncation": 0.5, "perturbFactors": [0.8, 1.25]}))
        cluster.on_pod_running = report_and_succeed(cluster,
                                                    lambda lr: lr)
        exp = run_to_completion(cluster, mgr)
        assert k8s.condition_true(exp, "Succeeded"), exp.get("status")
        trials = exp["status"]["trials"]
        assert len(trials) == 4
        gen0 = [t for t in trials if t["generation"] == 0]
        gen1 = [t for t in trials if t["generation"] == 1]
        assert len(gen0) == len(gen1) == 2
        winner = max(gen0, key=lambda t: t["objective"])
        # every gen-1 member resumed from a gen-0 checkpoint
        for t in gen1:
            assert t["parent"] in {g["name"] for g in gen0}
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              "kubeflow", t["name"])
            assert job["spec"]["resumeFrom"] == f"/ckpt/{t['parent']}"
            assert job["spec"]["checkpointDir"] == f"/ckpt/{t['name']}"
            # perturbed params stay inside the feasible range
            assert 0.05 <= t["parameters"]["--lr"] <= 1.0
        # the clone exploits the WINNER (not the loser it replaces)
        clones = [t for t in gen1 if t["parent"] == winner["name"]]
        assert clones, [t["parent"] for t in gen1]

    def test_legacy_studyjob_converts_and_mirrors(self, env):
        cluster, mgr = env
        cluster.create(studyjob_manifest())
        cluster.on_pod_running = report_and_succeed(
            cluster, lambda lr: 1.0 - (lr - 0.5) ** 2)
        study = run_to_completion(cluster, mgr,
                                  kind="StudyJob",
                                  api="kubeflow.org/v1alpha1",
                                  name="study")
        assert k8s.condition_true(study, "Succeeded"), study.get("status")
        st = study["status"]
        assert st["trialsTotal"] == 3 and st["trialsSucceeded"] == 3
        assert abs(st["bestTrial"]["parameters"]["--lr"] - 0.5) < 1e-9
        # deleting the StudyJob cascades through the Experiment to jobs
        cluster.delete("kubeflow.org/v1alpha1", "StudyJob", "kubeflow",
                       "study")
        assert cluster.list(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                            "kubeflow") == []
        assert cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                            "kubeflow") == []

    def test_example_prototype_end_to_end(self, env):
        """The shipped katib-studyjob-example prototype still runs to
        completion unmodified — now through the compat converter + the
        Experiment reconciler."""
        from kubeflow_tpu.manifests import build_component
        cluster, mgr = env
        study_manifest = build_component(
            "katib-studyjob-example",
            {"namespace": "kubeflow", "name": "study",
             "max_trials": 4, "request_number": 2})[0]
        cluster.create(study_manifest)
        cluster.on_pod_running = report_and_succeed(cluster,
                                                    lambda lr: 0.9)
        study = run_to_completion(cluster, mgr, kind="StudyJob",
                                  api="kubeflow.org/v1alpha1",
                                  name="study")
        assert k8s.condition_true(study, "Succeeded"), study.get("status")
        assert study["status"]["trialsTotal"] == 4
        best = study["status"]["bestTrial"]["name"]
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                          "kubeflow", best)
        args = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
            "containers"][0]["args"]
        assert any(a.startswith("--learning-rate=") for a in args)
        assert any(a.startswith("--global-batch=") for a in args)


# ------------------------------------------------- 200-trial burst


@pytest.mark.sched
class TestTrialBurst:
    """ISSUE 19 satellite: a 200-trial burst is the production
    arrival-rate stress test for the gang queue — quota holds across
    trial namespaces, FIFO tiebreaks stay stable for same-timestamp
    bulk creates, steady-state passes write nothing, and the queue
    gauges drain to zero when the swarm completes."""

    def trial_job(self, i, ns="kubeflow", queue="search"):
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": f"burst-t{i}", "namespace": ns,
                         "labels": {
                             "katib.kubeflow.org/experiment": "burst",
                             "katib.kubeflow.org/trial": f"burst-t{i}"}},
            "spec": {
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-4",
                    "template": {"spec": {"containers": [
                        {"name": "train", "image": "trainer:v1"}]}}}},
                "schedulingPolicy": {"queue": queue},
            },
        }

    def _mgr(self, cluster, config=None):
        from kubeflow_tpu.scheduler.core import SliceScheduler
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(config))
        mgr.add(TrainingJobReconciler("TPUJob"))
        return mgr

    def test_200_trial_burst_fifo_and_gauges_drain(self):
        from kubeflow_tpu.obs import registry as obsreg
        obsreg.reset_default_registry()
        cluster = FakeCluster()
        for i in range(8):
            cluster.add_tpu_slice_nodes("v5e-4", pool=f"p{i}")
        mgr = self._mgr(cluster)
        # bulk create: one burst, same wall-clock second
        for i in range(200):
            cluster.create(self.trial_job(i))
        cluster.on_pod_running = lambda pod: cluster.set_pod_phase(
            k8s.namespace_of(pod, "default"), k8s.name_of(pod),
            "Succeeded")
        from kubeflow_tpu.api.trainingjob import BINDING_ANNOTATION
        bind_order = []
        bound_seen = set()
        done = 0
        for _ in range(400):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            jobs = cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                "kubeflow")
            for j in sorted(jobs, key=lambda j: int(
                    k8s.name_of(j).rsplit("t", 1)[1])):
                name = k8s.name_of(j)
                if name not in bound_seen and \
                        k8s.annotations_of(j).get(BINDING_ANNOTATION):
                    bound_seen.add(name)
                    bind_order.append(name)
            done = sum(1 for j in jobs
                       if k8s.condition_true(j, "Succeeded"))
            if done == 200:
                break
        assert done == 200, f"only {done}/200 trials completed"
        # FIFO tiebreak stability: same-timestamp bulk creates bind in
        # submission (uid) order — a later trial never jumps an earlier
        # one within the burst
        indices = [int(n.rsplit("t", 1)[1]) for n in bind_order]
        assert indices == sorted(indices), \
            "burst bound out of submission order"
        # queue gauges drain to zero
        from kubeflow_tpu.scheduler.core import SliceScheduler
        sched = next(c.reconciler for c in mgr.controllers
                     if isinstance(c.reconciler, SliceScheduler))
        sched.reconcile(cluster, ("", "#cluster-pass"))
        text = obsreg.default_registry().render()
        assert 'kftpu_sched_queue_depth{queue="search"} 0' in text
        assert 'kftpu_sched_bound_gangs{queue="search"} 0' in text
        assert 'kftpu_sched_queued_chips{queue="search"} 0' in text
        for c in mgr.controllers:
            c.stop()

    def test_quota_holds_across_trial_namespaces(self):
        from kubeflow_tpu.api.trainingjob import BINDING_ANNOTATION
        from kubeflow_tpu.scheduler.queue import (QueueSpec,
                                                  SchedulerConfig)
        cluster = FakeCluster()
        for i in range(8):
            cluster.add_tpu_slice_nodes("v5e-4", pool=f"p{i}")
        cfg = SchedulerConfig(queues={"search": QueueSpec(
            "search", quota_chips={"team-a": 8, "team-b": 4})})
        mgr = self._mgr(cluster, cfg)
        for i in range(10):
            cluster.create(self.trial_job(i, ns="team-a"))
        for i in range(10, 20):
            cluster.create(self.trial_job(i, ns="team-b"))
        cluster.on_pod_running = lambda pod: None  # trials stay up
        for _ in range(6):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
        bound = {"team-a": 0, "team-b": 0}
        for ns in bound:
            for j in cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  ns):
                if k8s.annotations_of(j).get(BINDING_ANNOTATION):
                    bound[ns] += 4  # v5e-4 chips
        # quota caps each trial namespace despite free capacity
        assert bound["team-a"] == 8, bound
        assert bound["team-b"] == 4, bound
        for c in mgr.controllers:
            c.stop()

    def test_steady_burst_pass_is_write_idempotent(self):
        from kubeflow_tpu.scheduler.core import SliceScheduler
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-4", pool="p0")
        mgr = self._mgr(cluster)
        for i in range(50):  # 1 binds, 49 wait
            cluster.create(self.trial_job(i))
        cluster.on_pod_running = lambda pod: None
        for _ in range(4):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
        rvs = {k8s.name_of(j): j["metadata"]["resourceVersion"]
               for j in cluster.list("tpu.kubeflow.org/v1alpha1",
                                     "TPUJob", "kubeflow")}
        sched = next(c.reconciler for c in mgr.controllers
                     if isinstance(c.reconciler, SliceScheduler))
        for _ in range(3):
            sched.reconcile(cluster, ("", "#cluster-pass"))
        after = {k8s.name_of(j): j["metadata"]["resourceVersion"]
                 for j in cluster.list("tpu.kubeflow.org/v1alpha1",
                                       "TPUJob", "kubeflow")}
        assert rvs == after, "steady-state burst pass rewrote objects"
        for c in mgr.controllers:
            c.stop()


# ------------------------------------------------------ rollup units


class TestRollup:
    def _exp(self):
        return Experiment.from_manifest(experiment_manifest())

    def test_warm_start_fraction_skips_first_trial(self):
        from kubeflow_tpu.obs import registry as obsreg
        obsreg.reset_default_registry()
        r = ExperimentReconciler()
        status = {"startedAt": time.time() - 3600}
        trials = [
            {"name": "t0", "status": "Succeeded", "startKind": "cold",
             "parameters": {}, "objective": 1.0},
            {"name": "t1", "status": "Succeeded", "startKind": "aot",
             "parameters": {}, "objective": 2.0},
            {"name": "t2", "status": "Succeeded", "startKind": "warm",
             "parameters": {}, "objective": 3.0},
            {"name": "t3", "status": "Stopped", "startKind": "aot",
             "parameters": {}, "objective": 0.5,
             "chipSecondsSaved": 7200.0},
        ]
        exp = self._exp()
        r._rollup(status, trials, trials[2], exp)
        # trials after the first: aot, warm, aot -> all warm
        assert status["warmStartFraction"] == 1.0
        assert status["chipHours"]["saved"] == 2.0
        assert status["trialsPerHour"] == 4.0
        text = obsreg.default_registry().render()
        assert "kftpu_experiment_warm_start_fraction" in text
        assert 'category="saved"' in text
        obsreg.reset_default_registry()

    def test_start_kind_from_ledger_evidence(self):
        sk = ExperimentReconciler._start_kind
        assert sk(None) == "unknown"
        assert sk({"compileByStartKind": {"aot": 1.0}}) == "aot"
        assert sk({"compileByStartKind": {"warm": 2.0,
                                          "cold": 0.0}}) == "warm"
        assert sk({"compileByStartKind": {"cold": 5.0}}) == "cold"
        assert sk({"compileByStartKind": {}}) == "unknown"
