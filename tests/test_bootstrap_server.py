"""Bootstrap REST service tests: the deploy-as-a-service surface
(ksServer.go routes /kfctl/apps/create, /kfctl/apps/apply, /kfctl/e2eDeploy,
/metrics — the reference exercised this with testing/test_deploy_app.py
as a periodic prober; here it's direct HTTP coverage)."""

import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.kfctl.bootstrap_server import BootstrapServer


def post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def get(url, raw=False):
    with urllib.request.urlopen(url) as r:
        data = r.read()
        return data.decode() if raw else json.loads(data)


@pytest.fixture
def server(tmp_path):
    s = BootstrapServer(str(tmp_path / "apps"))
    s.start()
    yield s, f"http://127.0.0.1:{s.port}"
    s.stop()


class TestBootstrapServer:
    def test_e2e_deploy_flow(self, server):
        _, base = server
        result = post(f"{base}/kfctl/e2eDeploy",
                      {"name": "kf-prod",
                       "components": ["tpu-job-operator", "tpu-serving"]})
        assert result["applied"] > 0
        assert result["failed"] == []
        assert "Available=True" in result["conditions"]

        apps = get(f"{base}/kfctl/apps")["apps"]
        assert [a["name"] for a in apps] == ["kf-prod"]
        shown = get(f"{base}/kfctl/apps/kf-prod")
        assert shown["components"]["tpu-job-operator"] > 0

        metrics = get(f"{base}/metrics", raw=True)
        assert "kubeflow_bootstrap_deploys_total 1" in metrics
        assert "deploy_failures_total 0" in metrics

    def test_create_then_apply_separately(self, server):
        _, base = server
        created = post(f"{base}/kfctl/apps/create",
                       {"name": "kf2", "components": ["echo-server"]})
        assert "Generated=True" in created["conditions"]
        applied = post(f"{base}/kfctl/apps/apply", {"name": "kf2"})
        assert applied["applied"] > 0

    def test_duplicate_create_409(self, server):
        _, base = server
        post(f"{base}/kfctl/apps/create",
             {"name": "kf3", "components": ["echo-server"]})
        with pytest.raises(urllib.error.HTTPError) as e:
            post(f"{base}/kfctl/apps/create",
                 {"name": "kf3", "components": ["echo-server"]})
        assert e.value.code == 409

    def test_apply_unknown_app_404(self, server):
        _, base = server
        with pytest.raises(urllib.error.HTTPError) as e:
            post(f"{base}/kfctl/apps/apply", {"name": "ghost"})
        assert e.value.code == 404

    def test_invalid_name_400(self, server):
        _, base = server
        for bad in ("../escape", "", "a/b"):
            with pytest.raises(urllib.error.HTTPError) as e:
                post(f"{base}/kfctl/apps/create", {"name": bad})
            assert e.value.code == 400

    def test_delete_frees_the_name(self, server):
        _, base = server
        post(f"{base}/kfctl/e2eDeploy",
             {"name": "kf4", "components": ["echo-server"]})
        result = post(f"{base}/kfctl/apps/delete", {"name": "kf4"})
        assert result["deleted"] == "kf4"
        with pytest.raises(urllib.error.HTTPError) as e:
            get(f"{base}/kfctl/apps/kf4")
        assert e.value.code == 404
        # the name is reusable — a service has no other way to free it
        again = post(f"{base}/kfctl/e2eDeploy",
                     {"name": "kf4", "components": ["echo-server"]})
        assert again["applied"] > 0

    def test_unknown_component_400_and_name_not_wedged(self, server):
        _, base = server
        with pytest.raises(urllib.error.HTTPError) as e:
            post(f"{base}/kfctl/e2eDeploy",
                 {"name": "kf6", "components": ["not-a-component"]})
        assert e.value.code == 400
        # failed create counted as a failed deploy, and the name is free
        metrics = get(f"{base}/metrics", raw=True)
        assert "deploy_failures_total 1" in metrics
        ok = post(f"{base}/kfctl/e2eDeploy",
                  {"name": "kf6", "components": ["echo-server"]})
        assert ok["applied"] > 0

    def test_e2e_deploy_is_retryable(self, server):
        _, base = server
        post(f"{base}/kfctl/apps/create",
             {"name": "kf5", "components": ["echo-server"]})
        # a repeated e2eDeploy of an existing app applies instead of 409ing
        result = post(f"{base}/kfctl/e2eDeploy", {"name": "kf5"})
        assert result["applied"] > 0

    def test_iam_routes_503_without_executor(self, server):
        _, base = server
        for route, body in (("iam/apply", {"project": "p", "cluster": "c"}),
                            ("initProject", {"project": "p",
                                             "projectNumber": "1"})):
            with pytest.raises(urllib.error.HTTPError) as e:
                post(f"{base}/kfctl/{route}", body)
            assert e.value.code == 503


class TestIamRoutes:
    """/kfctl/iam/apply + /kfctl/initProject over the GcpSimulator
    (ksServer.go:1465-1466; gcpUtils.go ApplyIamPolicy; initHandler.go)."""

    @pytest.fixture
    def iam_server(self, tmp_path):
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        sim = GcpSimulator()
        s = BootstrapServer(str(tmp_path / "apps"), gcp_executor=sim)
        s.start()
        yield sim, f"http://127.0.0.1:{s.port}"
        s.stop()

    def test_iam_apply_add_binds_generated_sas_and_iap_user(self,
                                                            iam_server):
        sim, base = iam_server
        out = post(f"{base}/kfctl/iam/apply",
                   {"project": "proj", "cluster": "kf",
                    "email": "alice@example.com"})
        assert out["action"] == "add"
        roles = {b["role"]: b["members"]
                 for b in sim.iam_policy["bindings"]}
        admin = "serviceAccount:kf-admin@proj.iam.gserviceaccount.com"
        assert admin in roles["roles/tpu.admin"]
        assert admin in roles["roles/container.admin"]
        assert "serviceAccount:kf-vm@proj.iam.gserviceaccount.com" in \
            roles["roles/logging.logWriter"]
        assert "user:alice@example.com" in \
            roles["roles/iap.httpsResourceAccessor"]

    def test_iam_apply_preserves_unrelated_members(self, iam_server):
        sim, base = iam_server
        sim.iam_policy["bindings"] = [
            {"role": "roles/owner", "members": ["user:boss@example.com"]},
            {"role": "roles/tpu.admin",
             "members": ["serviceAccount:other@proj.iam.gserviceaccount"
                         ".com"]}]
        post(f"{base}/kfctl/iam/apply",
             {"project": "proj", "cluster": "kf"})
        roles = {b["role"]: b["members"]
                 for b in sim.iam_policy["bindings"]}
        assert "user:boss@example.com" in roles["roles/owner"]
        assert "serviceAccount:other@proj.iam.gserviceaccount.com" in \
            roles["roles/tpu.admin"]

    def test_iam_apply_remove_then_policy_clean(self, iam_server):
        sim, base = iam_server
        post(f"{base}/kfctl/iam/apply",
             {"project": "proj", "cluster": "kf",
              "email": "alice@example.com"})
        post(f"{base}/kfctl/iam/apply",
             {"project": "proj", "cluster": "kf",
              "email": "alice@example.com", "action": "remove"})
        members = [m for b in sim.iam_policy["bindings"]
                   for m in b["members"]]
        assert not any("kf-admin@proj" in m or "alice@" in m
                       for m in members)

    def test_iam_apply_clears_stale_generated_sa_bindings(self,
                                                          iam_server):
        # a leftover binding from a previous deploy under another role is
        # reset, not accumulated (ClearServiceAccountPolicy semantics)
        sim, base = iam_server
        sim.iam_policy["bindings"] = [
            {"role": "roles/owner",
             "members": ["serviceAccount:kf-admin@proj.iam"
                         ".gserviceaccount.com"]}]
        post(f"{base}/kfctl/iam/apply", {"project": "proj", "cluster": "kf"})
        roles = {b["role"]: b["members"]
                 for b in sim.iam_policy["bindings"]}
        assert "roles/owner" not in roles  # stale binding dropped (empty)
        assert "serviceAccount:kf-admin@proj.iam.gserviceaccount.com" in \
            roles["roles/tpu.admin"]

    def test_init_project_binds_dm_service_account(self, iam_server):
        sim, base = iam_server
        out = post(f"{base}/kfctl/initProject",
                   {"project": "proj", "projectNumber": "12345"})
        assert out["project"] == "proj"
        roles = {b["role"]: b["members"]
                 for b in sim.iam_policy["bindings"]}
        assert "serviceAccount:12345@cloudservices.gserviceaccount.com" \
            in roles["roles/resourcemanager.projectIamAdmin"]
        # idempotent: a second call does not duplicate the member
        post(f"{base}/kfctl/initProject",
             {"project": "proj", "projectNumber": "12345"})
        roles = {b["role"]: b["members"]
                 for b in sim.iam_policy["bindings"]}
        assert roles["roles/resourcemanager.projectIamAdmin"].count(
            "serviceAccount:12345@cloudservices.gserviceaccount.com") == 1

    def test_iam_apply_validates_request(self, iam_server):
        _, base = iam_server
        for bad in ({"cluster": "kf"}, {"project": "p"},
                    {"project": "p", "cluster": "c", "action": "wipe"}):
            with pytest.raises(urllib.error.HTTPError) as e:
                post(f"{base}/kfctl/iam/apply", bad)
            assert e.value.code == 400
