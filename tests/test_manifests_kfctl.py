"""Tests for the manifest registry and kfctl coordinator.

Tier-1 of the reference test strategy (SURVEY.md §4): manifest correctness by
pure evaluation — golden-object asserts like
kubeflow/tf-training/tests/tf-job_test.jsonnet — plus CLI lifecycle tests
(kfctl_go_test.py analog, against the simulated cluster instead of GCP).
"""

import os

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.manifests import (REGISTRY, build_component,
                                    component_names)
from kubeflow_tpu.kfctl.coordinator import Coordinator
from kubeflow_tpu.api.kfdef import DEFAULT_COMPONENTS


class TestRegistry:
    def test_default_components_all_registered(self):
        missing = [c for c in DEFAULT_COMPONENTS if c not in REGISTRY]
        assert not missing, f"default components without builders: {missing}"

    def test_every_builder_produces_valid_manifests(self):
        for name in component_names():
            objs = build_component(name)
            assert objs, f"{name} produced no manifests"
            for obj in objs:
                assert obj.get("apiVersion"), f"{name}: missing apiVersion"
                assert obj.get("kind"), f"{name}: missing kind"
                assert k8s.name_of(obj), f"{name}: missing metadata.name"

    def test_unknown_param_rejected(self):
        with pytest.raises(ValueError, match="unknown params"):
            build_component("tensorboard", {"nope": 1})

    def test_unknown_component_rejected(self):
        with pytest.raises(KeyError):
            build_component("does-not-exist")

    def test_params_introspected(self):
        assert "namespace" in REGISTRY["katib"].params


class TestGoldenManifests:
    """Golden-object asserts (tf-job_test.jsonnet:16-40 idiom)."""

    def test_legacy_job_kind_crds(self):
        """chainer/mxnet/paddle parity (kubeflow/chainer-job etc.)."""
        for comp, kind, plural in [
                ("chainer-operator", "ChainerJob", "chainerjobs"),
                ("mxnet-operator", "MXJob", "mxjobs"),
                ("paddle-operator", "PaddleJob", "paddlejobs")]:
            crd = build_component(comp)[0]
            assert crd["kind"] == "CustomResourceDefinition"
            assert crd["spec"]["names"]["kind"] == kind
            assert crd["spec"]["names"]["plural"] == plural
            assert crd["spec"]["group"] == "kubeflow.org"

    def test_aws_package_shapes(self):
        """kubeflow/aws parity: ALB ingress, EFS/FSx CSI, istio ingress."""
        alb = build_component("alb-ingress-controller")
        kinds = [o["kind"] for o in alb]
        assert "Deployment" in kinds and "ClusterRole" in kinds
        efs = build_component("aws-efs-csi-driver",
                              {"filesystem_id": "fs-123"})
        by_kind = {o["kind"]: o for o in efs}
        assert by_kind["DaemonSet"]["spec"]["template"]["spec"][
            "containers"][0]["securityContext"]["privileged"]
        assert by_kind["StorageClass"]["provisioner"] == "efs.csi.aws.com"
        assert by_kind["PersistentVolume"]["spec"]["csi"][
            "volumeHandle"] == "fs-123"
        ing = build_component("aws-istio-ingress")[0]
        assert ing["metadata"]["annotations"][
            "kubernetes.io/ingress.class"] == "alb"

    def test_tpu_job_operator_shape(self):
        objs = build_component("tpu-job-operator")
        by_kind = {}
        for o in objs:
            by_kind.setdefault(o["kind"], []).append(o)
        crd = by_kind["CustomResourceDefinition"][0]
        assert crd["spec"]["group"] == "tpu.kubeflow.org"
        assert crd["spec"]["names"]["kind"] == "TPUJob"
        dep = by_kind["Deployment"][0]
        assert "--enable-gang-scheduling" in \
            dep["spec"]["template"]["spec"]["containers"][0]["args"]
        role = by_kind["ClusterRole"][0]
        assert any("podgroups" in r.get("resources", []) for r in role["rules"])

    def test_gang_scheduling_off_drops_rbac(self):
        objs = build_component("tpu-job-operator", {"gang_scheduling": False})
        role = next(o for o in objs if o["kind"] == "ClusterRole")
        assert not any("podgroups" in r.get("resources", [])
                       for r in role["rules"])

    def test_mpijob_crd_oneof(self):
        crd = build_component("mpi-operator")[0]
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        oneof = schema["properties"]["spec"]["oneOf"]
        assert {"required": ["tpuTopology"]} in oneof

    def test_serving_http_proxy_sidecar(self):
        objs = build_component("tpu-serving",
                               {"model_name": "mnist",
                                "enable_http_proxy": True})
        dep = next(o for o in objs if o["kind"] == "Deployment")
        containers = dep["spec"]["template"]["spec"]["containers"]
        assert [c["name"] for c in containers] == ["model-server", "http-proxy"]
        assert dep["spec"]["template"]["spec"]["containers"][0][
            "resources"]["limits"]["google.com/tpu"] == 1
        vs = next(o for o in objs if o["kind"] == "VirtualService")
        assert vs["spec"]["http"][0]["match"][0]["uri"]["prefix"] == \
            "/models/mnist/"

    def test_serving_hpa_param(self):
        objs = build_component("tpu-serving", {"enable_hpa": True,
                                               "hpa_max": 8})
        hpa = next(o for o in objs
                   if o["kind"] == "HorizontalPodAutoscaler")
        assert hpa["spec"]["maxReplicas"] == 8

    def test_katib_suggestion_algorithms(self):
        objs = build_component("katib", {"algorithms": "random,grid"})
        deps = [k8s.name_of(o) for o in objs if o["kind"] == "Deployment"]
        assert "vizier-suggestion-random" in deps
        assert "vizier-suggestion-grid" in deps
        assert "vizier-suggestion-hyperband" not in deps

    def test_tpu_job_simple_example(self):
        job = build_component("tpu-job-simple", {"topology": "v5e-32"})[0]
        from kubeflow_tpu.api.trainingjob import TrainingJob
        parsed = TrainingJob.from_manifest(job)  # example must be admissible
        assert parsed.tpu_spec.topology.name == "v5e-32"

    def test_tpu_job_measured_routing_configmap(self):
        """fused_routing renders a mounted ConfigMap + the env var the
        worker's routing reads — the k8s path for deploying a
        chip-measured kernel routing table (bench fused-blocks output)."""
        import json
        routes = {"56x56_256_64_256": "spatial:14",
                  "7x7_2048_512_2048": "xla"}
        objs = build_component("tpu-job-simple", {
            "fused_blocks": True, "fused_routing": routes})
        cm = next(o for o in objs if o["kind"] == "ConfigMap")
        assert json.loads(cm["data"]["routing.json"])["routes"] == routes
        job = next(o for o in objs if o["kind"] == "TPUJob")
        spec = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"]
        c = spec["containers"][0]
        assert "--fused-blocks" in c["command"]
        env = {e["name"]: e["value"] for e in c["env"]}
        path = env["KFTPU_FUSED_ROUTING_TABLE"]
        mount = c["volumeMounts"][0]
        assert path.startswith(mount["mountPath"])
        assert spec["volumes"][0]["configMap"]["name"] == \
            cm["metadata"]["name"]
        # the example must stay admissible with the routing attached
        from kubeflow_tpu.api.trainingjob import TrainingJob
        TrainingJob.from_manifest(job)
        # a table without the fused path would be a silent no-op: rejected
        import pytest
        with pytest.raises(ValueError, match="fused_blocks"):
            build_component("tpu-job-simple", {"fused_routing": routes})

    def test_tpu_serving_simple_example(self):
        """tf-serving-simple analog: smallest useful serving instance."""
        objs = build_component("tpu-serving-simple")
        dep = next(o for o in objs if o["kind"] == "Deployment")
        containers = dep["spec"]["template"]["spec"]["containers"]
        assert "--model-name=mnist" in containers[0]["args"]
        assert any(c["name"] == "http-proxy" for c in containers)

    def test_katib_studyjob_example_schema(self):
        """katib-studyjob-test analog: StudyJob sweeping the TPUJob."""
        study = build_component("katib-studyjob-example")[0]
        spec = study["spec"]
        assert spec["suggestionSpec"]["suggestionAlgorithm"] == "random"
        assert {p["name"] for p in spec["parameterconfigs"]} == {
            "--learning-rate", "--global-batch"}
        tmpl = spec["workerSpec"]["template"]
        assert tmpl["kind"] == "TPUJob"
        assert tmpl["spec"]["replicaSpecs"]["TPU"]["tpuTopology"] == "v5e-8"

    def test_webhook_targets_pods(self):
        objs = build_component("admission-webhook")
        wh = next(o for o in objs
                  if o["kind"] == "MutatingWebhookConfiguration")
        assert wh["webhooks"][0]["rules"][0]["resources"] == ["pods"]


class TestEcosystemPackages:
    """Catalog breadth: the alt-serving + data/gitops/build packages
    (kubeflow/{openvino,nvidia-inference-server,modeldb,spark,pachyderm,
    weaveflux,knative-build} parity)."""

    def test_all_registered_and_render(self):
        from kubeflow_tpu.manifests import build_component
        for name in ("openvino", "tpu-inference-server", "modeldb",
                     "spark-operator", "pachyderm", "weaveflux",
                     "knative-build"):
            objs = build_component(name)
            assert objs, name
            for o in objs:
                assert o.get("kind") and o.get("apiVersion"), (name, o)

    def test_tpu_inference_server_targets_tpu_pool(self):
        from kubeflow_tpu.manifests import build_component
        objs = build_component("tpu-inference-server",
                               {"model_repository": "gs://m"})
        dep = next(o for o in objs if o["kind"] == "Deployment")
        sel = dep["spec"]["template"]["spec"]["nodeSelector"]
        assert "gke-tpu-accelerator" in next(iter(sel))
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--model-repository=gs://m" in args

    def test_spark_operator_crds(self):
        from kubeflow_tpu.manifests import build_component
        kinds = {o["kind"]: o for o in build_component("spark-operator")}
        crds = [o for o in build_component("spark-operator")
                if o["kind"] == "CustomResourceDefinition"]
        assert {c["spec"]["names"]["kind"] for c in crds} == \
            {"SparkApplication", "ScheduledSparkApplication"}
        assert "Deployment" in kinds


class TestCoordinator:
    def test_full_lifecycle(self, tmp_path):
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, platform="existing")
        coord.init()
        assert os.path.exists(os.path.join(app, "app.yaml"))
        written = coord.generate()
        assert len(written) == len(coord.kfdef.spec.components)
        outcome = coord.apply(sleep=lambda s: None)
        assert not outcome.failed and outcome.applied > 50
        # reload from disk (LoadKfApp analog) and verify cluster persisted
        coord2 = Coordinator.load(app)
        crds = coord2.client.list("apiextensions.k8s.io/v1",
                                  "CustomResourceDefinition")
        assert any(k8s.name_of(c) == "tpujobs.tpu.kubeflow.org" for c in crds)
        show = coord2.show()
        assert show["conditions"][-1] == "Available=True"
        coord2.delete()
        assert coord2.client.list("apps/v1", "Deployment") == []

    def test_flavor_overlays_render_differently(self, tmp_path):
        """kustomize-v2 MergeKustomization analog (r2 verdict #9): the
        iap and basic_auth flavors render different manifest sets from
        the same app."""
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, flavor="iap")
        coord.init()
        written = coord.generate()
        names = {os.path.basename(p) for p in written}
        assert {"iap-ingress.yaml", "cert-manager.yaml",
                "cloud-endpoints.yaml"} <= names
        assert "basic-auth-ingress.yaml" not in names

        # switching flavors re-renders: basic_auth drops the IAP set and
        # adds the gatekeeper-backed ingress (stale renders cleared)
        coord.kfdef.spec.flavor = "basic_auth"
        names2 = {os.path.basename(p) for p in coord.generate()}
        assert {"basic-auth-ingress.yaml", "gatekeeper.yaml"} <= names2
        assert "iap-ingress.yaml" not in names2
        mdir = os.path.join(app, "manifests")
        assert not os.path.exists(os.path.join(mdir, "iap-ingress.yaml"))

        # flavor params flow into the rendered objects, user params win
        from kubeflow_tpu.manifests.overlays import resolve
        comps, params = resolve(
            ["centraldashboard"], {"iap-ingress": {"hostname": "kf.my.org"}},
            "iap")
        assert params["iap-ingress"]["hostname"] == "kf.my.org"
        assert params["iap-ingress"]["upstream"] == "centraldashboard:80"

    def test_flavor_unknown_rejected(self, tmp_path):
        from kubeflow_tpu.manifests.overlays import resolve
        with pytest.raises(KeyError, match="unknown flavor"):
            resolve(["istio"], {}, "nope")

    def _write_config_dir(self, root):
        os.makedirs(os.path.join(root, "base"))
        os.makedirs(os.path.join(root, "overlays", "gcp", "iap"))
        os.makedirs(os.path.join(root, "overlays", "monitoring"))
        with open(os.path.join(root, "base", "config.yaml"), "w") as f:
            f.write("components: [centraldashboard, echo-server]\n"
                    "componentParams:\n  echo-server: {namespace: mon}\n")
        with open(os.path.join(root, "overlays", "gcp", "iap",
                               "config.yaml"), "w") as f:
            f.write("description: IAP ingress\n"
                    "componentsAdd: [iap-ingress]\n"
                    "componentsRemove: [echo-server]\n"
                    "componentParams:\n"
                    "  iap-ingress: {hostname: kf.example.org}\n")
        with open(os.path.join(root, "overlays", "monitoring",
                               "config.yaml"), "w") as f:
            f.write("componentsAdd: [prometheus]\n")

    def test_config_dir_walk_and_merge(self, tmp_path):
        """On-disk config layouts (bootstrap/config/{base,overlays/*}):
        the walk discovers nested overlays (kustomize.go mapDirs) and
        the merge is user > overlay > base."""
        from kubeflow_tpu.manifests.overlays import (resolve_config_dir,
                                                     walk_config_dir)
        root = str(tmp_path / "config")
        self._write_config_dir(root)
        base, overlays = walk_config_dir(root)
        assert base.components_add == ("centraldashboard", "echo-server")
        assert set(overlays) == {"gcp/iap", "monitoring"}

        comps, params = resolve_config_dir(
            root, ["tensorboard"],
            {"iap-ingress": {"hostname": "user.example.org"}},
            flavor="gcp/iap")
        assert comps == ["centraldashboard", "iap-ingress", "tensorboard"]
        assert params["iap-ingress"]["hostname"] == "user.example.org"

        # built-in flavors still resolve when the dir has no such overlay
        comps2, _ = resolve_config_dir(root, [], {}, flavor="basic_auth")
        assert "basic-auth-ingress" in comps2
        with pytest.raises(KeyError, match="unknown flavor"):
            resolve_config_dir(root, [], {}, flavor="nope")
        with pytest.raises(FileNotFoundError, match="base/config.yaml"):
            walk_config_dir(str(tmp_path / "missing"))

    def test_config_dir_drives_generate(self, tmp_path):
        # the full CLI path: base list renders, overlay flavor swaps it
        root = str(tmp_path / "config")
        self._write_config_dir(root)
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, components=[], config_dir=root)
        coord.init()
        names = {os.path.basename(p) for p in coord.generate()}
        assert names == {"centraldashboard.yaml", "echo-server.yaml"}
        coord.kfdef.spec.flavor = "gcp/iap"
        names2 = {os.path.basename(p) for p in coord.generate()}
        assert "iap-ingress.yaml" in names2
        assert "echo-server.yaml" not in names2
        # persisted: a reloaded app keeps the config dir AND the
        # explicit empty component list (a falsy-[] reload falling back
        # to DEFAULT_COMPONENTS would resurrect ~23 components on top
        # of the base)
        coord3 = Coordinator.load(app)
        assert coord3.kfdef.spec.config_dir == root
        assert coord3.kfdef.spec.components == []
        coord3.kfdef.spec.flavor = ""
        names3 = {os.path.basename(p) for p in coord3.generate()}
        assert names3 == {"centraldashboard.yaml", "echo-server.yaml"}

    def test_flavor_persisted_in_app_yaml(self, tmp_path):
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, flavor="basic_auth")
        coord.init()
        coord2 = Coordinator.load(app)
        assert coord2.kfdef.spec.flavor == "basic_auth"

    def test_apply_without_generate_fails(self, tmp_path):
        app = str(tmp_path / "app")
        coord = Coordinator.new(app)
        coord.init()
        with pytest.raises(FileNotFoundError, match="generate"):
            coord.apply()

    def test_component_params_flow_through(self, tmp_path):
        app = str(tmp_path / "app")
        coord = Coordinator.new(
            app, components=["tpu-serving"],
            component_params={"tpu-serving": {"model_name": "bert",
                                              "num_replicas": 3}})
        coord.init()
        coord.generate()
        from kubeflow_tpu.utils import yamlio
        objs = yamlio.load_all(
            open(os.path.join(app, "manifests", "tpu-serving.yaml")).read())
        dep = next(o for o in objs if o["kind"] == "Deployment")
        assert dep["spec"]["replicas"] == 3

    def test_gcp_generate_writes_tpu_nodepool(self, tmp_path):
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, platform="gcp", project="my-proj",
                                default_tpu_topology="v5e-32")
        coord.init()
        coord.generate()
        from kubeflow_tpu.utils import yamlio
        cfg = yamlio.load_file(
            os.path.join(app, "gcp_config", "cluster-kubeflow.yaml"))
        pools = cfg["resources"][0]["properties"]["cluster"]["nodePools"]
        tpu_pool = next(p for p in pools if p["name"] == "tpu-pool")
        assert tpu_pool["initialNodeCount"] == 8  # v5e-32 = 8 hosts
        assert tpu_pool["config"]["machineType"] == "ct5lp-hightpu-4t"

    def test_gcp_apply_gated_without_executor(self, tmp_path):
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, platform="gcp", project="p")
        coord.init()
        coord.generate()
        with pytest.raises(RuntimeError, match="cloud access"):
            coord.apply("platform")


class TestGcpDriver:
    """gcp.go parity behind the executor seam (r2 verdict weak #6):
    updateDM insert/update, blockingWait backoff, IAM merge, secrets."""

    def _platform(self, tmp_path, sim, **kw):
        from kubeflow_tpu.kfctl.platforms import Backoff, GcpPlatform
        app = str(tmp_path / "app")
        coord = Coordinator.new(app, platform="gcp", project="proj-1")
        coord.init()
        coord.generate()
        sleeps = []
        platform = GcpPlatform(executor=sim,
                               backoff=Backoff(initial_s=1.0, factor=2.0,
                                               max_interval_s=8.0,
                                               deadline_s=100.0),
                               sleep=sleeps.append, **kw)
        return coord, platform, sleeps

    def test_apply_inserts_then_updates(self, tmp_path):
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        sim = GcpSimulator(polls_until_done=2)
        coord, platform, _ = self._platform(tmp_path, sim)
        platform.apply(coord.kfdef)
        methods = [m for m, _ in sim.calls]
        assert "deployments.insert" in methods
        assert "deployments.update" not in methods
        # second apply takes the update path with the live fingerprint
        platform.apply(coord.kfdef)
        methods = [m for m, _ in sim.calls]
        assert "deployments.update" in methods

    def test_blocking_wait_backs_off_exponentially(self, tmp_path):
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        sim = GcpSimulator(polls_until_done=4)
        coord, platform, sleeps = self._platform(tmp_path, sim)
        platform.apply(coord.kfdef)
        # first op: RUNNING for 3 polls → sleeps 1, 2, 4 then DONE
        assert sleeps[:3] == [1.0, 2.0, 4.0]

    def test_op_error_raises(self, tmp_path):
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        from kubeflow_tpu.kfctl.platforms import CloudOpError
        sim = GcpSimulator(polls_until_done=2, fail_op="op-1")
        coord, platform, _ = self._platform(tmp_path, sim)
        with pytest.raises(CloudOpError, match="quota exceeded"):
            platform.apply(coord.kfdef)

    def test_iam_merge_preserves_existing_members(self, tmp_path):
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        sim = GcpSimulator()
        sim.iam_policy["bindings"] = [
            {"role": "roles/tpu.admin", "members": ["user:pre@corp.io"]}]
        coord, platform, _ = self._platform(tmp_path, sim)
        platform.apply(coord.kfdef)
        roles = {b["role"]: b["members"]
                 for b in sim.iam_policy["bindings"]}
        assert "user:pre@corp.io" in roles["roles/tpu.admin"]
        assert any("serviceAccount:" in m
                   for m in roles["roles/tpu.admin"])
        assert "roles/container.admin" in roles

    def test_secrets_and_admin_binding_staged(self, tmp_path):
        import os as _os
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        from kubeflow_tpu.utils import yamlio
        sim = GcpSimulator()
        coord, platform, _ = self._platform(tmp_path, sim)
        platform.apply(coord.kfdef)
        d = _os.path.join(coord.kfdef.spec.app_dir, "gcp_config")
        secrets = yamlio.load_file(_os.path.join(d, "secrets.yaml"))
        assert secrets["secrets"][0]["metadata"]["name"] == "admin-gcp-sa"
        assert secrets["secrets"][0]["data"]["admin-gcp-sa.json"]
        rbac = yamlio.load_file(_os.path.join(d, "default-admin.yaml"))
        assert rbac["roleRef"]["name"] == "cluster-admin"

    def test_delete_polls_to_done(self, tmp_path):
        from kubeflow_tpu.kfctl.gcp_sim import GcpSimulator
        sim = GcpSimulator(polls_until_done=2)
        coord, platform, _ = self._platform(tmp_path, sim)
        platform.apply(coord.kfdef)
        platform.delete(coord.kfdef)
        assert coord.kfdef.name + "-cluster" not in sim.deployments
        assert [m for m, _ in sim.calls].count("deployments.delete") == 1


class TestLocalPlatformDrivers:
    """minikube.go / dockerfordesktop.go parity behind the runner seam."""

    def test_minikube_checks_vm_and_context(self):
        from kubeflow_tpu.api.kfdef import KfDef
        from kubeflow_tpu.kfctl.platforms import Minikube
        calls = []

        def runner(cmd):
            calls.append(cmd)
            if cmd[0] == "minikube":
                return "Running\n"
            return "minikube\n"

        Minikube(runner=runner).init(KfDef(name="k"))
        assert calls[0][0] == "minikube"
        assert calls[1][:2] == ["kubectl", "config"]

    def test_minikube_not_running_rejected(self):
        from kubeflow_tpu.api.kfdef import KfDef
        from kubeflow_tpu.kfctl.platforms import Minikube
        with pytest.raises(RuntimeError, match="not running"):
            Minikube(runner=lambda cmd: "Stopped").init(KfDef(name="k"))

    def test_minikube_wrong_context_rejected(self):
        from kubeflow_tpu.api.kfdef import KfDef
        from kubeflow_tpu.kfctl.platforms import Minikube

        def runner(cmd):
            return "Running" if cmd[0] == "minikube" else "gke_prod"

        with pytest.raises(RuntimeError, match="context"):
            Minikube(runner=runner).init(KfDef(name="k"))

    def test_docker_for_desktop_context(self):
        from kubeflow_tpu.api.kfdef import KfDef
        from kubeflow_tpu.kfctl.platforms import DockerForDesktop
        DockerForDesktop(runner=lambda c: "docker-desktop").init(
            KfDef(name="k"))
        with pytest.raises(RuntimeError, match="context"):
            DockerForDesktop(runner=lambda c: "minikube").init(
                KfDef(name="k"))

    def test_missing_cli_is_loud(self):
        # default runner shells out; a missing minikube/kubectl CLI must
        # be an actionable error, not a silent no-op
        from kubeflow_tpu.api.kfdef import KfDef
        from kubeflow_tpu.kfctl.platforms import Minikube, _subprocess_runner
        with pytest.raises(RuntimeError,
                           match="not found|not running|failed"):
            Minikube(runner=lambda cmd: _subprocess_runner(
                ["definitely-not-a-binary-xyz"])).init(KfDef(name="k"))


class TestGoldenManifestsRound3:
    """Golden-shape asserts for the packages only the generic render loop
    touched (observability, multitenancy, GCP auth/storage, pipelines)."""

    def test_prometheus_scrapes_platform_targets(self):
        objs = build_component("prometheus")
        cm = next(o for o in objs if o["kind"] == "ConfigMap")
        conf = "".join(cm["data"].values())
        assert "scrape_configs" in conf
        dep = next(o for o in objs if o["kind"] == "Deployment")
        assert "prometheus" in dep["spec"]["template"]["spec"][
            "containers"][0]["image"]

    def test_tpu_device_plugin_daemonset(self):
        objs = build_component("tpu-device-plugin")
        ds = next(o for o in objs if o["kind"] == "DaemonSet")
        spec = ds["spec"]["template"]["spec"]
        # lands ONLY on TPU nodes (the gpu-driver.libsonnet slot) — a
        # toleration alone would schedule it everywhere
        assert "cloud.google.com/gke-tpu-accelerator" in \
            spec["nodeSelector"]

    def test_profiles_crd_and_controller(self):
        objs = build_component("profiles")
        crd = next(o for o in objs if o["kind"] == "CustomResourceDefinition")
        assert crd["spec"]["names"]["kind"] == "Profile"
        assert crd["spec"]["scope"] == "Cluster"

    def test_credentials_pod_preset_shape(self):
        objs = build_component("credentials-pod-preset")
        pd = next(o for o in objs if o["kind"] == "PodDefault")
        assert pd["spec"].get("env") or pd["spec"].get("volumeMounts")

    def test_iap_ingress_wires_jwt_key(self):
        objs = build_component("iap-ingress")
        by_kind = {}
        for o in objs:
            by_kind.setdefault(o["kind"], []).append(o)
        dep = by_kind["Deployment"][0]
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--mode=iap" in args
        secret_vols = {v["secret"]["secretName"]
                       for v in dep["spec"]["template"]["spec"]["volumes"]
                       if v.get("secret")}
        assert "iap-ingress-key" in secret_vols  # the JWT signing key

    def test_cert_manager_crds(self):
        objs = build_component("cert-manager")
        kinds = {o["spec"]["names"]["kind"] for o in objs
                 if o["kind"] == "CustomResourceDefinition"}
        assert "Certificate" in kinds

    def test_minio_and_db_have_storage(self):
        for comp in ("minio", "pipeline-db"):
            objs = build_component(comp)
            kinds = [o["kind"] for o in objs]
            assert "PersistentVolumeClaim" in kinds, comp

    def test_pipeline_viewer_crd(self):
        objs = build_component("pipeline-viewercrd")
        crd = next(o for o in objs if o["kind"] == "CustomResourceDefinition")
        assert crd["spec"]["names"]["kind"] == "Viewer"

    def test_gcp_filestore_pv_pvc_pair(self):
        objs = build_component("gcp-filestore",
                               {"server_ip": "10.9.9.9"})  # non-default:
        # the builder falls back to 10.0.0.2, so only a non-default value
        # proves the param is actually wired
        pv = next(o for o in objs if o["kind"] == "PersistentVolume")
        assert pv["spec"]["nfs"]["server"] == "10.9.9.9"
        assert any(o["kind"] == "PersistentVolumeClaim" for o in objs)
