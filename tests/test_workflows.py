"""Workflow engine + kubebench harness tests.

The reference's analog is Argo (deployed, not tested in-repo) and the Argo
DAG builder its CI uses (testing/workflows/components/workflows.libsonnet
kfTests: checkout → deploy → parallel steps → teardown). Here the engine is
ours, so the DAG semantics — dependency gating, fail-fast + Omitted,
resource-template condition matching, deadlines — get direct envtest-style
coverage.
"""


import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.workflows.engine import (WORKFLOW_API_VERSION,
                                           WorkflowReconciler,
                                           check_condition_expr)
from kubeflow_tpu.workflows.kubebench import (KUBEBENCH_API_VERSION,
                                              KubebenchJobReconciler,
                                              build_kubebench_workflow,
                                              write_csv_report)


def wf_manifest(name="wf", tasks=None, templates=None, entrypoint="main",
                **spec_extra):
    tasks = tasks if tasks is not None else [
        {"name": "a", "template": "step"},
        {"name": "b", "template": "step", "dependencies": ["a"]},
    ]
    templates = templates if templates is not None else [
        {"name": "step", "container": {"image": "busybox",
                                       "command": ["true"]}},
    ]
    return {
        "apiVersion": WORKFLOW_API_VERSION, "kind": "Workflow",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {"entrypoint": entrypoint,
                 "templates": [{"name": "main", "dag": {"tasks": tasks}}]
                 + templates,
                 **spec_extra},
    }


@pytest.fixture
def env():
    cluster = FakeCluster()
    cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
    mgr = Manager(cluster)
    mgr.add(WorkflowReconciler())
    return cluster, mgr


def get_wf(cluster, name="wf"):
    return cluster.get(WORKFLOW_API_VERSION, "Workflow", "kubeflow", name)


def finish_pods(cluster, phase="Succeeded"):
    for pod in cluster.list("v1", "Pod", "kubeflow"):
        if pod.get("status", {}).get("phase") == "Running":
            cluster.set_pod_phase("kubeflow", k8s.name_of(pod), phase)


class TestConditionExpr:
    def test_status_phase_form(self):
        assert check_condition_expr({"status": {"phase": "Succeeded"}},
                                    "status.phase = Succeeded")
        assert not check_condition_expr({"status": {}}, "status.phase=X")

    def test_condition_form(self):
        obj = {"status": {"conditions": [
            {"type": "Succeeded", "status": "True"}]}}
        assert check_condition_expr(obj, "condition:Succeeded=True")
        assert not check_condition_expr(obj, "condition:Failed=True")


class TestWorkflowEngine:
    def test_dag_dependency_ordering(self, env):
        cluster, mgr = env
        cluster.create(wf_manifest())
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert [k8s.name_of(p) for p in pods] == ["wf-a"]  # b gated on a
        cluster.tick()  # a starts Running
        cluster.set_pod_phase("kubeflow", "wf-a", "Succeeded")
        mgr.run_pending()
        pods = {k8s.name_of(p) for p in cluster.list("v1", "Pod", "kubeflow")}
        assert pods == {"wf-a", "wf-b"}
        cluster.tick()
        cluster.set_pod_phase("kubeflow", "wf-b", "Succeeded")
        mgr.run_pending()
        wf = get_wf(cluster)
        assert wf["status"]["phase"] == "Succeeded"
        assert wf["status"]["nodes"]["a"]["phase"] == "Succeeded"

    def test_fail_fast_marks_downstream_omitted(self, env):
        cluster, mgr = env
        cluster.create(wf_manifest(tasks=[
            {"name": "a", "template": "step"},
            {"name": "b", "template": "step", "dependencies": ["a"]},
            {"name": "c", "template": "step", "dependencies": ["b"]},
        ]))
        mgr.run_pending()
        cluster.tick()
        cluster.fail_pod("kubeflow", "wf-a")
        mgr.run_pending()
        wf = get_wf(cluster)
        assert wf["status"]["phase"] == "Failed"
        assert wf["status"]["nodes"]["a"]["phase"] == "Failed"
        assert wf["status"]["nodes"]["b"]["phase"] == "Omitted"
        assert wf["status"]["nodes"]["c"]["phase"] == "Omitted"

    def test_steps_template_serial_groups(self, env):
        cluster, mgr = env
        m = {
            "apiVersion": WORKFLOW_API_VERSION, "kind": "Workflow",
            "metadata": {"name": "wf", "namespace": "kubeflow"},
            "spec": {"entrypoint": "main", "templates": [
                {"name": "main", "steps": [
                    [{"name": "s1", "template": "step"}],
                    [{"name": "s2a", "template": "step"},
                     {"name": "s2b", "template": "step"}],
                ]},
                {"name": "step", "container": {"image": "busybox"}},
            ]},
        }
        cluster.create(m)
        mgr.run_pending()
        assert {k8s.name_of(p) for p in cluster.list("v1", "Pod", "kubeflow")} \
            == {"wf-s1"}
        cluster.tick()
        finish_pods(cluster)
        mgr.run_pending()
        # both members of group 2 launch together after group 1
        assert {k8s.name_of(p) for p in cluster.list("v1", "Pod", "kubeflow")} \
            == {"wf-s1", "wf-s2a", "wf-s2b"}

    def test_parameter_substitution(self, env):
        cluster, mgr = env
        m = wf_manifest(
            tasks=[{"name": "a", "template": "step"}],
            templates=[{"name": "step", "container": {
                "image": "bench:$(workflow.parameters.tag)",
                "args": ["--run=$(workflow.name)"]}}],
            arguments={"parameters": [{"name": "tag", "value": "v9"}]})
        cluster.create(m)
        mgr.run_pending()
        pod = cluster.get("v1", "Pod", "kubeflow", "wf-a")
        assert pod["spec"]["containers"][0]["image"] == "bench:v9"
        assert pod["spec"]["containers"][0]["args"] == ["--run=wf"]

    def test_resource_template_tracks_condition(self, env):
        cluster, mgr = env
        m = wf_manifest(
            tasks=[{"name": "train", "template": "run-job"}],
            templates=[{"name": "run-job", "resource": {
                "action": "create",
                "manifest": {"apiVersion": "tpu.kubeflow.org/v1alpha1",
                             "kind": "TPUJob",
                             "metadata": {"name": "bench-job"},
                             "spec": {}},
                "successCondition": "condition:Succeeded=True",
                "failureCondition": "condition:Failed=True"}}])
        cluster.create(m)
        mgr.run_pending()
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                          "bench-job")
        assert job["metadata"]["ownerReferences"][0]["kind"] == "Workflow"
        assert get_wf(cluster)["status"]["phase"] == "Running"
        k8s.set_condition(job, k8s.Condition("Succeeded", "True", "Done", ""))
        cluster.update_status(job)
        mgr.run_pending()
        assert get_wf(cluster)["status"]["phase"] == "Succeeded"

    def test_deadline_fails_task(self, env):
        cluster, mgr = env
        now = [0.0]
        recon = WorkflowReconciler(clock=lambda: now[0])
        mgr2 = Manager(cluster)
        ctrl = mgr2.add(recon)
        m = wf_manifest(
            tasks=[{"name": "a", "template": "slow"}],
            templates=[{"name": "slow", "activeDeadlineSeconds": 10,
                        "container": {"image": "busybox"}}],
            name="dlwf")
        cluster.create(m)
        mgr2.run_pending()
        cluster.tick()  # pod Running
        now[0] = 11.0
        # deadline polling: requeue_after fires after the delay elapses
        import time as _t
        _t.sleep(0.06)
        ctrl.pump_events()
        mgr2.run_pending()
        wf = get_wf(cluster, "dlwf")
        assert wf["status"]["phase"] == "Failed"
        assert "deadline" in wf["status"]["nodes"]["a"]["message"]
        # the pod was killed
        assert cluster.get_or_none("v1", "Pod", "kubeflow", "dlwf-a") is None

    def test_bad_entrypoint_errors(self, env):
        cluster, mgr = env
        m = wf_manifest(entrypoint="nope")
        cluster.create(m)
        mgr.run_pending()
        assert get_wf(cluster)["status"]["phase"] == "Error"

    def test_unknown_dependency_errors(self, env):
        cluster, mgr = env
        cluster.create(wf_manifest(tasks=[
            {"name": "a", "template": "step", "dependencies": ["ghost"]}]))
        mgr.run_pending()
        assert get_wf(cluster)["status"]["phase"] == "Error"


class TestKubebench:
    def test_workflow_shape_and_env_contract(self):
        wf = build_kubebench_workflow(
            "bench1", "kubeflow",
            {"kind": "TPUJob", "metadata": {"name": "bench1-job"},
             "spec": {}})
        names = [t["name"] for t in wf["spec"]["templates"]]
        assert names == ["kubebench", "configurator", "run-job", "reporter"]
        dag = wf["spec"]["templates"][0]["dag"]["tasks"]
        assert dag[1]["dependencies"] == ["configure"]
        assert dag[2]["dependencies"] == ["run"]
        env = {e["name"]: e["value"]
               for e in wf["spec"]["templates"][1]["container"]["env"]}
        assert env["KUBEBENCH_EXP_ID"] == "bench1"
        assert env["KUBEBENCH_EXP_PATH"].endswith("/bench1")

    def test_kubebenchjob_end_to_end(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(WorkflowReconciler())
        mgr.add(KubebenchJobReconciler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create({
            "apiVersion": KUBEBENCH_API_VERSION, "kind": "KubebenchJob",
            "metadata": {"name": "bench1", "namespace": "kubeflow"},
            "spec": {"jobTemplate": {
                "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
                "spec": {"replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "bench", "image": "bench:v1"}]}}}}},
            }},
        })

        def on_running(pod):
            # benchmark workload pods finish immediately; workflow step pods
            # (configurator/reporter) too
            cluster.set_pod_phase(k8s.namespace_of(pod, "default"),
                                  k8s.name_of(pod), "Succeeded")

        cluster.on_pod_running = on_running
        kb = None
        for _ in range(30):
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            kb = cluster.get(KUBEBENCH_API_VERSION, "KubebenchJob",
                             "kubeflow", "bench1")
            if kb["status"].get("phase") in ("Succeeded", "Failed"):
                break
        assert kb["status"]["phase"] == "Succeeded", kb["status"]
        wf = cluster.get(WORKFLOW_API_VERSION, "Workflow", "kubeflow",
                         "bench1-wf")
        assert wf["status"]["nodes"]["run"]["phase"] == "Succeeded"
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                          "bench1-job")
        assert k8s.condition_true(job, "Succeeded")

    def test_csv_report(self, tmp_path):
        path = str(tmp_path / "out" / "report.csv")
        write_csv_report(path, [
            {"experiment": "e1", "examples_per_sec": 100.0},
            {"experiment": "e2", "examples_per_sec": 120.0, "extra": 1},
        ])
        with open(path) as f:
            lines = f.read().strip().splitlines()
        assert lines[0] == "experiment,examples_per_sec,extra"
        assert lines[1].startswith("e1,100.0")
        assert len(lines) == 3

    def test_csv_report_empty_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            write_csv_report(str(tmp_path / "r.csv"), [])

    def test_pvc_shared_volume_wiring(self):
        wf = build_kubebench_workflow(
            "b", "kubeflow",
            {"kind": "TPUJob", "metadata": {"name": "b-job"},
             "spec": {"replicaSpecs": {"TPU": {"template": {"spec": {
                 "containers": [{"name": "t", "image": "i"}]}}}}}},
            pvc="kubebench-pvc")
        assert wf["spec"]["volumes"][0]["persistentVolumeClaim"][
            "claimName"] == "kubebench-pvc"
        for tmpl in wf["spec"]["templates"]:
            if "container" in tmpl:
                assert tmpl["container"]["volumeMounts"][0][
                    "mountPath"] == "/kubebench"
        pod_spec = wf["spec"]["templates"][2]["resource"]["manifest"][
            "spec"]["replicaSpecs"]["TPU"]["template"]["spec"]
        assert pod_spec["volumes"][0]["name"] == "kubebench"
        assert pod_spec["containers"][0]["volumeMounts"][0][
            "mountPath"] == "/kubebench"

    def test_job_env_injection(self):
        wf = build_kubebench_workflow(
            "b", "kubeflow",
            {"kind": "TPUJob", "metadata": {"name": "b-job"},
             "spec": {"replicaSpecs": {"TPU": {"template": {"spec": {
                 "containers": [{"name": "t", "image": "i"}]}}}}}})
        manifest = wf["spec"]["templates"][2]["resource"]["manifest"]
        env = {e["name"]: e["value"] for e in
               manifest["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
                   "containers"][0]["env"]}
        assert env["KFTPU_METRICS_PATH"].endswith("/b/metrics.jsonl")
        assert env["KUBEBENCH_EXP_ID"] == "b"

    def test_report_from_metrics_aggregates_job_run(self, tmp_path):
        import json
        from kubeflow_tpu.workflows.kubebench import report_from_metrics
        path = tmp_path / "metrics.jsonl"
        rows = [{"step": i + 1, "step_time_s": 0.1,
                 "examples_per_sec": 320.0,
                 "metrics": {"loss": 2.0 - 0.1 * i}} for i in range(5)]
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        row = report_from_metrics(str(path), job_kind="TFJob",
                                  env={"KUBEBENCH_EXP_ID": "e7"})
        assert row["experiment"] == "e7"
        assert row["job_kind"] == "TFJob"
        assert row["steps"] == 5
        assert row["examples_per_sec"] == 320.0
        assert row["metric_loss"] == pytest.approx(1.6)

    def test_early_event_folds_into_first_record(self, tmp_path):
        """An event record earlier than every timed step folds into the
        FIRST record, not the last (ADVICE r3): it must not masquerade
        as a final-step model metric."""
        import json
        from kubeflow_tpu.workflows.kubebench import report_from_metrics
        path = tmp_path / "metrics.jsonl"
        rows = [{"step": 0, "event": "eval",
                 "metrics": {"startup_top1": 0.001}}]
        rows += [{"step": i + 1, "step_time_s": 0.1,
                  "examples_per_sec": 320.0,
                  "metrics": {"loss": 1.0}} for i in range(3)]
        rows += [{"step": 3, "event": "eval", "metrics": {"top1": 0.5}}]
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        row = report_from_metrics(str(path), env={})
        # the late event folded into the last record and is reported...
        assert row["metric_top1"] == pytest.approx(0.5)
        # ...the startup event folded into the FIRST record, so it is not
        assert "metric_startup_top1" not in row


class TestWorkflowEdgeCases:
    def test_task_missing_template_key_errors_cleanly(self, env):
        cluster, mgr = env
        cluster.create(wf_manifest(tasks=[{"name": "a"}]))
        mgr.run_pending()
        wf = get_wf(cluster)
        assert wf["status"]["phase"] == "Error"
        assert "name and template" in wf["status"]["message"]

    def test_succeeded_before_deadline_observed_late_still_succeeds(self):
        cluster = FakeCluster()
        cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
        now = [0.0]
        mgr = Manager(cluster)
        mgr.add(WorkflowReconciler(clock=lambda: now[0]))
        cluster.create(wf_manifest(
            tasks=[{"name": "a", "template": "slow"}],
            templates=[{"name": "slow", "activeDeadlineSeconds": 10,
                        "container": {"image": "busybox"}}]))
        mgr.run_pending()
        cluster.tick()
        cluster.set_pod_phase("kubeflow", "wf-a", "Succeeded")
        now[0] = 100.0  # reconcile lands long after the deadline instant
        mgr.run_pending()
        assert get_wf(cluster)["status"]["phase"] == "Succeeded"
