"""Wire-level tests: HttpKubeClient ↔ ClusterAPIServer ↔ FakeCluster.

The envtest analog at the HTTP layer (SURVEY.md §4 tier 2): the same wire
format a real apiserver speaks — resource paths, label selectors, status
subresource, typed Status errors, chunked watch streams with BOOKMARKs —
plus the kubeconfig loader and the deployable manager entrypoint.
Reference parity: ksonnet.go:92-197 (apply against a live apiserver),
notebook_controller.go:57-144 (watch wiring through client-go).
"""

import json
import urllib.request

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import (AlreadyExistsError, ConflictError,
                                  FakeCluster, NotFoundError)
from kubeflow_tpu.cluster import wire
from kubeflow_tpu.cluster.apiserver import ClusterAPIServer
from kubeflow_tpu.cluster.http_client import HttpKubeClient


@pytest.fixture
def env():
    backend = FakeCluster()
    server = ClusterAPIServer(backend, port=0)
    server.start()
    client = HttpKubeClient(server.url, sync_watches=True)
    yield backend, server, client
    client.close()
    server.stop()


def pod(name="p1", ns="default", labels=None):
    obj = k8s.make("v1", "Pod", name, namespace=ns, labels=labels or {})
    obj["spec"] = {"containers": [{"name": "c", "image": "busybox"}]}
    return obj


class TestWireFormat:
    def test_plurals(self):
        assert wire.plural_of("Pod") == "pods"
        assert wire.plural_of("Ingress") == "ingresses"
        assert wire.plural_of("NetworkPolicy") == "networkpolicies"
        assert wire.plural_of("Endpoints") == "endpoints"
        assert wire.plural_of("TPUJob") == "tpujobs"

    def test_paths(self):
        assert wire.collection_path("v1", "Pod", "ns1") == \
            "/api/v1/namespaces/ns1/pods"
        assert wire.object_path("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                "kf", "train") == \
            "/apis/tpu.kubeflow.org/v1alpha1/namespaces/kf/tpujobs/train"
        # cluster-scoped kinds never get a namespace segment
        assert wire.collection_path("v1", "Node", "ignored") == \
            "/api/v1/nodes"

    def test_parse_path_roundtrip(self):
        p = wire.parse_path(
            "/apis/kubeflow.org/v1alpha1/namespaces/alice/notebooks/nb/status")
        assert (p.api_version, p.plural, p.namespace, p.name,
                p.subresource) == \
            ("kubeflow.org/v1alpha1", "notebooks", "alice", "nb", "status")
        p = wire.parse_path("/api/v1/nodes/n1")
        assert (p.api_version, p.plural, p.namespace, p.name) == \
            ("v1", "nodes", None, "n1")
        assert wire.parse_path("/healthz") is None

    def test_selector_codec(self):
        sel = {"app": "x", "tier": "web"}
        assert wire.parse_selector(wire.encode_selector(sel)) == sel
        assert wire.parse_selector("a==b") == {"a": "b"}
        with pytest.raises(ValueError):
            wire.parse_selector("environment in (prod)")


class TestCrudOverHttp:
    def test_create_get_roundtrip(self, env):
        backend, _, client = env
        created = client.create(pod())
        assert created["metadata"]["uid"]
        got = client.get("v1", "Pod", "default", "p1")
        assert got["spec"]["containers"][0]["image"] == "busybox"
        # visible in the backend too (same store)
        assert backend.get("v1", "Pod", "default", "p1")

    def test_typed_errors(self, env):
        _, _, client = env
        client.create(pod())
        with pytest.raises(AlreadyExistsError):
            client.create(pod())
        with pytest.raises(NotFoundError):
            client.get("v1", "Pod", "default", "ghost")
        stale = client.get("v1", "Pod", "default", "p1")
        client.update(stale)  # bumps rv
        with pytest.raises(ConflictError):
            client.update(stale)  # stale rv now conflicts

    def test_list_with_selector(self, env):
        _, _, client = env
        client.create(pod("a", labels={"app": "x"}))
        client.create(pod("b", labels={"app": "y"}))
        names = [k8s.name_of(o) for o in
                 client.list("v1", "Pod", "default", selector={"app": "x"})]
        assert names == ["a"]

    def test_status_subresource(self, env):
        _, _, client = env
        client.create(pod())
        obj = client.get("v1", "Pod", "default", "p1")
        obj["status"] = {"phase": "Running"}
        obj["spec"] = {"mutated": True}  # must NOT land via /status
        updated = client.update_status(obj)
        assert updated["status"]["phase"] == "Running"
        assert "mutated" not in updated["spec"]

    def test_patch(self, env):
        _, _, client = env
        client.create(pod())
        out = client.patch("v1", "Pod", "default", "p1",
                           {"metadata": {"labels": {"patched": "yes"}}})
        assert out["metadata"]["labels"]["patched"] == "yes"

    def test_delete_and_cascade(self, env):
        _, _, client = env
        owner = client.create(pod("owner"))
        child = pod("child")
        k8s.set_owner(child, owner)
        client.create(child)
        client.delete("v1", "Pod", "default", "owner")
        with pytest.raises(NotFoundError):
            client.get("v1", "Pod", "default", "child")

    def test_unknown_plural_404(self, env):
        _, server, client = env
        with pytest.raises(NotFoundError):
            client.get("v1", "Frob", "default", "x")

    def test_healthz_and_version(self, env):
        _, server, _ = env
        for path, key in [("/healthz", "status"), ("/version", "gitVersion")]:
            with urllib.request.urlopen(server.url + path) as r:
                assert key in json.loads(r.read())


class TestWatchOverHttp:
    def test_events_stream(self, env):
        _, _, client = env
        w = client.watch("v1", "Pod")
        client.create(pod())  # sync_watches barriers on the stream
        ev = w.get(timeout=5)
        assert ev is not None and ev.type == "ADDED"
        assert k8s.name_of(ev.obj) == "p1"
        obj = client.get("v1", "Pod", "default", "p1")
        obj["metadata"]["labels"] = {"x": "y"}
        client.update(obj)
        ev = w.get(timeout=5)
        assert ev.type == "MODIFIED"
        client.delete("v1", "Pod", "default", "p1")
        ev = w.get(timeout=5)
        assert ev.type == "DELETED"
        w.close()

    def test_bookmarks_advance_filtered_streams(self, env):
        """A Service-only watch still catches up past Pod mutations —
        the BOOKMARK mechanism sync_watches depends on."""
        _, _, client = env
        w = client.watch("v1", "Service")
        for i in range(3):
            client.create(pod(f"p{i}"))  # barriers; would hang w/o bookmarks
        assert w.get(timeout=0.2) is None  # no real Service events
        assert w.last_rv >= 3
        w.close()

    def test_watch_requires_kind(self, env):
        _, _, client = env
        with pytest.raises(Exception, match="requires"):
            client.watch()

    def test_reconnect_relists_gap_events(self):
        """Objects mutated while the stream is down are re-delivered on
        reconnect (informer relist semantics) — a deployed manager must
        not permanently miss jobs created during a connection blip."""
        backend = FakeCluster()
        server = ClusterAPIServer(backend, port=0)
        port = server.start()
        client = HttpKubeClient(server.url)
        w = client.watch("v1", "Pod")
        client.create(pod("before"))
        ev = w.get(timeout=5)
        assert ev and k8s.name_of(ev.obj) == "before"
        server.stop()  # connection gap begins
        backend.create(pod("during-gap"))  # event lost on the wire
        server2 = ClusterAPIServer(backend, host="127.0.0.1", port=port)
        server2.start()
        try:
            seen = set()
            deadline = 10
            import time
            t0 = time.monotonic()
            while time.monotonic() - t0 < deadline and \
                    "during-gap" not in seen:
                ev = w.get(timeout=0.5)
                if ev:
                    seen.add(k8s.name_of(ev.obj))
            assert "during-gap" in seen, seen
        finally:
            w.close()
            client.close()
            server2.stop()


class TestKubeconfig:
    def test_from_kubeconfig(self, env, tmp_path):
        backend, server, _ = env
        from kubeflow_tpu.kfctl.coordinator import write_local_kubeconfig
        cfg = tmp_path / "kubeconfig"
        write_local_kubeconfig(str(cfg), server.url)
        client = HttpKubeClient.from_kubeconfig(str(cfg))
        client.create(pod("from-kubeconfig"))
        assert backend.get("v1", "Pod", "default", "from-kubeconfig")
        client.close()

    def test_from_kubeconfig_token_and_errors(self, tmp_path):
        import yaml
        cfg = {"apiVersion": "v1", "kind": "Config",
               "clusters": [{"name": "c",
                             "cluster": {"server": "https://example:6443",
                                         "insecure-skip-tls-verify": True}}],
               "users": [{"name": "u", "user": {"token": "abc123"}}],
               "contexts": [{"name": "ctx",
                             "context": {"cluster": "c", "user": "u"}}],
               "current-context": "ctx"}
        path = tmp_path / "kc"
        path.write_text(yaml.safe_dump(cfg))
        client = HttpKubeClient.from_kubeconfig(str(path))
        assert client._headers["Authorization"] == "Bearer abc123"
        assert client.base_url == "https://example:6443"
        with pytest.raises(Exception, match="context"):
            HttpKubeClient.from_kubeconfig(str(path), context="nope")


class TestManagerEntrypoint:
    def test_build_manager_over_http(self, env):
        """The deployable manager (python -m kubeflow_tpu.controllers)
        reconciles over the wire: Notebook → StatefulSet + Service."""
        backend, _, client = env
        from kubeflow_tpu.controllers.__main__ import build_manager
        mgr = build_manager(client, ["notebook", "statefulset"])
        client.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "Notebook",
            "metadata": {"name": "nb", "namespace": "alice"},
            "spec": {"template": {"spec": {"containers": [
                {"name": "notebook", "image": "jupyter:latest"}]}}}})
        mgr.run_pending()
        assert client.get("apps/v1", "StatefulSet", "alice", "nb")
        assert client.get("v1", "Service", "alice", "nb")
        for c in mgr.controllers:
            c.stop()

    def test_unknown_controller_rejected(self):
        from kubeflow_tpu.controllers.__main__ import build_manager
        with pytest.raises(SystemExit, match="unknown controller"):
            build_manager(FakeCluster(), ["frobnicator"])
