"""Kernel-layer tests: Pallas flash attention + ring attention.

Run on the 8-virtual-device CPU mesh (conftest) with kernels in interpret
mode — the "fake slice backend" tier from SURVEY.md §4.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.flash_attention import (flash_attention,
                                              reference_attention)
from kubeflow_tpu.ops.ring_attention import ring_attention
from kubeflow_tpu.api.trainingjob import ShardingSpec
from kubeflow_tpu.parallel.mesh import build_mesh


def _qkv(b=2, s=128, h=2, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_forward_uneven_blocks():
    # seq not a multiple of 128 → block picker finds a divisor
    q, k, v = _qkv(s=96)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grad_matches_reference(causal):
    q, k, v = _qkv(s=64, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name}")


def test_flash_lse():
    q, k, v = _qkv(s=64, d=16)
    out, lse = flash_attention(q, k, v, causal=False, with_lse=True)
    # lse = logsumexp of scaled scores
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    ref_lse = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(lse, ref_lse, atol=1e-5, rtol=1e-5)


@pytest.fixture(scope="module")
def seq_mesh():
    # 2-way data x 4-way sequence over the 8 virtual devices
    return build_mesh(ShardingSpec(data=2, sequence=4))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(seq_mesh, causal):
    q, k, v = _qkv(b=2, s=256, h=2, d=16)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=seq_mesh, causal=causal))(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_attention_grad(seq_mesh):
    q, k, v = _qkv(b=1, s=128, h=2, d=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=seq_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name}")


def test_ring_attention_degenerate_axis():
    # sequence axis of size 1 → falls back to flash, still correct
    mesh = build_mesh(ShardingSpec(data=8))
    q, k, v = _qkv(s=64, d=16)
    out = ring_attention(q, k, v, mesh=mesh)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_transformer_attention_impls_agree(seq_mesh):
    """Same params, same batch → same loss across einsum/flash/ring."""
    from kubeflow_tpu.models import transformer as T

    losses = {}
    for impl in ("einsum", "flash", "ring"):
        cfg = T.TransformerConfig(
            vocab_size=64, num_layers=1, embed_dim=32, num_heads=2,
            head_dim=16, mlp_dim=64, max_seq_len=64, dtype=jnp.float32,
            attention=impl, mesh=seq_mesh if impl == "ring" else None)
        model = T.TransformerLM(cfg)
        init = T.init_fn(model, seq_len=64)
        params, _ = init(jax.random.PRNGKey(0))
        batch = T.synthetic_batch(jax.random.PRNGKey(1), 4, 64, 64)
        loss_fn = T.make_loss_fn(model)
        with seq_mesh:
            loss, _ = jax.jit(
                lambda p, b: loss_fn(p, {}, b, jax.random.PRNGKey(0)))(
                    params, batch)
        losses[impl] = float(loss)
    assert abs(losses["flash"] - losses["einsum"]) < 1e-4, losses
    assert abs(losses["ring"] - losses["einsum"]) < 1e-4, losses


class TestFusedBlock:
    """ops/fused_block.py: the fused bottleneck kernel equals the jnp
    reference and the flax eval path (interpret mode on CPU)."""

    def _weights(self, rng, cin, cmid, cout, proj):
        import numpy as np
        from kubeflow_tpu.ops.fused_block import FusedBlockWeights
        def arr(*s):
            return jnp.asarray(rng.normal(0, 0.1, s), jnp.float32)
        kw = {}
        if proj:
            kw = dict(wp=arr(cin, cout), sp=arr(cout) + 1, bp=arr(cout))
        return FusedBlockWeights(
            w1=arr(cin, cmid), s1=arr(cmid) + 1, b1=arr(cmid),
            w2=arr(3, 3, cmid, cmid), s2=arr(cmid) + 1, b2=arr(cmid),
            w3=arr(cmid, cout), s3=arr(cout) + 1, b3=arr(cout), **kw)

    def test_kernel_matches_reference(self):
        import numpy as np
        from kubeflow_tpu.ops.fused_block import (fused_bottleneck_eval,
                                                  reference_bottleneck_eval)
        rng = np.random.default_rng(0)
        for cin, cout, proj, bt in ((16, 32, True, 2), (32, 32, False, 1),
                                    (32, 32, False, 4)):
            w = self._weights(rng, cin, 8, cout, proj)
            x = jnp.asarray(rng.normal(0, 1, (4, 8, 8, cin)), jnp.float32)
            got = fused_bottleneck_eval(x, w, block_bt=bt)
            want = reference_bottleneck_eval(x, w)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_missing_projection_rejected(self):
        import numpy as np
        import pytest
        from kubeflow_tpu.ops.fused_block import fused_bottleneck_eval
        rng = np.random.default_rng(0)
        w = self._weights(rng, 16, 8, 32, proj=False)
        with pytest.raises(ValueError, match="projection"):
            fused_bottleneck_eval(
                jnp.zeros((2, 8, 8, 16), jnp.float32), w)

    def test_fused_eval_apply_matches_flax(self):
        import numpy as np
        from kubeflow_tpu.models import resnet as R
        model = R.resnet50(num_classes=10)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=False)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        want = model.apply(variables, x, train=False)
        got = R.fused_eval_apply(variables, x)
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
        assert (got.argmax(-1) == want.argmax(-1)).all()


class TestResNetFamily:
    """The tf_cnn_benchmarks --model family surface: resnet{18,34,50,101,152}
    as workloads and servable types, BasicBlock path included."""

    def test_basic_block_depth_forward(self):
        from kubeflow_tpu.models import resnet as R
        model = R.resnet18(num_classes=7)
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                       train=False)
        out = model.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
        assert out.shape == (2, 7)

    def test_unsupported_depth_rejected(self):
        from kubeflow_tpu.models import resnet as R
        with pytest.raises(ValueError, match="depth"):
            R.make_resnet(77)

    def test_registries_cover_family(self):
        from kubeflow_tpu.models import RESNET_DEPTHS
        from kubeflow_tpu.runtime.worker import WORKLOADS, _IMAGE_WORKLOADS
        from kubeflow_tpu.serving.servable import _MODEL_BUILDERS
        family = {f"resnet{d}" for d in RESNET_DEPTHS}
        assert family <= set(WORKLOADS)
        assert family <= _IMAGE_WORKLOADS
        assert family <= set(_MODEL_BUILDERS)
