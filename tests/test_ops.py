"""Kernel-layer tests: Pallas flash attention + ring attention.

Run on the 8-virtual-device CPU mesh (conftest) with kernels in interpret
mode — the "fake slice backend" tier from SURVEY.md §4.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.flash_attention import (flash_attention,
                                              reference_attention)
from kubeflow_tpu.ops.ring_attention import ring_attention
from kubeflow_tpu.api.trainingjob import ShardingSpec
from kubeflow_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.compute  # JAX trace/compile tests: excluded from smoke tier


def _qkv(b=2, s=128, h=2, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_forward_uneven_blocks():
    # seq not a multiple of 128 → block picker finds a divisor
    q, k, v = _qkv(s=96)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grad_matches_reference(causal):
    q, k, v = _qkv(s=64, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name}")


def test_flash_traces_at_bench_geometry():
    """Abstract trace (no execution) of the flash kernel fwd+bwd at the
    EXACT TPU LM-bench configs (bench.py: head_dim 128 = lane width, seq
    1024 and the 8192 long-context mode) — catches block-layout/shape
    asserts in the pallas_call structure without paying an interpret-mode
    run at full size."""
    for seq, batch in ((1024, 32), (8192, 4)):   # bench.py's real pairs
        q = jax.ShapeDtypeStruct((batch, seq, 8, 128), jnp.bfloat16)

        def loss(q_, k_, v_):
            return flash_attention(q_, k_, v_,
                                   causal=True).astype(jnp.float32).sum()

        out = jax.eval_shape(lambda a, b, c: flash_attention(
            a, b, c, causal=True), q, q, q)
        assert out.shape == (batch, seq, 8, 128)
        assert out.dtype == jnp.bfloat16
        grads = jax.eval_shape(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
        assert all(g.shape == (batch, seq, 8, 128) for g in grads)


def test_flash_lse():
    q, k, v = _qkv(s=64, d=16)
    out, lse = flash_attention(q, k, v, causal=False, with_lse=True)
    # lse = logsumexp of scaled scores
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    ref_lse = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(lse, ref_lse, atol=1e-5, rtol=1e-5)


@pytest.fixture(scope="module")
def seq_mesh():
    # 2-way data x 4-way sequence over the 8 virtual devices
    return build_mesh(ShardingSpec(data=2, sequence=4))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(seq_mesh, causal):
    q, k, v = _qkv(b=2, s=256, h=2, d=16)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=seq_mesh, causal=causal))(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_attention_grad(seq_mesh):
    q, k, v = _qkv(b=1, s=128, h=2, d=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=seq_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name}")


def test_ring_attention_degenerate_axis():
    # sequence axis of size 1 → falls back to flash, still correct
    mesh = build_mesh(ShardingSpec(data=8))
    q, k, v = _qkv(s=64, d=16)
    out = ring_attention(q, k, v, mesh=mesh)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_transformer_attention_impls_agree(seq_mesh):
    """Same params, same batch → same loss across einsum/flash/ring."""
    from kubeflow_tpu.models import transformer as T

    losses = {}
    for impl in ("einsum", "flash", "ring"):
        cfg = T.TransformerConfig(
            vocab_size=64, num_layers=1, embed_dim=32, num_heads=2,
            head_dim=16, mlp_dim=64, max_seq_len=64, dtype=jnp.float32,
            attention=impl, mesh=seq_mesh if impl == "ring" else None)
        model = T.TransformerLM(cfg)
        init = T.init_fn(model, seq_len=64)
        params, _ = init(jax.random.PRNGKey(0))
        batch = T.synthetic_batch(jax.random.PRNGKey(1), 4, 64, 64)
        loss_fn = T.make_loss_fn(model)
        with seq_mesh:
            loss, _ = jax.jit(
                lambda p, b: loss_fn(p, {}, b, jax.random.PRNGKey(0)))(
                    params, batch)
        losses[impl] = float(loss)
    assert abs(losses["flash"] - losses["einsum"]) < 1e-4, losses
    assert abs(losses["ring"] - losses["einsum"]) < 1e-4, losses


@pytest.mark.slow
class TestFusedBlock:
    """ops/fused_block.py: the fused bottleneck kernel equals the jnp
    reference and the flax eval path (interpret mode on CPU)."""

    def _weights(self, rng, cin, cmid, cout, proj):
        import numpy as np
        from kubeflow_tpu.ops.fused_block import FusedBlockWeights
        def arr(*s):
            return jnp.asarray(rng.normal(0, 0.1, s), jnp.float32)
        kw = {}
        if proj:
            kw = dict(wp=arr(cin, cout), sp=arr(cout) + 1, bp=arr(cout))
        return FusedBlockWeights(
            w1=arr(cin, cmid), s1=arr(cmid) + 1, b1=arr(cmid),
            w2=arr(3, 3, cmid, cmid), s2=arr(cmid) + 1, b2=arr(cmid),
            w3=arr(cmid, cout), s3=arr(cout) + 1, b3=arr(cout), **kw)

    def test_kernel_matches_reference(self):
        import numpy as np
        from kubeflow_tpu.ops.fused_block import (fused_bottleneck_eval,
                                                  reference_bottleneck_eval)
        rng = np.random.default_rng(0)
        for cin, cout, proj, bt in ((16, 32, True, 2), (32, 32, False, 1),
                                    (32, 32, False, 4)):
            w = self._weights(rng, cin, 8, cout, proj)
            x = jnp.asarray(rng.normal(0, 1, (4, 8, 8, cin)), jnp.float32)
            got = fused_bottleneck_eval(x, w, block_bt=bt)
            want = reference_bottleneck_eval(x, w)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_missing_projection_rejected(self):
        import numpy as np
        import pytest
        from kubeflow_tpu.ops.fused_block import fused_bottleneck_eval
        rng = np.random.default_rng(0)
        w = self._weights(rng, 16, 8, 32, proj=False)
        with pytest.raises(ValueError, match="projection"):
            fused_bottleneck_eval(
                jnp.zeros((2, 8, 8, 16), jnp.float32), w)

    def test_fused_eval_apply_matches_flax(self):
        import numpy as np
        from kubeflow_tpu.models import resnet as R
        model = R.resnet50(num_classes=10)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=False)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        want = model.apply(variables, x, train=False)
        got = R.fused_eval_apply(variables, x)
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
        assert (got.argmax(-1) == want.argmax(-1)).all()


class TestResNetFamily:
    """The tf_cnn_benchmarks --model family surface: resnet{18,34,50,101,152}
    as workloads and servable types, BasicBlock path included."""

    def test_basic_block_depth_forward(self):
        from kubeflow_tpu.models import resnet as R
        model = R.resnet18(num_classes=7)
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                       train=False)
        out = model.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
        assert out.shape == (2, 7)

    def test_unsupported_depth_rejected(self):
        from kubeflow_tpu.models import resnet as R
        with pytest.raises(ValueError, match="depth"):
            R.make_resnet(77)

    def test_registries_cover_family(self):
        from kubeflow_tpu.models import RESNET_DEPTHS
        from kubeflow_tpu.runtime.worker import WORKLOADS, _IMAGE_WORKLOADS
        from kubeflow_tpu.serving.servable import _MODEL_BUILDERS
        family = {f"resnet{d}" for d in RESNET_DEPTHS}
        assert family <= set(WORKLOADS)
        assert family <= _IMAGE_WORKLOADS
        assert family <= set(_MODEL_BUILDERS)


@pytest.mark.slow
class TestFusedBlockTrain:
    """ops/fused_block_train.py: the ghost-BN training kernel pair equals
    the differentiable jnp reference — values, stats, AND jax.grad —
    in interpret mode on CPU."""

    def _params(self, rng, cin, cmid, cout, proj):
        import numpy as np

        def arr(*s):
            return jnp.asarray(rng.normal(0, 0.1, s), jnp.float32)

        p = {
            "Conv_0": {"kernel": arr(1, 1, cin, cmid)},
            "BatchNorm_0": {"scale": arr(cmid) + 1, "bias": arr(cmid)},
            "Conv_1": {"kernel": arr(3, 3, cmid, cmid)},
            "BatchNorm_1": {"scale": arr(cmid) + 1, "bias": arr(cmid)},
            "Conv_2": {"kernel": arr(1, 1, cmid, cout)},
            "BatchNorm_2": {"scale": arr(cout) + 1, "bias": arr(cout)},
        }
        if proj:
            p["conv_proj"] = {"kernel": arr(1, 1, cin, cout)}
            p["norm_proj"] = {"scale": arr(cout) + 1, "bias": arr(cout)}
        return p

    @pytest.mark.parametrize("proj", [False, True])
    def test_forward_and_stats_match_reference(self, proj):
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import (
            block_weights, fused_bottleneck_train,
            reference_bottleneck_train)
        rng = np.random.default_rng(0)
        cin = 16 if proj else 32
        p = self._params(rng, cin, 8, 32, proj)
        x = jnp.asarray(rng.normal(0, 1, (8, 8, 8, cin)), jnp.float32)
        out, stats = fused_bottleneck_train(x, p, tile_bt=2)
        ref_out, ref_stats = reference_bottleneck_train(
            x, block_weights(p), tile_bt=2)
        np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(stats["BatchNorm_0"]["mean"],
                                   ref_stats[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(stats["BatchNorm_2"]["var"],
                                   ref_stats[5], rtol=1e-5, atol=1e-6)
        if proj:
            np.testing.assert_allclose(stats["norm_proj"]["mean"],
                                       ref_stats[6], rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("proj", [False, True])
    def test_backward_matches_jax_grad_of_reference(self, proj):
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import (
            _fused, block_weights, reference_bottleneck_train)
        rng = np.random.default_rng(1)
        cin = 16 if proj else 32
        p = self._params(rng, cin, 8, 32, proj)
        w = block_weights(p)
        x = jnp.asarray(rng.normal(0, 1, (4, 8, 8, cin)), jnp.float32)

        def loss_k(x, *w):
            o, _ = _fused(2, 1e-5, x, *w)
            return jnp.sum(jnp.sin(o))

        def loss_r(x, *w):
            o, _ = reference_bottleneck_train(x, w, tile_bt=2)
            return jnp.sum(jnp.sin(o))

        argnums = tuple(range(len(w) + 1))
        gk = jax.grad(loss_k, argnums=argnums)(x, *w)
        gr = jax.grad(loss_r, argnums=argnums)(x, *w)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_ghost_stats_are_per_tile_not_per_batch(self):
        """tile_bt=n collapses ghost BN to exact batch BN; a smaller tile
        must produce different normalization — the documented semantics
        departure the variant is opt-in for."""
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import (
            fused_bottleneck_train)
        rng = np.random.default_rng(2)
        p = self._params(rng, 32, 8, 32, proj=False)
        x = jnp.asarray(rng.normal(0, 1, (8, 8, 8, 32)), jnp.float32)
        out_full, _ = fused_bottleneck_train(x, p, tile_bt=8)
        out_ghost, _ = fused_bottleneck_train(x, p, tile_bt=2)
        assert float(jnp.max(jnp.abs(out_full - out_ghost))) > 1e-6

    def test_tile_must_divide_batch(self):
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import (
            fused_bottleneck_train)
        rng = np.random.default_rng(3)
        p = self._params(rng, 32, 8, 32, proj=False)
        with pytest.raises(ValueError, match="divide"):
            fused_bottleneck_train(
                jnp.zeros((6, 8, 8, 32), jnp.float32), p, tile_bt=4)

    def test_fused_train_apply_updates_running_stats(self):
        import numpy as np
        from kubeflow_tpu.models import resnet as R
        model = R.resnet50(num_classes=10)
        params, variables = R.init_fn(model, image_size=32, batch=2)(
            jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        logits, new_stats = R.fused_train_apply(
            {"params": params, **variables}, x, tile_bt=2)
        assert logits.shape == (4, 10)
        assert np.isfinite(np.asarray(logits)).all()
        # EMA moved every BN's running mean (momentum 0.9 on real data)
        old = variables["batch_stats"]
        moved = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), old, new_stats)
        assert all(v > 0 for v in jax.tree.leaves(moved))
        # structure matches flax's exactly (checkpoint compatibility)
        assert jax.tree.structure(old) == jax.tree.structure(new_stats)

    @pytest.mark.parametrize("proj", [False, True])
    def test_spatial_forward_and_stats_match_reference(self, proj):
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import block_weights
        from kubeflow_tpu.ops.fused_block_train_spatial import (
            fused_bottleneck_train_spatial,
            reference_bottleneck_train_spatial)
        rng = np.random.default_rng(4)
        cin = 16 if proj else 32
        p = self._params(rng, cin, 8, 32, proj)
        x = jnp.asarray(rng.normal(0, 1, (4, 8, 8, cin)), jnp.float32)
        out, stats = fused_bottleneck_train_spatial(x, p, tile_bt=2,
                                                    tile_h=4)
        ref_out, ref_stats = reference_bottleneck_train_spatial(
            x, block_weights(p), tile_bt=2, tile_h=4)
        np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(stats["BatchNorm_0"]["mean"],
                                   ref_stats[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(stats["BatchNorm_1"]["var"],
                                   ref_stats[3], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(stats["BatchNorm_2"]["var"],
                                   ref_stats[5], rtol=1e-5, atol=1e-6)
        if proj:
            np.testing.assert_allclose(stats["norm_proj"]["mean"],
                                       ref_stats[6], rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("proj", [False, True])
    def test_spatial_backward_matches_jax_grad_of_reference(self, proj):
        """The halo gradient path (seam rows feed TWO strips' conv2 and
        the BN1 stat-correction of their owning strip only) must equal
        jax.grad of the spec — the test that catches seam/mask bugs."""
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import block_weights
        from kubeflow_tpu.ops.fused_block_train_spatial import (
            _fused, reference_bottleneck_train_spatial)
        rng = np.random.default_rng(5)
        cin = 16 if proj else 32
        p = self._params(rng, cin, 8, 32, proj)
        w = block_weights(p)
        x = jnp.asarray(rng.normal(0, 1, (2, 8, 8, cin)), jnp.float32)

        def loss_k(x, *w):
            o, _ = _fused(1, 4, 1e-5, x, *w)
            return jnp.sum(jnp.sin(o))

        def loss_r(x, *w):
            o, _ = reference_bottleneck_train_spatial(x, w, tile_bt=1,
                                                      tile_h=4)
            return jnp.sum(jnp.sin(o))

        argnums = tuple(range(len(w) + 1))
        gk = jax.grad(loss_k, argnums=argnums)(x, *w)
        gr = jax.grad(loss_r, argnums=argnums)(x, *w)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_spatial_full_height_matches_batch_tiled(self):
        """tile_h=h (one strip, zero halo rows in play) must reproduce
        the batch-tiled kernel exactly — same ghost batches."""
        import numpy as np
        from kubeflow_tpu.ops.fused_block_train import (
            fused_bottleneck_train)
        from kubeflow_tpu.ops.fused_block_train_spatial import (
            fused_bottleneck_train_spatial)
        rng = np.random.default_rng(6)
        p = self._params(rng, 32, 8, 32, proj=False)
        x = jnp.asarray(rng.normal(0, 1, (4, 8, 8, 32)), jnp.float32)
        out_s, stats_s = fused_bottleneck_train_spatial(
            x, p, tile_bt=2, tile_h=8)
        out_b, stats_b = fused_bottleneck_train(x, p, tile_bt=2)
        np.testing.assert_allclose(out_s, out_b, rtol=1e-5, atol=1e-5)
        for a, b in zip(jax.tree.leaves(stats_s),
                        jax.tree.leaves(stats_b)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_spatial_default_tile_h_fits_flagship_stage1(self):
        # the whole point: a strip height exists for the 56x56 stage-1
        # geometry the batch-tiled kernel cannot fit
        from kubeflow_tpu.ops.fused_block_train import fits_vmem_budget
        from kubeflow_tpu.ops.fused_block_train_spatial import (
            default_tile_h, fits_vmem_budget_spatial)
        assert not fits_vmem_budget(56, 56, 256, 64, 256)
        th = default_tile_h(56, 56, 256, 64, 256)
        assert th is not None and 56 % th == 0
        assert fits_vmem_budget_spatial(th, 56, 256, 64, 256)

    def test_routing_geometry_matches_real_apply_shapes(self):
        """The routing walk's per-block input geometry must equal the
        real model's tensor shapes — at 112px the stage-4 height is
        SAME-padding ceil(7/2)=4, where floor division would drift to 3
        and report a route the apply never took."""
        import jax
        from kubeflow_tpu.models import resnet as R
        model = R.resnet50(num_classes=10)

        def f(x):
            variables = model.init(jax.random.PRNGKey(0), x, train=False)
            _, inter = model.apply(variables, x, train=False,
                                   capture_intermediates=True,
                                   mutable=["intermediates"])
            return inter
        shapes = jax.eval_shape(
            f, jax.ShapeDtypeStruct((1, 112, 112, 3), jnp.float32))
        blocks = shapes["intermediates"]

        # replicate the walk's geometry and compare against the real
        # block OUTPUT shapes (input of block j+1 = output of block j)
        def ceil_half(n):
            return -(-n // 2)
        h = ceil_half(ceil_half(112))
        from kubeflow_tpu.models.resnet import STAGE_SIZES
        for i, n_blocks in enumerate(STAGE_SIZES[50]):
            for j in range(n_blocks):
                if i > 0 and j == 0:
                    h = ceil_half(h)
                name = f"stage{i + 1}_block{j + 1}"
                real = blocks[name]["__call__"][0].shape
                assert real[1] == h, (name, real, h)
                assert real[3] == 64 * 2 ** i * 4, (name, real)
        assert h == 4  # the ceil-division case floor would get wrong

    def test_fused_block_routing_covers_flagship(self):
        # the routing report shares the decision fn with the apply: at
        # 224px every stride-1 block is fused (spatial early, batch
        # late); tiny images all batch-tile
        from kubeflow_tpu.models.resnet import fused_block_routing
        r = fused_block_routing(50, 224)
        assert len(r) == 16
        assert r["stage1_block1"].startswith("fused-spatial")
        assert r["stage2_block2"].startswith("fused-spatial")
        assert r["stage3_block2"] == "fused-batch"
        assert r["stage4_block3"] == "fused-batch"
        assert r["stage2_block1"] == "xla-strided"
        assert not any(v == "xla" for v in r.values())
        tiny = fused_block_routing(50, 64)
        assert set(tiny.values()) == {"fused-batch", "xla-strided"}

    def test_measured_routing_table_overrides_model(self, tmp_path,
                                                    monkeypatch):
        """A measured table (KFTPU_FUSED_ROUTING_TABLE) pins routing for
        the geometries it names — the consumption path for the on-TPU
        fused-blocks microbench output — and unnamed geometries keep the
        modeled route."""
        import json as _json
        from kubeflow_tpu.models import resnet as R
        base = R.fused_block_routing(50, 224)
        assert base["stage4_block2"] == "fused-batch"
        table = {"routes": {
            R.geometry_key(7, 7, 2048, 512, 2048): "xla",
            R.geometry_key(56, 56, 256, 64, 256): "spatial:28",
        }}
        path = tmp_path / "routing.json"
        path.write_text(_json.dumps(table))
        monkeypatch.setenv("KFTPU_FUSED_ROUTING_TABLE", str(path))
        pinned = R.fused_block_routing(50, 224)
        assert pinned["stage4_block2"] == "xla"
        assert pinned["stage1_block2"] == "fused-spatial(th=28)"
        # geometries the table does not name keep the modeled route
        assert pinned["stage3_block2"] == base["stage3_block2"]
        # the spatial kill-switch outranks a table-pinned spatial route
        # (a wedged Mosaic compile must be stoppable mid-measurement)
        monkeypatch.setenv("KFTPU_FUSED_DISABLE_SPATIAL", "1")
        assert R._fused_route(56, 56, 256, 64, 256) == ("xla", None)

    def test_stride1_geometries_match_routing_walk(self):
        """The microbench work-list covers exactly the stride-1 blocks
        of the flagship config, with the right multiplicities."""
        from kubeflow_tpu.models import resnet as R
        geoms = R.stride1_geometries(50, 224)
        assert sum(g["count"] for g in geoms) == 13  # 16 blocks - 3 strided
        by_key = {g["key"]: g for g in geoms}
        g1 = by_key[R.geometry_key(56, 56, 64, 64, 256)]
        assert g1["proj"] and g1["count"] == 1
        g4 = by_key[R.geometry_key(14, 14, 1024, 256, 1024)]
        assert not g4["proj"] and g4["count"] == 5
        # every geometry builds valid single-block params
        p = R.random_block_params(jax.random.PRNGKey(0), 64, 64, 256, True)
        assert p["conv_proj"]["kernel"].shape == (1, 1, 64, 256)

    def test_fused_loss_close_to_flax_on_shared_params(self):
        """Ghost BN differs from batch BN but must stay in the same
        numeric neighborhood at init — a gross mismatch means a bug, not
        a semantics difference."""
        import numpy as np
        from kubeflow_tpu.models import resnet as R
        model = R.resnet50(num_classes=10)
        params, variables = R.init_fn(model, image_size=32, batch=2)(
            jax.random.PRNGKey(0))
        batch = {
            "images": jax.random.normal(jax.random.PRNGKey(1),
                                        (8, 32, 32, 3)),
            "labels": jnp.arange(8) % 10,
        }
        fused = R.make_fused_loss_fn(model, tile_bt=2)
        std = R.make_loss_fn(model)
        lf, _ = fused(params, variables, batch, jax.random.PRNGKey(2))
        ls, _ = std(params, variables, batch, jax.random.PRNGKey(2))
        assert abs(float(lf) - float(ls)) < 0.5

    def _run_sharded_fused_step(self):
        """One jitted value_and_grad of the fused loss under shard_map
        on the full mesh; asserts loss/grad finiteness and the stats
        tree shape. Shared by the plain and forced-spatial tests."""
        import numpy as np
        from kubeflow_tpu.models import resnet as R
        from kubeflow_tpu.parallel.mesh import build_mesh
        mesh = build_mesh()
        model = R.resnet50(num_classes=10)
        params, variables = R.init_fn(model, image_size=32, batch=2)(
            jax.random.PRNGKey(0))
        loss_fn = R.make_fused_loss_fn(model, tile_bt=1, mesh=mesh)
        batch = {
            "images": jax.random.normal(jax.random.PRNGKey(1),
                                        (16, 32, 32, 3)),
            "labels": jnp.arange(16) % 10,
        }
        with mesh:
            (loss, aux), grads = jax.jit(
                jax.value_and_grad(loss_fn, has_aux=True))(
                params, variables, batch, jax.random.PRNGKey(2))
        assert np.isfinite(float(loss))
        gsq = sum(float(jnp.sum(jnp.square(g)))
                  for g in jax.tree.leaves(grads))
        assert np.isfinite(gsq) and gsq > 0
        ns = aux["variables"]["batch_stats"]
        assert jax.tree.structure(ns) == \
            jax.tree.structure(variables["batch_stats"])

    def test_fused_loss_shard_maps_over_data_axes(self):
        """On a dp>1 mesh the apply runs inside shard_map (per-shard
        ghost BN); grads flow and stats come back replicated."""
        self._run_sharded_fused_step()

    def test_spatial_kernel_inside_shard_map(self, monkeypatch):
        """The composition the 224px --fused-blocks path runs on TPU:
        the spatially-tiled kernel (2-D grid, windowed halo reads, thin
        seam-row gradient scatter) under shard_map over the data axes.
        Forced here by shrinking the VMEM budget so the small test
        geometry routes spatial exactly like the flagship stage-1."""
        from kubeflow_tpu.models import resnet as R
        from kubeflow_tpu.ops import fused_block_train as fbt
        from kubeflow_tpu.ops import fused_block_train_spatial as fbts
        # at 32px stage 1 runs 8x8 blocks (cin 64/256, cmid 64, cout
        # 256): set the budget so the full image busts it but a th=4
        # halo strip fits — the flagship stage-1 situation in miniature
        budget = fbts._strip_bytes(4, 8, 256, 64, 256)
        assert budget < fbt._per_image_bytes(8, 8, 64, 64, 256)
        monkeypatch.setattr(fbt, "VMEM_BUDGET_BYTES", budget)
        monkeypatch.setattr(fbts, "VMEM_BUDGET_BYTES", budget)
        kind, th = R._fused_route(8, 8, 256, 64, 256)
        assert (kind, th) == ("spatial", 4)
        self._run_sharded_fused_step()

    def test_measured_table_drives_kernel_selection_in_apply(
            self, tmp_path, monkeypatch):
        """The table→kernel path end to end in a real traced apply: pin
        a geometry the VMEM model would batch-tile to the SPATIAL kernel
        via a measured table and run the full sharded fused step (the
        TPU fused-measured-routing re-measurement in miniature)."""
        import json as _json
        from kubeflow_tpu.models import resnet as R
        # the 32px test geometry batch-tiles under the default budget
        assert R._fused_route(8, 8, 256, 64, 256) == ("batch", None)
        table = {"routes": {R.geometry_key(8, 8, 256, 64, 256): "spatial:4"}}
        path = tmp_path / "routing.json"
        path.write_text(_json.dumps(table))
        monkeypatch.setenv("KFTPU_FUSED_ROUTING_TABLE", str(path))
        assert R._fused_route(8, 8, 256, 64, 256) == ("spatial", 4)
        self._run_sharded_fused_step()

    def test_basicblock_depths_rejected(self):
        from kubeflow_tpu.models import resnet as R
        with pytest.raises(ValueError, match="bottleneck"):
            R.make_fused_loss_fn(R.resnet18(num_classes=10))

    def test_worker_trains_with_fused_blocks(self):
        import numpy as np
        from kubeflow_tpu.runtime.worker import train
        r = train(workload="resnet50", steps=2, global_batch=16,
                  sync_every=1, seed=0,
                  workload_kwargs={"image_size": 32, "num_classes": 10,
                                   "fused": True, "fused_tile_bt": 1})
        assert r.steps == 2
        assert np.isfinite(r.final_metrics["loss"])
