"""Node-health subsystem tests (ISSUE 6).

Tiered like the scheduler/chaos suites:
- pure-core: decay/fold math, quarantine record round-trips, probation
  release rules, host→cell mapping — no cluster at all;
- control-plane: the operator's suspect attribution + evidence
  recording and the scheduler's quarantine/evacuation pass over
  FakeCluster (crash → suspect → migrate within one rebind; quarantine
  threshold → carve; decay → release; health disabled → placement-blind
  baseline), plus the per-worker stall watchdog, the heartbeat
  clock-skew clamp, and step-skew scoring;
- sim: degraded-node A/B (quarantine strictly reduces recompute);
- soak (slow): the real-training flaky-host migration drill
  (scheduler/soak.py HealthSoak), the bench.py --mode health bar.
"""

import json
import time

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.trainingjob import (BINDING_ANNOTATION,
                                          HEARTBEAT_ANNOTATION,
                                          HEALTH_ANNOTATION,
                                          QUARANTINE_ANNOTATION,
                                          SUSPECT_ANNOTATION)
from kubeflow_tpu.api.topology import parse_topology
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.scheduler import health as H
from kubeflow_tpu.scheduler.core import SliceScheduler
from kubeflow_tpu.scheduler.queue import SchedulerConfig

pytestmark = pytest.mark.health


def node_with(annotations=None, ready=True, name="n0"):
    node = k8s.make("v1", "Node", name, labels={"kubeflow.org/pool": "p"})
    node["metadata"]["annotations"] = dict(annotations or {})
    node["status"] = {"conditions": [
        {"type": "Ready", "status": "True" if ready else "False"}]}
    return node


class TestScoring:
    def test_fold_decays_then_adds(self):
        rec = {"score": 2.0, "time": 1000.0, "events": 3, "last": "x"}
        # one half-life later: 2.0 decays to 1.0, crash adds 1.0
        out = H.fold_event(rec, H.EVENT_POD_CRASH, 1000.0 + 600.0,
                           half_life_s=600.0)
        assert out["score"] == pytest.approx(2.0, abs=1e-6)
        assert out["events"] == 4 and out["last"] == "pod-crash"

    def test_event_weights_applied(self):
        out = H.fold_event({"score": 0.0, "time": 0.0}, H.EVENT_STEP_SKEW,
                           100.0)
        assert out["score"] == pytest.approx(0.25)

    def test_decayed_score_reads_annotation(self):
        now = time.time()
        node = node_with({HEALTH_ANNOTATION: json.dumps(
            {"score": 4.0, "time": now - 600.0, "events": 4})})
        assert H.decayed_score(node, now, 600.0) == pytest.approx(
            2.0, rel=1e-3)

    def test_future_stamped_record_is_clamped(self):
        # writer clock ahead of ours: decays from NOW, never amplifies
        now = time.time()
        node = node_with({HEALTH_ANNOTATION: json.dumps(
            {"score": 1.0, "time": now + 3600.0})})
        assert H.decayed_score(node, now) == pytest.approx(1.0)

    def test_malformed_annotation_reads_healthy(self):
        assert H.decayed_score(node_with({HEALTH_ANNOTATION: "]["})) == 0.0
        assert H.health_of(node_with({HEALTH_ANNOTATION: "3"}))[
            "score"] == 0.0

    def test_record_host_event_folds_through_apiserver(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        H.record_host_event(cluster, "tpu-pool-v5e-8-0",
                            H.EVENT_POD_CRASH, job_key="ns/j")
        H.record_host_event(cluster, "tpu-pool-v5e-8-0", H.EVENT_STALL)
        rec = H.health_of(cluster.get("v1", "Node", "",
                                      "tpu-pool-v5e-8-0"))
        assert rec["events"] == 2 and rec["score"] > 1.9
        assert rec["last"] == "stall"

    def test_record_host_event_never_raises(self):
        # evidence must not block recovery: a missing node logs and
        # returns None
        assert H.record_host_event(FakeCluster(), "gone",
                                   H.EVENT_POD_CRASH) is None


class TestQuarantineContract:
    def test_record_round_trip(self):
        raw = H.quarantine_record("score 3.1 >= 3", 3.1, 100.0, 900.0)
        q = H.quarantine_of(node_with({QUARANTINE_ANNOTATION: raw}))
        assert q["reason"].startswith("score")
        assert q["until"] == pytest.approx(1000.0)
        assert H.is_quarantined(node_with({QUARANTINE_ANNOTATION: raw}))
        assert not H.is_quarantined(node_with())

    def test_unparseable_quarantine_fails_safe(self):
        # garbage reads as manual-quarantined: keep the host OUT and
        # let a human fix the JSON
        q = H.quarantine_of(node_with({QUARANTINE_ANNOTATION: "}{"}))
        assert q is not None and q["reason"] == H.MANUAL_REASON

    def test_release_is_probational(self):
        cfg = H.HealthConfig(half_life_s=600.0, release_threshold=1.0)
        now = time.time()
        hot = json.dumps({"score": 5.0, "time": now})
        cold = json.dumps({"score": 0.1, "time": now})
        expired = H.quarantine_record("r", 3.0, now - 1000.0, 900.0)
        live = H.quarantine_record("r", 3.0, now, 900.0)
        # expired + cold score -> release
        assert H.release_eligible(node_with(
            {QUARANTINE_ANNOTATION: expired, HEALTH_ANNOTATION: cold}),
            cfg, now)
        # expired but still hot -> stays out (probation)
        assert not H.release_eligible(node_with(
            {QUARANTINE_ANNOTATION: expired, HEALTH_ANNOTATION: hot}),
            cfg, now)
        # not yet expired -> stays out regardless of score
        assert not H.release_eligible(node_with(
            {QUARANTINE_ANNOTATION: live, HEALTH_ANNOTATION: cold}),
            cfg, now)

    def test_manual_quarantine_never_auto_releases(self):
        cfg = H.HealthConfig()
        manual = json.dumps({"reason": "manual"})
        node = node_with({QUARANTINE_ANNOTATION: manual})
        assert H.is_quarantined(node)
        assert not H.release_eligible(node, cfg, time.time() + 1e9)

    def test_config_round_trip_and_unknown_key_rejected(self):
        cfg = H.HealthConfig.from_dict(
            {"enabled": False, "quarantineThreshold": 7})
        assert not cfg.enabled and cfg.quarantine_threshold == 7.0
        assert H.HealthConfig.from_dict(cfg.to_dict()) == cfg
        with pytest.raises(ValueError, match="unknown"):
            H.HealthConfig.from_dict({"quarantineTreshold": 7})


class TestHostCells:
    def test_row_major_host_tiling(self):
        topo = parse_topology("v5e-32")   # 4x8 grid, 4 chips/host
        assert set(H.host_cells("p", topo, 0)) == {
            ("p", 0, 0), ("p", 0, 1), ("p", 0, 2), ("p", 0, 3)}
        assert set(H.host_cells("p", topo, 3)) == {
            ("p", 1, 4), ("p", 1, 5), ("p", 1, 6), ("p", 1, 7)}

    def test_natural_node_name_order(self):
        names = [f"pool-v5e-32-{i}" for i in (0, 2, 10, 9, 1)]
        assert sorted(names, key=H.host_sort_key) == [
            f"pool-v5e-32-{i}" for i in (0, 1, 2, 9, 10)]

    def test_hash_suffixed_names_fall_back_to_positional(self):
        # GKE-style hash suffixes can END in a digit that is NOT a host
        # index; trusting it would misattribute cells. A pool whose
        # names do not form a consistent {distinct, in-range} index set
        # uses positional assignment for the WHOLE pool instead
        from kubeflow_tpu.scheduler.inventory import SliceInventory
        cluster = FakeCluster()
        for suffix in ("8b9f2c-x4q7", "a01d33-p2m7", "c77e10-zzb3"):
            cluster.add_node(
                f"gke-pool-{suffix}",
                {"google.com/tpu": 4, "cpu": 96, "memory": 2 ** 37},
                labels={"cloud.google.com/gke-tpu-topology": "v5e-16",
                        "kubeflow.org/pool": "gke"})
        inv = SliceInventory.from_nodes(cluster.list("v1", "Node"))
        topo = parse_topology("v5e-16")
        # positional by natural name order: 3 nodes claim hosts 0-2,
        # the 4th host (no node) is down — nothing lands on host 7 just
        # because a name ends in "7"
        names = sorted(inv.cells_by_node)
        assert [inv.cells_by_node[n] for n in names] == [
            set(H.host_cells("gke", topo, i)) for i in range(3)]
        assert inv.down_cells == set(H.host_cells("gke", topo, 3))

    def test_deleted_middle_node_does_not_shift_attribution(self):
        # host indices come from the node NAME, not list position: with
        # node -2 deleted, node -3 must keep host 3's cells and ONLY
        # host 2's cells go down — positional assignment would shift
        # every later host one block over and carve the wrong chips
        from kubeflow_tpu.scheduler.inventory import SliceInventory
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="p")
        cluster.delete("v1", "Node", "", "p-v5e-32-2")
        inv = SliceInventory.from_nodes(cluster.list("v1", "Node"))
        topo = parse_topology("v5e-32")
        assert inv.cells_by_node["p-v5e-32-3"] == \
            set(H.host_cells("p", topo, 3))
        assert inv.cells_by_node["p-v5e-32-7"] == \
            set(H.host_cells("p", topo, 7))
        assert inv.down_cells == set(H.host_cells("p", topo, 2))


# ------------------------------------------------------- control plane


def tpujob(name, ckpt="", stall_timeout=None, backoff=None):
    spec = {
        "replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}},
        "schedulingPolicy": {"queue": "research", "priority": 0,
                             "preemptible": False},
    }
    if ckpt:
        spec["checkpointDir"] = ckpt
    rp = {"backoffLimit": 6}
    if stall_timeout is not None:
        rp["stallTimeoutSeconds"] = stall_timeout
    if backoff is not None:
        rp["restartBackoffSeconds"] = backoff
    spec["runPolicy"] = rp
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "kubeflow"},
            "spec": spec}


def two_pool_env(quarantine=True, threshold=0.9):
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8", pool="pool-a")
    cluster.add_tpu_slice_nodes("v5e-8", pool="pool-b")
    config = SchedulerConfig(health=H.HealthConfig(
        enabled=quarantine, quarantine_threshold=threshold,
        release_threshold=0.5, quarantine_s=300.0))
    mgr = Manager(cluster)
    mgr.add(SliceScheduler(config))
    mgr.add(TrainingJobReconciler("TPUJob"))
    return cluster, mgr


def drive(cluster, mgr, ticks=4):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


def get_job(cluster, name="job"):
    return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                       name)


def binding_pools(job):
    raw = k8s.annotations_of(job).get(BINDING_ANNOTATION)
    if not raw:
        return None
    return sorted({r["pool"] for r in json.loads(raw)["slices"]})


class TestSuspectRebind:
    def test_crash_records_suspect_and_evidence(self):
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job", ckpt="/ckpt/job", backoff=30))
        drive(cluster, mgr)
        assert binding_pools(get_job(cluster)) == ["pool-a"]
        victim = cluster.get("v1", "Pod", "kubeflow", "job-worker-0-1")
        flaky = victim["spec"]["nodeName"]
        # only the OPERATOR reacts (no scheduler pass yet): the suspect
        # annotation and the node's health evidence both land
        op = TrainingJobReconciler("TPUJob")
        cluster.fail_pod("kubeflow", "job-worker-0-1", "crash loop")
        op.reconcile(cluster, ("kubeflow", "job"))
        job = get_job(cluster)
        assert k8s.annotations_of(job)[SUSPECT_ANNOTATION] == flaky
        rec = H.health_of(cluster.get("v1", "Node", "", flaky))
        assert rec["events"] == 1 and rec["last"] == "pod-crash"
        assert job["spec"]["resumeFrom"] == "/ckpt/job"
        for c in mgr.controllers:
            c.stop()

    def test_gang_migrates_within_one_rebind(self):
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job", ckpt="/ckpt/job", backoff=30))
        drive(cluster, mgr)
        cluster.fail_pod("kubeflow", "job-worker-0-1", "crash loop")
        drive(cluster, mgr, ticks=6)
        job = get_job(cluster)
        # ONE rebind later the gang is on the clean pool, the suspect
        # record is spent, and the flaky host is quarantined (threshold
        # 0.9 < one crash's weight)
        assert binding_pools(job) == ["pool-b"]
        # cleared = null-delete: key absent or patched to None (the
        # kube semantics FakeCluster mirrors; suspect_of treats both as
        # no-suspect)
        assert not k8s.annotations_of(job).get(SUSPECT_ANNOTATION)
        flaky = cluster.get("v1", "Node", "", "pool-a-v5e-8-1")
        assert H.is_quarantined(flaky)
        for c in mgr.controllers:
            c.stop()

    def test_health_disabled_restarts_in_place(self):
        # the placement-blind baseline: suspect recorded but ignored,
        # no quarantine, the binding never moves
        cluster, mgr = two_pool_env(quarantine=False)
        cluster.create(tpujob("job", ckpt="/ckpt/job"))
        drive(cluster, mgr)
        cluster.fail_pod("kubeflow", "job-worker-0-1", "crash loop")
        drive(cluster, mgr, ticks=6)
        job = get_job(cluster)
        assert binding_pools(job) == ["pool-a"]
        assert k8s.annotations_of(job).get(SUSPECT_ANNOTATION)
        assert not H.is_quarantined(
            cluster.get("v1", "Node", "", "pool-a-v5e-8-1"))
        for c in mgr.controllers:
            c.stop()

    def test_multi_host_failure_attributes_to_nobody(self):
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job"))
        drive(cluster, mgr)
        cluster.fail_pod("kubeflow", "job-worker-0-0", "power loss")
        cluster.fail_pod("kubeflow", "job-worker-0-1", "power loss")
        drive(cluster, mgr, ticks=4)
        # both hosts died: no single suspect, the gang restarts in
        # place (migrating off one host would not help)
        job = get_job(cluster)
        assert not k8s.annotations_of(job).get(SUSPECT_ANNOTATION)
        assert binding_pools(job) == ["pool-a"]
        for c in mgr.controllers:
            c.stop()

    def test_suspect_on_only_feasible_placement_falls_back_in_place(self):
        # starvation guard: a SINGLE-pool cluster, full-pool gang, one
        # transient pod crash — excluding the suspect leaves no
        # feasible placement, so the exclusion degrades to preference:
        # the gang re-binds in place (the pre-health behavior) instead
        # of sitting QUEUED forever, and the spent suspect clears
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8", pool="only")
        mgr = Manager(cluster)
        # threshold high: suspect path only, no quarantine rescue
        mgr.add(SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            quarantine_threshold=50.0))))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("solo", ckpt="/ckpt/solo"))
        drive(cluster, mgr)
        assert binding_pools(get_job(cluster, "solo")) == ["only"]
        cluster.fail_pod("kubeflow", "solo-worker-0-1", "one-off crash")
        drive(cluster, mgr, ticks=8)
        job = get_job(cluster, "solo")
        assert binding_pools(job) == ["only"]     # re-bound, not starved
        assert not k8s.annotations_of(job).get(SUSPECT_ANNOTATION)
        for c in mgr.controllers:
            c.stop()

    def test_new_placements_avoid_quarantined_host(self):
        cluster, mgr = two_pool_env()
        # quarantine pool-a host 1 by hand (the kubectl path)
        cluster.patch("v1", "Node", "", "pool-a-v5e-8-1", {
            "metadata": {"annotations": {
                QUARANTINE_ANNOTATION: json.dumps(
                    {"reason": "manual"})}}})
        cluster.create(tpujob("job"))
        drive(cluster, mgr)
        # a full-pool v5e-8 gang cannot use pool-a with one host out
        assert binding_pools(get_job(cluster)) == ["pool-b"]
        for c in mgr.controllers:
            c.stop()


class TestQuarantineLifecycle:
    def test_threshold_quarantines_and_decay_releases(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8", pool="p")
        # tiny half-life/duration so the whole lifecycle runs in-test
        sched = SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            half_life_s=0.05, quarantine_threshold=0.9,
            release_threshold=0.3, quarantine_s=0.05)))
        node_name = "p-v5e-8-0"
        H.record_host_event(cluster, node_name, H.EVENT_POD_CRASH)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        node = cluster.get("v1", "Node", "", node_name)
        assert H.is_quarantined(node)
        assert "health score" in H.quarantine_of(node)["reason"]
        # expiry passes AND the score decays -> probation release
        time.sleep(0.15)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        assert not H.is_quarantined(
            cluster.get("v1", "Node", "", node_name))

    def test_still_hot_host_gets_extended_not_released(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8", pool="p")
        # long half-life: the score barely decays while the (short)
        # quarantine expires -> the pass re-ups instead of releasing
        sched = SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            half_life_s=600.0, quarantine_threshold=0.9,
            release_threshold=0.3, quarantine_s=0.01)))
        node_name = "p-v5e-8-0"
        H.record_host_event(cluster, node_name, H.EVENT_POD_CRASH)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        first = H.quarantine_of(cluster.get("v1", "Node", "", node_name))
        time.sleep(0.05)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        second = H.quarantine_of(cluster.get("v1", "Node", "", node_name))
        assert second is not None and second["until"] > first["until"]

    def test_quarantine_cordons_and_release_uncordons(self):
        # cell carving only steers the PLANNER; a sub-slice gang's pods
        # pin by pool label, so the kube scheduler could put them right
        # back on the bad host — quarantine therefore cordons the node
        # (spec.unschedulable) and the probation release lifts OUR
        # cordon again
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="p")
        sched = SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            half_life_s=0.05, quarantine_threshold=0.9,
            release_threshold=0.3, quarantine_s=0.05)))
        H.record_host_event(cluster, "p-v5e-32-0", H.EVENT_POD_CRASH)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        node = cluster.get("v1", "Node", "", "p-v5e-32-0")
        assert node["spec"]["unschedulable"] is True
        assert H.quarantine_of(node)["cordoned"] is True
        time.sleep(0.15)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        node = cluster.get("v1", "Node", "", "p-v5e-32-0")
        assert not H.is_quarantined(node)
        assert not node["spec"].get("unschedulable")

    def test_sub_slice_gang_pods_stay_off_quarantined_host(self):
        # the within-pool hole closed end to end: a v5e-8 gang carved
        # out of a v5e-32 pool with a quarantined host must neither
        # PLAN onto its cells nor have its pods SCHEDULED onto its node
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="p")
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            quarantine_threshold=0.9))))
        mgr.add(TrainingJobReconciler("TPUJob"))
        H.record_host_event(cluster, "p-v5e-32-0", H.EVENT_POD_CRASH)
        cluster.create(tpujob("carved"))
        drive(cluster, mgr)
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 2
        assert all(p["status"]["phase"] == "Running" for p in pods)
        assert all(p["spec"]["nodeName"] != "p-v5e-32-0" for p in pods)
        binding = json.loads(k8s.annotations_of(get_job(
            cluster, "carved"))[BINDING_ANNOTATION])
        topo = parse_topology("v5e-32")
        rect_cells = set()
        for r in binding["slices"]:
            for i in range(r["x"], r["x"] + r["h"]):
                for jj in range(r["y"], r["y"] + r["w"]):
                    rect_cells.add((r["pool"], i, jj))
        assert rect_cells.isdisjoint(H.host_cells("p", topo, 0))
        for c in mgr.controllers:
            c.stop()

    def test_disabling_health_releases_auto_quarantines(self):
        # flipping the ConfigMap to enabled:false must revert to
        # placement-blind for real: auto-quarantines release (cordon
        # lifted) instead of stranding chips behind annotations nothing
        # will ever expire; MANUAL quarantines are a human's call and
        # stay
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="p")
        on = SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            quarantine_threshold=0.9)))
        H.record_host_event(cluster, "p-v5e-32-0", H.EVENT_POD_CRASH)
        on.reconcile(cluster, ("", "#cluster-pass"))
        cluster.patch("v1", "Node", "", "p-v5e-32-1", {
            "metadata": {"annotations": {QUARANTINE_ANNOTATION:
                                         json.dumps({"reason":
                                                     "manual"})}}})
        assert H.is_quarantined(cluster.get("v1", "Node", "",
                                            "p-v5e-32-0"))
        off = SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            enabled=False)))
        off.reconcile(cluster, ("", "#cluster-pass"))
        auto = cluster.get("v1", "Node", "", "p-v5e-32-0")
        assert not H.is_quarantined(auto)
        assert not auto["spec"].get("unschedulable")
        assert H.is_quarantined(cluster.get("v1", "Node", "",
                                            "p-v5e-32-1"))

    def test_manual_quarantine_survives_passes(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8", pool="p")
        cluster.patch("v1", "Node", "", "p-v5e-8-0", {
            "metadata": {"annotations": {QUARANTINE_ANNOTATION:
                                         json.dumps({"reason":
                                                     "manual"})}}})
        sched = SliceScheduler(SchedulerConfig(health=H.HealthConfig(
            half_life_s=0.01, quarantine_s=0.01)))
        sched.reconcile(cluster, ("", "#cluster-pass"))
        time.sleep(0.05)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        assert H.is_quarantined(
            cluster.get("v1", "Node", "", "p-v5e-8-0"))


class TestWorkerWatchdogs:
    def _running_env(self, stall_timeout=60):
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job", stall_timeout=stall_timeout,
                              backoff=30))
        drive(cluster, mgr)
        return cluster, mgr

    def _beat(self, cluster, pod, step, t):
        cluster.patch("v1", "Pod", "kubeflow", pod, {
            "metadata": {"annotations": {HEARTBEAT_ANNOTATION:
                                         json.dumps({"step": step,
                                                     "time": t})}}})

    def test_stalled_worker_restarts_gang_with_suspect(self):
        cluster, mgr = self._running_env()
        now = time.time()
        self._beat(cluster, "job-worker-0-0", 10, now)         # chief ok
        self._beat(cluster, "job-worker-0-1", 4, now - 120)    # stale
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        job = get_job(cluster)
        cond = k8s.get_condition(job, "Restarting")
        assert cond and cond.get("reason") == "WorkerStallTimeout"
        suspect = k8s.annotations_of(job)[SUSPECT_ANNOTATION]
        rec = H.health_of(cluster.get("v1", "Node", "", suspect))
        assert rec["last"] == "worker-stall"
        for c in mgr.controllers:
            c.stop()

    def test_fresh_workers_never_trip(self):
        cluster, mgr = self._running_env()
        now = time.time()
        self._beat(cluster, "job-worker-0-0", 10, now)
        self._beat(cluster, "job-worker-0-1", 10, now)
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))
        assert not k8s.condition_true(get_job(cluster), "Restarting")
        for c in mgr.controllers:
            c.stop()

    def test_future_heartbeat_clamped_not_infinitely_fresh(
            self, monkeypatch):
        # the clock-skew regression (satellite 1): a hung chief whose
        # last beat is stamped in the FUTURE must still trip the
        # watchdog one timeout after we first SAW that beat — the old
        # code read now-beat<0 as fresh until the controller's clock
        # caught up with the skew (potentially never)
        import kubeflow_tpu.controllers.tpujob as tpujob_mod
        cluster, mgr = self._running_env(stall_timeout=60)
        t0 = time.time()
        clock = {"t": t0}
        monkeypatch.setattr(tpujob_mod, "_now", lambda: clock["t"])
        self._beat(cluster, "job-worker-0-0", 5, t0 + 100_000.0)
        op = TrainingJobReconciler("TPUJob")
        op.reconcile(cluster, ("kubeflow", "job"))       # first sight
        assert not k8s.condition_true(get_job(cluster), "Restarting")
        clock["t"] = t0 + 30                             # under timeout
        op.reconcile(cluster, ("kubeflow", "job"))
        assert not k8s.condition_true(get_job(cluster), "Restarting")
        clock["t"] = t0 + 61                             # past timeout
        op.reconcile(cluster, ("kubeflow", "job"))
        job = get_job(cluster)
        cond = k8s.get_condition(job, "Restarting")
        assert cond and cond.get("reason") == "StallTimeout"
        for c in mgr.controllers:
            c.stop()

    def test_advancing_future_beat_clears_clamp(self, monkeypatch):
        # a LIVE worker with a skewed clock keeps advancing its beat:
        # each new value resets the first-seen clamp, so skew alone
        # never restarts a healthy gang
        import kubeflow_tpu.controllers.tpujob as tpujob_mod
        cluster, mgr = self._running_env(stall_timeout=60)
        t0 = time.time()
        clock = {"t": t0}
        monkeypatch.setattr(tpujob_mod, "_now", lambda: clock["t"])
        op = TrainingJobReconciler("TPUJob")
        for i in range(4):
            self._beat(cluster, "job-worker-0-0", i,
                       t0 + 100_000.0 + i)      # future, but advancing
            op.reconcile(cluster, ("kubeflow", "job"))
            clock["t"] += 50                    # near the timeout each
        assert not k8s.condition_true(get_job(cluster), "Restarting")
        for c in mgr.controllers:
            c.stop()

    def test_step_skew_streak_scores_the_slow_host(self):
        cluster, mgr = self._running_env()
        now = time.time()
        op = TrainingJobReconciler("TPUJob")
        slow_node = cluster.get(
            "v1", "Pod", "kubeflow",
            "job-worker-0-1")["spec"]["nodeName"]
        for i in range(H.STEP_SKEW_STREAK):
            self._beat(cluster, "job-worker-0-0", 20 + i, now)
            self._beat(cluster, "job-worker-0-1", 2, now)   # straggler
            op.reconcile(cluster, ("kubeflow", "job"))
        rec = H.health_of(cluster.get("v1", "Node", "", slow_node))
        assert rec["last"] == "step-skew"
        assert rec["score"] == pytest.approx(0.25, abs=0.01)
        # no teardown: skew is evidence, not a failure
        assert not k8s.condition_true(get_job(cluster), "Restarting")
        # a recovered worker clears the streak: no further events
        self._beat(cluster, "job-worker-0-1", 23, now)
        op.reconcile(cluster, ("kubeflow", "job"))
        assert H.health_of(cluster.get("v1", "Node", "",
                                       slow_node))["events"] == 1
        for c in mgr.controllers:
            c.stop()

    def test_stale_worker_beat_never_scores_skew(self):
        # a FROZEN heartbeat is a hung worker (the watchdogs' case),
        # not a slow host: skew scoring requires both beats fresh, so a
        # wedged pod on a watchdog-less job cannot slowly quarantine a
        # healthy host on step-skew evidence
        cluster, mgr = two_pool_env()
        # no stallTimeoutSeconds: freshness falls to STEP_SKEW_FRESH_S
        cluster.create(tpujob("job"))
        drive(cluster, mgr)
        now = time.time()
        op = TrainingJobReconciler("TPUJob")
        slow_node = cluster.get(
            "v1", "Pod", "kubeflow",
            "job-worker-0-1")["spec"]["nodeName"]
        for i in range(H.STEP_SKEW_STREAK + 2):
            self._beat(cluster, "job-worker-0-0", 50 + i, now)
            self._beat(cluster, "job-worker-0-1", 2,
                       now - H.STEP_SKEW_FRESH_S - 60)   # frozen beat
            op.reconcile(cluster, ("kubeflow", "job"))
        assert H.health_of(cluster.get("v1", "Node", "",
                                       slow_node))["events"] == 0
        for c in mgr.controllers:
            c.stop()


class TestStatePruning:
    def test_finished_job_drops_watchdog_state_and_skew_series(self):
        # a long-lived controller must not keep clamp/streak entries or
        # export a stale skew gauge for every job that ever straggled
        from kubeflow_tpu.obs import registry as obsreg
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job", stall_timeout=60))
        drive(cluster, mgr)
        op = TrainingJobReconciler("TPUJob")
        now = time.time()
        beats = {"job-worker-0-0": (30, now),
                 "job-worker-0-1": (2, now),          # straggler
                 }
        for pod, (step, t) in beats.items():
            cluster.patch("v1", "Pod", "kubeflow", pod, {
                "metadata": {"annotations": {HEARTBEAT_ANNOTATION:
                                             json.dumps({"step": step,
                                                         "time": t})}}})
        # a future-stamped beat seeds the clamp map too
        cluster.patch("v1", "Pod", "kubeflow", "job-worker-0-0", {
            "metadata": {"annotations": {HEARTBEAT_ANNOTATION:
                                         json.dumps({"step": 30,
                                                     "time": now + 999}
                                                    )}}})
        op.reconcile(cluster, ("kubeflow", "job"))
        assert op._skew_streak and op._future_beats
        cluster.set_pod_phase("kubeflow", "job-worker-0-0", "Succeeded")
        op.reconcile(cluster, ("kubeflow", "job"))
        assert not op._skew_streak and not op._future_beats
        gauge = obsreg.gauge("kftpu_job_step_skew",
                             "chief step minus the slowest worker's "
                             "heartbeat step",
                             labels=("namespace", "name"))
        assert ("kubeflow", "job") not in gauge._children
        for c in mgr.controllers:
            c.stop()


class TestDashboard:
    def test_sched_nodes_endpoint(self):
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job"))
        drive(cluster, mgr)
        H.record_host_event(cluster, "pool-b-v5e-8-0", H.EVENT_STALL)
        cluster.patch("v1", "Node", "", "pool-b-v5e-8-1", {
            "metadata": {"annotations": {QUARANTINE_ANNOTATION:
                                         H.quarantine_record(
                                             "r", 2.0, 0.0, 60.0)}}})
        app = build_dashboard_app(cluster)
        status, rows = app.dispatch("GET", "/api/sched/nodes", b"")
        assert status == 200
        by_node = {r["node"]: r for r in rows}
        assert len(by_node) == 4
        gangs = by_node["pool-a-v5e-8-0"]["gangs"]
        assert gangs == ["kubeflow/job"]
        assert by_node["pool-b-v5e-8-0"]["healthScore"] > 0.9
        assert by_node["pool-b-v5e-8-0"]["lastEvent"] == "stall"
        q = by_node["pool-b-v5e-8-1"]
        assert q["quarantined"] and q["quarantineReason"] == "r"
        assert q["quarantineExpiry"] == 60.0
        for c in mgr.controllers:
            c.stop()

    def test_queues_view_carries_quarantine_context(self):
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        cluster, mgr = two_pool_env()
        cluster.create(tpujob("job"))
        drive(cluster, mgr)
        cluster.patch("v1", "Node", "", "pool-b-v5e-8-1", {
            "metadata": {"annotations": {QUARANTINE_ANNOTATION:
                                         json.dumps({"reason":
                                                     "manual"})}}})
        cluster.patch("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                      "job", {"metadata": {"annotations": {
                          SUSPECT_ANNOTATION: "pool-a-v5e-8-1"}}})
        app = build_dashboard_app(cluster)
        status, body = app.dispatch("GET", "/api/sched/queues", b"")
        assert status == 200
        q = next(row for row in body if row["queue"] == "research")
        assert q["quarantinedHosts"] == 1
        assert q["jobs"][0]["suspect"] == "pool-a-v5e-8-1"
        for c in mgr.controllers:
            c.stop()


class TestDegradedSim:
    def test_quarantine_strictly_reduces_recompute(self):
        from kubeflow_tpu.scheduler.sim import compare_health
        table = compare_health([0, 1], n_jobs=12)
        on, off = table["quarantine_on"], table["quarantine_off"]
        assert off["host_faults"] > on["host_faults"]
        assert on["recomputed_ticks"] < off["recomputed_ticks"]
        assert on["useful_work_fraction"] >= off["useful_work_fraction"]
        # everything still finishes in both arms (no starvation)
        assert on["unfinished"] == 0 and off["unfinished"] == 0

    def test_degraded_sim_is_seed_deterministic(self):
        from kubeflow_tpu.scheduler.sim import (DegradedHost,
                                                make_workload, simulate)
        def run():
            return simulate(
                make_workload(3, n_jobs=10), pools=("v5e-32",),
                policy="preempt",
                degraded=(DegradedHost(pool="pool-0-v5e-32", host=2,
                                       start=4, end=30),),
                node_health=True)
        assert run() == run()


@pytest.mark.slow
@pytest.mark.compute
class TestHealthSoak:
    def test_flaky_host_migration_with_parity(self, tmp_path):
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.scheduler.soak import HealthSoak

        soak = HealthSoak(workdir=str(tmp_path), quarantine=True)
        report = soak.run()
        assert report["outcome"] == "succeeded", report
        # the acceptance bar: migrated off the suspect host within ONE
        # rebind, params identical to a clean run
        assert report["migrated"] and report["rebinds"] == 1
        assert report["restarts"] == 1
        assert report["flaky_quarantined"]
        migrated = final_params(report["checkpoint_dir"])
        clean = soak.clean_params()
        delta = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(np.max(np.abs(
                np.asarray(a) - np.asarray(b)))),
            migrated, clean)), default=0.0)
        assert delta <= 1e-5, f"params diverged by {delta}"
