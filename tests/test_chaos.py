"""Seeded chaos scenarios (ISSUE 2): the recovery paths SURVEY §5 promises
— "a dead worker kills the gang", checkpoint-resume makes restarts cheap —
exercised against injected faults instead of trusted.

Fault menu (cluster/chaos.py) and the hardening each one pins:

- pod deletion mid-run (preemption)      → vanish-detector gang restart
- transient apiserver 5xx burst          → controller retry budget +
                                           HttpKubeClient retry-with-jitter
- watch-stream drop                      → periodic resync re-enqueue
- truncated / uncommitted checkpoint     → integrity manifest, latest_step
                                           skip, previous-intact fallback,
                                           corrupt-remains clearing on
                                           re-save
- hung-but-not-dead chief                → heartbeat + stall watchdog
- SIGTERM mid-train (slice reclaim)      → PreemptionGuard forced save +
                                           PREEMPTED_EXIT_CODE

Everything here is seeded/deterministic and fast enough for tier-1 (the
``chaos`` marker, ci_config.yaml unit_tests_chaos); the end-to-end soaks
with REAL training segments are ``slow`` (and ``bench.py --mode chaos``).
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.trainingjob import HEARTBEAT_ANNOTATION
from kubeflow_tpu.cluster.chaos import (ChaosKubeClient, ChaosPolicy,
                                        ChaosSoak, SoakFault,
                                        TransientAPIError,
                                        truncate_checkpoint_payload,
                                        uncommit_checkpoint)
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                             RESTART_NOT_BEFORE_ANNOTATION,
                                             TrainingJobReconciler)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPU_AV = "tpu.kubeflow.org/v1alpha1"


def tpujob_manifest(name="train", **run_policy):
    return {
        "apiVersion": TPU_AV, "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "replicaSpecs": {
                "TPU": {"tpuTopology": "v5e-8",
                        "template": {"spec": {"containers": [
                            {"name": "jax", "image": "trainer:v1"}]}}},
            },
            "checkpointDir": "/ckpt/train",
            "runPolicy": {"backoffLimit": 4, **run_policy},
        },
    }


def make_env(policy=None):
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8")
    chaos = ChaosKubeClient(cluster, policy)
    mgr = Manager(chaos)
    ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
    return cluster, chaos, mgr, ctrl


def drive(cluster, mgr, ticks=3):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
        # error-requeue backoff (controllers/runtime.py): retry keys sit
        # in _delayed for a jittered exponential interval — give SHORT
        # delays their due time so the deterministic drive still sees
        # bounded retries complete (long requeue_after timers — stall
        # probes, TTLs — stay untouched)
        due = [t for c in mgr.controllers for (t, _k) in c._delayed]
        wait = min(due, default=0.0) - time.monotonic()
        if 0 < wait <= 1.0:
            time.sleep(wait + 0.005)
    mgr.run_pending()


def get_job(cluster, name="train"):
    return cluster.get(TPU_AV, "TPUJob", "kubeflow", name)


def running_pods(cluster):
    return [p for p in cluster.list("v1", "Pod", "kubeflow")
            if p.get("status", {}).get("phase") == "Running"]


# ---------------------------------------------------------------- injection


class TestChaosKubeClient:
    def test_seeded_rate_injection_is_deterministic(self):
        def positions(seed):
            c = ChaosKubeClient(FakeCluster(),
                                ChaosPolicy(seed=seed, error_rate=0.3,
                                            max_errors=5))
            for _ in range(40):
                try:
                    c.list("v1", "Pod")
                except TransientAPIError:
                    pass
            return [f.at_call for f in c.injected]

        assert positions(7) == positions(7)        # replayable
        assert positions(7) != positions(8)        # actually seeded
        assert len(positions(7)) == 5              # budget respected

    def test_burst_and_passthrough(self):
        cluster = FakeCluster()
        chaos = ChaosKubeClient(cluster)
        chaos.fail_next(2)
        with pytest.raises(TransientAPIError):
            chaos.list("v1", "Pod")
        with pytest.raises(TransientAPIError):
            chaos.list("v1", "Pod")
        assert chaos.list("v1", "Pod") == []       # burst exhausted
        # test-driver helpers bypass injection entirely
        chaos.fail_next(1)
        chaos.add_tpu_slice_nodes("v5e-8")
        assert chaos._burst == 1                    # helper consumed no fault


# ------------------------------------------------- control-plane scenarios


class TestGangRecovery:
    def test_pod_kill_restarts_gang_with_resume(self):
        """Preemption deletes the pod OBJECT — no Failed phase ever
        appears; the vanish detector must restart the whole gang and
        point it at its own checkpoints."""
        cluster, _, mgr, _ = make_env()
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        assert len(running_pods(cluster)) == 2
        cluster.delete("v1", "Pod", "kubeflow", "train-worker-0-1")
        drive(cluster, mgr)
        job = get_job(cluster)
        assert k8s.annotations_of(job)[RESTART_COUNT_ANNOTATION] == "1"
        assert job["spec"]["resumeFrom"] == "/ckpt/train"
        assert len(running_pods(cluster)) == 2     # gang is back

    def test_api_5xx_burst_survived_by_retry_budget(self):
        """A worker dies exactly as the apiserver starts throwing 5xxs:
        the reconciler's bounded retries must absorb the burst and still
        complete the gang restart."""
        cluster, chaos, mgr, _ = make_env()
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        chaos.fail_next(3)
        cluster.fail_pod("kubeflow", "train-worker-0-1", "chaos: died")
        drive(cluster, mgr, ticks=8)
        assert len(chaos.injected) == 3            # faults really fired
        job = get_job(cluster)
        assert k8s.annotations_of(job)[RESTART_COUNT_ANNOTATION] == "1"
        assert len(running_pods(cluster)) == 2

    def test_watch_drop_recovered_by_resync(self):
        """Every watch stream dies, then a worker fails: no event will
        ever arrive, so only the periodic relist (controllers/runtime.py
        resync_interval) can re-enqueue the job."""
        cluster, chaos, mgr, ctrl = make_env()
        cluster.create(tpujob_manifest())
        drive(cluster, mgr)
        assert chaos.drop_watch_streams() > 0
        cluster.fail_pod("kubeflow", "train-worker-0-0", "chaos: died")
        mgr.run_pending()
        # watches are dead and resync is off: the failure went unseen
        assert RESTART_COUNT_ANNOTATION not in \
            k8s.annotations_of(get_job(cluster))
        ctrl.resync_interval = 0.001
        time.sleep(0.002)
        drive(cluster, mgr)
        job = get_job(cluster)
        assert k8s.annotations_of(job)[RESTART_COUNT_ANNOTATION] == "1"
        assert len(running_pods(cluster)) == 2

    def test_hung_chief_restarted_by_stall_watchdog(self):
        """Live pod, stale heartbeat: a wedged collective never produces
        a Failed phase — runPolicy.stallTimeoutSeconds is the only
        recovery path."""
        cluster, _, mgr, _ = make_env()
        cluster.create(tpujob_manifest(stallTimeoutSeconds=60))
        drive(cluster, mgr)
        chief = "train-worker-0-0"
        stale = json.dumps({"step": 3, "time": time.time() - 120})
        cluster.patch("v1", "Pod", "kubeflow", chief,
                      {"metadata": {"annotations":
                                    {HEARTBEAT_ANNOTATION: stale}}})
        drive(cluster, mgr)
        job = get_job(cluster)
        assert k8s.annotations_of(job)[RESTART_COUNT_ANNOTATION] == "1"
        # recreated chief has NO heartbeat yet: must not re-trip
        drive(cluster, mgr)
        assert k8s.annotations_of(
            get_job(cluster))[RESTART_COUNT_ANNOTATION] == "1"

    def test_fresh_heartbeat_never_trips_watchdog(self):
        cluster, _, mgr, _ = make_env()
        cluster.create(tpujob_manifest(stallTimeoutSeconds=60))
        drive(cluster, mgr)
        fresh = json.dumps({"step": 3, "time": time.time()})
        cluster.patch("v1", "Pod", "kubeflow", "train-worker-0-0",
                      {"metadata": {"annotations":
                                    {HEARTBEAT_ANNOTATION: fresh}}})
        drive(cluster, mgr)
        assert RESTART_COUNT_ANNOTATION not in \
            k8s.annotations_of(get_job(cluster))

    def test_restart_backoff_gates_recreation(self, monkeypatch):
        """The not-before annotation persists the wait: the gang stays
        down until it passes (even across a controller restart), then
        recreates."""
        import kubeflow_tpu.controllers.tpujob as tpujob_mod

        cluster, _, mgr, _ = make_env()
        cluster.create(tpujob_manifest(restartBackoffSeconds=30,
                                       restartBackoffMaxSeconds=300))
        drive(cluster, mgr)
        t0 = time.time()
        cluster.fail_pod("kubeflow", "train-worker-0-1", "chaos: died")
        drive(cluster, mgr)
        job = get_job(cluster)
        not_before = float(
            k8s.annotations_of(job)[RESTART_NOT_BEFORE_ANNOTATION])
        # base 30s, deterministic jitter in [1.0, 1.5)
        assert 30 <= not_before - t0 <= 46
        # inside the window: a fresh reconciler (controller restart) must
        # still hold the gang down
        rec = TrainingJobReconciler("TPUJob")
        res = rec.reconcile(cluster, ("kubeflow", "train"))
        assert res.requeue_after > 0
        assert cluster.list("v1", "Pod", "kubeflow") == []
        # after the window: recreate
        monkeypatch.setattr(tpujob_mod, "_now", lambda: not_before + 1)
        rec.reconcile(cluster, ("kubeflow", "train"))
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2

    def test_backoff_delay_grows_exponentially(self, monkeypatch):
        """delay = min(base·2^restarts, max) · seeded jitter — computed
        against a fake clock so the schedule is checked exactly."""
        import random as random_mod

        import kubeflow_tpu.controllers.tpujob as tpujob_mod

        clock = {"t": 1000.0}
        monkeypatch.setattr(tpujob_mod, "_now", lambda: clock["t"])
        cluster, _, mgr, ctrl = make_env()
        cluster.create(tpujob_manifest(restartBackoffSeconds=30,
                                       restartBackoffMaxSeconds=300,
                                       backoffLimit=5))
        for attempt in range(3):
            drive(cluster, mgr)
            victim = k8s.name_of(running_pods(cluster)[0])
            cluster.fail_pod("kubeflow", victim, "chaos: died")
            drive(cluster, mgr)
            nb = float(k8s.annotations_of(get_job(cluster))[
                RESTART_NOT_BEFORE_ANNOTATION])
            expected = min(30 * (2 ** attempt), 300) * random_mod.Random(
                f"kubeflow/train:{attempt}").uniform(1.0, 1.5)
            assert abs((nb - clock["t"]) - expected) < 1e-3
            clock["t"] = nb + 1        # step the clock past the window
            # the controller's requeue timer runs on REAL time; with the
            # fake clock advanced, re-enqueue the key by hand
            ctrl.enqueue_existing()


# ------------------------------------------------------- worker heartbeat


class TestHeartbeatReporter:
    def _pod(self, cluster, name="hb-pod"):
        cluster.create({"apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": name,
                                     "namespace": "kubeflow"},
                        "spec": {"containers": [{"name": "c"}]}})

    def test_beat_patches_own_pod_and_rate_limits(self):
        from kubeflow_tpu.runtime.metrics import HeartbeatReporter
        cluster = FakeCluster()
        self._pod(cluster)
        hb = HeartbeatReporter(cluster, "kubeflow", "hb-pod", interval_s=60)
        assert hb.beat(5)
        raw = k8s.annotations_of(
            cluster.get("v1", "Pod", "kubeflow",
                        "hb-pod"))[HEARTBEAT_ANNOTATION]
        payload = json.loads(raw)
        assert payload["step"] == 5 and payload["time"] > 0
        assert not hb.beat(6)                  # rate-limited
        assert hb.beat(7, force=True)          # ...unless forced

    def test_flaky_apiserver_never_raises(self):
        from kubeflow_tpu.runtime.metrics import HeartbeatReporter
        cluster = FakeCluster()
        self._pod(cluster)
        chaos = ChaosKubeClient(cluster)
        chaos.fail_next(1)
        hb = HeartbeatReporter(chaos, "kubeflow", "hb-pod", interval_s=0)
        assert not hb.beat(1)                  # swallowed, reported False
        assert hb.beat(2)                      # next beat lands

    def test_from_env_requires_pod_identity(self):
        from kubeflow_tpu.runtime.metrics import HeartbeatReporter
        assert HeartbeatReporter.from_env(env={}) is None
        hb = HeartbeatReporter.from_env(client=FakeCluster(),
                                        env={"KFTPU_POD_NAME": "p",
                                             "KFTPU_POD_NAMESPACE": "ns"})
        assert hb is not None and hb.pod == "p" and hb.namespace == "ns"


# ----------------------------------------------------- http client retries


class _ScriptedHandler(BaseHTTPRequestHandler):
    # shared across requests: [(code, body)] or [(code, body, headers)]
    script: list
    hits: list

    def do_GET(self):
        entry = self.script.pop(0) if self.script else (200, {"items": []})
        code, body = entry[0], entry[1]
        headers = entry[2] if len(entry) > 2 else {}
        type(self).hits.append(code)
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture
def scripted_server():
    servers = []

    def make(script):
        handler = type("H", (_ScriptedHandler,),
                       {"script": list(script), "hits": []})
        srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_port}", handler

    yield make
    for srv in servers:
        srv.shutdown()
        srv.server_close()


class TestHttpClientRetry:
    def test_transient_5xx_retried_to_success(self, scripted_server):
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        url, handler = scripted_server([
            (503, {"code": 503, "reason": "ServiceUnavailable",
                   "message": "leader election"}),
            (500, {"code": 500, "reason": "InternalError",
                   "message": "boom"}),
            (200, {"items": [{"metadata": {"name": "ok"}}]}),
        ])
        client = HttpKubeClient(url, retries=3, retry_backoff_s=0.01)
        items = client.list("v1", "Pod")
        assert [i["metadata"]["name"] for i in items] == ["ok"]
        assert handler.hits == [503, 500, 200]

    def test_4xx_is_meaning_not_weather(self, scripted_server):
        from kubeflow_tpu.cluster.client import NotFoundError
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        url, handler = scripted_server([
            (404, {"code": 404, "reason": "NotFound", "message": "nope"}),
        ])
        client = HttpKubeClient(url, retries=3, retry_backoff_s=0.01)
        with pytest.raises(NotFoundError):
            client.get("v1", "Pod", "ns", "missing")
        assert handler.hits == [404]           # exactly one attempt

    def test_exhausted_budget_surfaces_typed_error(self, scripted_server):
        from kubeflow_tpu.cluster.client import KubeError
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        url, handler = scripted_server([
            (503, {"code": 503, "reason": "ServiceUnavailable",
                   "message": "down"})] * 10)
        client = HttpKubeClient(url, retries=2, retry_backoff_s=0.01)
        with pytest.raises(KubeError):
            client.list("v1", "Pod")
        assert handler.hits == [503, 503, 503]  # 1 try + 2 retries

    def test_retry_after_is_honored_on_429(self, scripted_server):
        """A throttling apiserver's Retry-After beats the client's own
        (much shorter) jitter schedule — the server said when to come
        back, so a health-event storm must not hammer it early."""
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        url, handler = scripted_server([
            (429, {"code": 429, "reason": "TooManyRequests",
                   "message": "throttled"}, {"Retry-After": "0.4"}),
            (200, {"items": []}),
        ])
        client = HttpKubeClient(url, retries=3, retry_backoff_s=0.001)
        t0 = time.monotonic()
        assert client.list("v1", "Pod") == []
        # the wait was the server's 0.4s, not the client's ~1ms jitter
        assert time.monotonic() - t0 >= 0.35
        assert handler.hits == [429, 200]

    def test_retry_wall_clock_cap_bounds_retry_after(self, scripted_server):
        """A Retry-After larger than the wall-clock budget surfaces the
        typed error immediately instead of pinning the caller — the
        reconcile loop's own requeue is the cheaper way to wait."""
        from kubeflow_tpu.cluster.client import KubeError
        from kubeflow_tpu.cluster.http_client import HttpKubeClient
        url, handler = scripted_server([
            (503, {"code": 503, "reason": "ServiceUnavailable",
                   "message": "down"}, {"Retry-After": "30"})] * 5)
        client = HttpKubeClient(url, retries=3, retry_backoff_s=0.01,
                                retry_wall_clock_s=0.2)
        t0 = time.monotonic()
        with pytest.raises(KubeError):
            client.list("v1", "Pod")
        assert time.monotonic() - t0 < 5.0      # no 30s sleep happened
        assert handler.hits == [503]            # gave up before retrying


# ------------------------------------------------ checkpoint integrity


class TestCheckpointIntegrity:
    """The on-disk states a writer dying mid-save leaves behind, and the
    restore-side behavior each must produce. Uses a tiny raw pytree (no
    train step) so the tier stays fast."""

    def _mgr(self, directory):
        import numpy as np
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        m = CheckpointManager(str(directory), save_interval_steps=1,
                              retry_backoff_s=0.01)
        for step in (1, 2):
            m.save(step, {"params": {"w": np.full((64,), float(step))}},
                   force=True)
        m.wait()
        return m, np

    def test_manifest_written_and_verified(self, tmp_path):
        from kubeflow_tpu.runtime.checkpoint import MANIFEST_NAME
        m, _ = self._mgr(tmp_path)
        try:
            for step in (1, 2):
                mpath = tmp_path / str(step) / MANIFEST_NAME
                assert mpath.exists()
                ok, reason = m.verify_step(step)
                assert ok, reason
            assert m.latest_step() == 2
        finally:
            m.close()

    def test_uncommitted_latest_is_skipped(self, tmp_path):
        m, _ = self._mgr(tmp_path)
        try:
            uncommit_checkpoint(str(tmp_path / "2"))
            assert m.latest_step() == 1
            assert m.restore_params()["w"][0] == 1.0
        finally:
            m.close()

    def test_truncated_latest_falls_back_to_prior_intact(self, tmp_path):
        m, _ = self._mgr(tmp_path)
        try:
            truncate_checkpoint_payload(str(tmp_path / "2"))
            ok, reason = m.verify_step(2)
            assert not ok and "mismatch" in reason
            assert m.latest_step() == 1
            assert m.restore_params()["w"][0] == 1.0   # prior intact step
            # an operator asking for the corrupt step EXACTLY must get an
            # error, not a silently different checkpoint
            with pytest.raises(ValueError, match="not intact"):
                m.restore_params(step=2)
        finally:
            m.close()

    def test_resave_over_corrupt_remains_recovers(self, tmp_path):
        """The resume-replay collision the chaos soak flushed out:
        restore fell back past corrupt step N, training replayed to N,
        and the re-save must clear N's remains instead of dying on
        orbax's StepAlreadyExistsError."""
        m, np = self._mgr(tmp_path)
        try:
            truncate_checkpoint_payload(str(tmp_path / "2"))
            assert m.restore_params()["w"][0] == 1.0
            assert m.save(2, {"params": {"w": np.full((64,), 2.5)}},
                          force=True)
            m.wait()
            assert m.latest_step() == 2
            assert m.restore_params()["w"][0] == 2.5
        finally:
            m.close()

    def test_intact_existing_step_never_cleared(self, tmp_path):
        """The corrupt-remains clearing is gated on verification: a save
        retry must never delete a GOOD checkpoint."""
        m, _ = self._mgr(tmp_path)
        try:
            m._clear_corrupt_step(2)               # step 2 is intact
            assert m.latest_step() == 2
        finally:
            m.close()


# ----------------------------------------------------- end-to-end (slow)


@pytest.mark.slow
class TestEndToEnd:
    def test_sigterm_forces_checkpoint_and_preempted_exit(self, tmp_path):
        """Slice reclaim: SIGTERM mid-train → PreemptionGuard finishes
        the step, forces a save, and exits PREEMPTED_EXIT_CODE — non-zero
        (the pod lands Failed, restart-eligible) but recognizable."""
        from kubeflow_tpu.runtime.checkpoint import ORBAX_COMMIT_MARKER
        from kubeflow_tpu.runtime.worker import PREEMPTED_EXIT_CODE

        ckpt = tmp_path / "ckpt"
        env = {**os.environ,
               "KFTPU_CHILD_STEPS": "100000",   # must NOT finish on its own
               "KFTPU_CHILD_CKPT": str(ckpt),
               "KFTPU_CHILD_CKPT_EVERY": "5",
               "KFTPU_CHILD_SIGTERM": "1",
               "PYTHONPATH": REPO}
        env.pop("XLA_FLAGS", None)
        child = os.path.join(os.path.dirname(__file__),
                             "_distributed_train_child.py")
        proc = subprocess.Popen([sys.executable, child], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.monotonic() + 300
            committed = []
            while time.monotonic() < deadline and not committed:
                if proc.poll() is not None:
                    break
                committed = glob.glob(
                    str(ckpt / "*" / ORBAX_COMMIT_MARKER))
                time.sleep(0.2)
            assert committed, "no checkpoint committed before deadline"
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=120)
        except BaseException:
            proc.kill()
            raise
        assert proc.returncode == PREEMPTED_EXIT_CODE, err[-3000:]
        result = json.loads(out.strip().splitlines()[-1])
        assert result["preempted"] is True
        # the FORCED save: an intact checkpoint exists at a step the
        # interval alone (every 5) need not have produced
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        m = CheckpointManager(str(ckpt))
        try:
            last = m.latest_step()
            assert last is not None and last >= 1
            ok, reason = m.verify_step(last)
            assert ok, reason
        finally:
            m.close()

    @pytest.mark.compute
    def test_soak_truncated_checkpoint_parity(self, tmp_path):
        """The acceptance scenario: a run whose LATEST checkpoint is
        truncated mid-soak must restore from the prior intact step,
        replay, and land on the same final params as an uninjected run
        (≤1e-5)."""
        import jax
        import numpy as np
        from kubeflow_tpu.cluster.chaos import final_params

        injected = ChaosSoak(workdir=str(tmp_path / "injected"),
                             faults=[SoakFault(3, "truncate-ckpt")],
                             total_steps=5, checkpoint_every=2).run()
        assert injected["outcome"] == "succeeded", injected
        assert injected["restart_reasons"] == ["GangRestart"]
        clean = ChaosSoak(workdir=str(tmp_path / "clean"), faults=[],
                          total_steps=5, checkpoint_every=2).run()
        assert clean["outcome"] == "succeeded", clean
        deltas = jax.tree.map(
            lambda a, b: float(np.max(np.abs(np.asarray(a) -
                                             np.asarray(b)))),
            final_params(injected["checkpoint_dir"]),
            final_params(clean["checkpoint_dir"]))
        assert max(jax.tree.leaves(deltas), default=0.0) <= 1e-5

    @pytest.mark.compute
    def test_soak_full_fault_menu(self, tmp_path):
        """All five distinct fault kinds in one run, each recovered, job
        Succeeded (the bench.py --mode chaos scenario, compressed)."""
        report = ChaosSoak(
            workdir=str(tmp_path),
            faults=[SoakFault(2, "pod-kill"), SoakFault(3, "api-burst"),
                    SoakFault(4, "watch-drop"),
                    SoakFault(5, "truncate-ckpt"),
                    SoakFault(6, "hung-chief")],
            total_steps=8, checkpoint_every=2).run()
        assert report["outcome"] == "succeeded", report
        assert len(report["injected"]) == 5
        assert "GangPodsVanished" in report["restart_reasons"]
        assert "StallTimeout" in report["restart_reasons"]
        assert report["api_faults"] >= 3           # the burst really hit

    @pytest.mark.compute
    @pytest.mark.sentinel
    def test_soak_chaos_eats_the_lkg_falls_back_to_next_intact(
            self, tmp_path):
        """Satellite (c) of ISSUE 17: a NaN trip rolls the job back to
        the LKG, but chaos truncates the LKG step's payload at trip
        time — the rollback restore must walk back to the NEXT-oldest
        intact step, replay, and still land on the clean run's params
        (≤1e-5)."""
        import jax
        import numpy as np
        from kubeflow_tpu.cluster.chaos import (NaNInjector, SentinelSoak,
                                                final_params)

        injected = SentinelSoak(
            workdir=str(tmp_path / "injected"),
            fault=NaNInjector(at_step=5),
            total_steps=10, checkpoint_every=2,
            corrupt_lkg=True).run()
        assert injected["outcome"] == "succeeded", injected
        assert injected["lkg_corrupted"] is True
        assert len(injected["anomalies"]) == 1
        assert injected["rollbacks"] == 1
        clean = SentinelSoak(workdir=str(tmp_path / "clean"),
                             total_steps=10, checkpoint_every=2).run()
        assert clean["outcome"] == "succeeded", clean
        deltas = jax.tree.map(
            lambda a, b: float(np.max(np.abs(np.asarray(a) -
                                             np.asarray(b)))),
            final_params(injected["checkpoint_dir"]),
            final_params(clean["checkpoint_dir"]))
        assert max(jax.tree.leaves(deltas), default=0.0) <= 1e-5
