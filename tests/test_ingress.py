"""Ingress/auth data plane: IAP JWT verification and basic-auth ext-authz
routing, end-to-end through real HTTP servers to the echo backend — the
E2E shape of the reference's iap-ingress/basic-auth-ingress prototypes
(kubeflow/gcp/prototypes/iap-ingress.jsonnet,
kubeflow/common/ambassador.libsonnet:149-176)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.manifests import build_component
from kubeflow_tpu.support.echo_server import EchoServer
from kubeflow_tpu.webapps.gatekeeper import Gatekeeper, GatekeeperServer
from kubeflow_tpu.webapps.ingress import (AuthIngress,
                                          IAP_EMAIL_HEADER, IAP_JWT_HEADER,
                                          JwtError, JwtVerifier, Route,
                                          build_ext_authz_ingress,
                                          jwt_encode, jwt_verify)

KEY = "cluster-secret"


class TestJwt:
    def test_roundtrip(self):
        token = jwt_encode({"email": "a@b.c", "aud": "aud1",
                            "iss": "https://cloud.google.com/iap"}, KEY)
        claims = jwt_verify(token, KEY, audience="aud1",
                            issuer="https://cloud.google.com/iap")
        assert claims["email"] == "a@b.c"

    def test_bad_signature(self):
        token = jwt_encode({"email": "a@b.c"}, KEY)
        with pytest.raises(JwtError, match="signature"):
            jwt_verify(token, "other-key")

    def test_tampered_payload(self):
        token = jwt_encode({"email": "a@b.c"}, KEY)
        h, p, s = token.split(".")
        other = jwt_encode({"email": "evil@b.c"}, KEY).split(".")[1]
        with pytest.raises(JwtError):
            jwt_verify(f"{h}.{other}.{s}", KEY)

    def test_expired(self):
        token = jwt_encode({"exp": 1000.0}, KEY)
        with pytest.raises(JwtError, match="expired"):
            jwt_verify(token, KEY, now=lambda: 2000.0)

    def test_audience_mismatch(self):
        token = jwt_encode({"aud": "x"}, KEY)
        with pytest.raises(JwtError, match="audience"):
            jwt_verify(token, KEY, audience="y")

    def test_unsupported_alg_rejected(self):
        # alg:none downgrade must not pass
        import base64
        header = base64.urlsafe_b64encode(
            json.dumps({"alg": "none"}).encode()).rstrip(b"=").decode()
        payload = jwt_encode({"email": "a@b.c"}, KEY).split(".")[1]
        with pytest.raises(JwtError, match="alg"):
            jwt_verify(f"{header}.{payload}.", KEY)


def _get(url, headers=None):
    req = urllib.request.Request(url)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.fixture
def echo():
    server = EchoServer()
    server.start()
    yield server
    server.stop()


class TestIapIngress:
    @pytest.fixture
    def ingress(self, echo):
        ing = AuthIngress(
            JwtVerifier(key=KEY, audience="backend-1",
                        issuer="https://cloud.google.com/iap"),
            [Route("/", f"127.0.0.1:{echo.port}")])
        ing.start()
        yield ing
        ing.stop()

    def test_no_token_401(self, ingress):
        status, body, _ = _get(f"http://127.0.0.1:{ingress.port}/app")
        assert status == 401
        assert "missing" in json.loads(body)["error"]

    def test_bad_token_401(self, ingress):
        token = jwt_encode({"aud": "backend-1"}, "wrong-key")
        status, _, _ = _get(f"http://127.0.0.1:{ingress.port}/app",
                            {IAP_JWT_HEADER: token})
        assert status == 401

    def test_valid_token_routes_with_identity(self, ingress):
        token = jwt_encode({"email": "user@example.com", "aud": "backend-1",
                            "iss": "https://cloud.google.com/iap"}, KEY)
        status, body, _ = _get(
            f"http://127.0.0.1:{ingress.port}/app/x?q=1",
            {IAP_JWT_HEADER: token})
        assert status == 200
        seen = json.loads(body)
        assert seen["path"] == "/app/x?q=1"
        # identity header injected IAP-style; assertion stripped
        headers = {k.lower(): v for k, v in seen["headers"].items()}
        assert headers[IAP_EMAIL_HEADER] == \
            "accounts.google.com:user@example.com"
        assert IAP_JWT_HEADER not in headers

    def test_garbage_token_clean_401(self, ingress):
        # malformed base64/JSON segments must be a clean 401, not a crash
        for bad in ("!!!.x.y", "a.b", "e30.e30.", "AAA.AAA.AAA"):
            status, _, _ = _get(f"http://127.0.0.1:{ingress.port}/app",
                                {IAP_JWT_HEADER: bad})
            assert status == 401, bad

    def test_client_identity_header_stripped(self, ingress):
        # a client-supplied identity header must never reach the upstream
        token = jwt_encode({"email": "real@example.com", "aud": "backend-1",
                            "iss": "https://cloud.google.com/iap"}, KEY)
        status, body, _ = _get(
            f"http://127.0.0.1:{ingress.port}/app",
            {IAP_JWT_HEADER: token,
             IAP_EMAIL_HEADER: "accounts.google.com:evil@example.com"})
        assert status == 200
        headers = {k.lower(): v for k, v in
                   json.loads(body)["headers"].items()}
        assert headers[IAP_EMAIL_HEADER] == \
            "accounts.google.com:real@example.com"

    def test_denied_post_does_not_poison_keepalive(self, ingress, echo):
        # an unread POST body on a persistent connection must not be
        # parsed as the next request
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", ingress.port,
                                          timeout=10)
        conn.request("POST", "/app", body=b"x" * 100)  # no token → 401
        assert conn.getresponse().read() is not None
        token = jwt_encode({"email": "u@e.c", "aud": "backend-1",
                            "iss": "https://cloud.google.com/iap"}, KEY)
        conn.request("GET", "/app", headers={IAP_JWT_HEADER: token})
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["path"] == "/app"
        conn.close()

    def test_wrong_audience_401(self, ingress):
        token = jwt_encode({"email": "u@e.c", "aud": "other",
                            "iss": "https://cloud.google.com/iap"}, KEY)
        status, _, _ = _get(f"http://127.0.0.1:{ingress.port}/app",
                            {IAP_JWT_HEADER: token})
        assert status == 401


class TestBasicAuthIngress:
    @pytest.fixture
    def gate(self):
        server = GatekeeperServer(
            Gatekeeper(username="admin", password="pw"))
        server.start()
        yield server
        server.stop()

    @pytest.fixture
    def ingress(self, echo, gate):
        # the production ext-authz wiring (what main() builds from the
        # mounted ConfigMap): login/logout proxy to the gatekeeper, public
        ing = build_ext_authz_ingress(
            {"upstream": f"127.0.0.1:{echo.port}",
             "auth_url": f"http://127.0.0.1:{gate.port}/auth"})
        ing.start()
        yield ing
        ing.stop()

    def test_unauthenticated_redirects_to_login(self, ingress):
        req = urllib.request.Request(f"http://127.0.0.1:{ingress.port}/app")
        opener = urllib.request.build_opener(_NoRedirect)
        try:
            resp = opener.open(req, timeout=10)
            status, headers = resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            status, headers = e.code, dict(e.headers)
        assert status == 302
        # original destination rides along so login can send the browser back
        assert headers["Location"] == "/login?rd=%2Fapp"

    def test_basic_header_routes(self, ingress):
        import base64
        cred = base64.b64encode(b"admin:pw").decode()
        status, body, _ = _get(f"http://127.0.0.1:{ingress.port}/app",
                               {"Authorization": f"Basic {cred}"})
        assert status == 200
        assert json.loads(body)["path"] == "/app"

    def test_login_cookie_flow(self, ingress, gate):
        # login at the gatekeeper, then present the session cookie at the
        # ingress — the full browser flow
        data = b"username=admin&password=pw"
        req = urllib.request.Request(
            f"http://127.0.0.1:{gate.port}/login", data=data, method="POST")
        req.add_header("Content-Type", "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=10) as resp:
            cookie = resp.headers["Set-Cookie"].split(";")[0]
        status, body, _ = _get(f"http://127.0.0.1:{ingress.port}/app",
                               {"Cookie": cookie})
        assert status == 200
        assert json.loads(body)["path"] == "/app"

    def test_full_browser_flow_through_ingress(self, ingress):
        """Every hop rides the ingress itself: 302 to login, login page
        served (public path → gatekeeper route), form POST sets the
        session cookie and 303s back, original page loads."""
        base = f"http://127.0.0.1:{ingress.port}"

        class NoRedirect(urllib.request.HTTPErrorProcessor):
            def http_response(self, request, response):
                return response
        opener = urllib.request.build_opener(NoRedirect)
        # 1. protected page → redirect carrying the destination
        with opener.open(f"{base}/app", timeout=10) as resp:
            assert resp.status == 302
            loc = resp.headers["Location"]
        assert loc == "/login?rd=%2Fapp"
        # 2. the login page is reachable THROUGH the ingress (no auth loop)
        with opener.open(base + loc, timeout=10) as resp:
            assert resp.status == 200
            page = resp.read().decode()
        assert 'value="/app"' in page
        # 3. posting the form through the ingress logs in and redirects back
        req = urllib.request.Request(
            f"{base}/login", data=b"username=admin&password=pw&rd=%2Fapp")
        with opener.open(req, timeout=10) as resp:
            assert resp.status == 303
            assert resp.headers["Location"] == "/app"
            cookie = resp.headers["Set-Cookie"].split(";")[0]
        # 4. the destination now loads with the session cookie
        status, body, _ = _get(f"{base}/app", {"Cookie": cookie})
        assert status == 200
        assert json.loads(body)["path"] == "/app"

    def test_logout_reachable_and_revokes(self, ingress):
        import base64
        cred = base64.b64encode(b"admin:pw").decode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{ingress.port}/login", data=b"",
            headers={"Authorization": f"Basic {cred}"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            cookie = resp.headers["Set-Cookie"].split(";")[0]
        _get(f"http://127.0.0.1:{ingress.port}/logout", {"Cookie": cookie})
        req = urllib.request.Request(f"http://127.0.0.1:{ingress.port}/app",
                                     headers={"Cookie": cookie})
        opener = urllib.request.build_opener(_NoRedirect)
        try:
            status = opener.open(req, timeout=10).status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 302  # session gone → back to login

    def test_bad_credentials_denied(self, ingress):
        import base64
        cred = base64.b64encode(b"admin:nope").decode()
        req = urllib.request.Request(f"http://127.0.0.1:{ingress.port}/app")
        req.add_header("Authorization", f"Basic {cred}")
        opener = urllib.request.build_opener(_NoRedirect)
        try:
            resp = opener.open(req, timeout=10)
            status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 302  # back to login


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *args, **kwargs):
        return None


class TestGcpManifests:
    def test_iap_ingress_component(self):
        objs = build_component("iap-ingress", {"audience": "aud-xyz"})
        kinds = [o["kind"] for o in objs]
        assert "Ingress" in kinds and "BackendConfig" in kinds
        cm = next(o for o in objs if o["kind"] == "ConfigMap")
        assert cm["data"]["audience"] == "aud-xyz"
        ing = next(o for o in objs if o["kind"] == "Ingress")
        assert "kubernetes.io/ingress.global-static-ip-name" in \
            ing["metadata"]["annotations"]

    def test_basic_auth_ingress_component(self):
        objs = build_component("basic-auth-ingress")
        cm = next(o for o in objs if o["kind"] == "ConfigMap")
        assert cm["data"]["auth_url"].endswith("/auth")

    def test_cert_manager_component(self):
        objs = build_component("cert-manager", {"acme_email": "a@b.c"})
        kinds = [o["kind"] for o in objs]
        assert kinds.count("CustomResourceDefinition") == 3
        issuers = [o for o in objs if o["kind"] == "ClusterIssuer"]
        assert {i["metadata"]["name"] for i in issuers} == \
            {"kubeflow-self-signing-issuer", "letsencrypt-prod"}

    def test_cloud_endpoints_and_filestore(self):
        assert any(o["kind"] == "CustomResourceDefinition"
                   for o in build_component("cloud-endpoints"))
        objs = build_component("gcp-filestore", {"server_ip": "10.1.2.3"})
        pv = next(o for o in objs if o["kind"] == "PersistentVolume")
        assert pv["spec"]["nfs"]["server"] == "10.1.2.3"
