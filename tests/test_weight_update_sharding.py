"""Cross-replica sharded weight update (ZeRO-2, Xu et al.): numerics
parity with the replicated path, the compiled collectives (reduce-scatter
+ all-gather, NO full-gradient all-reduce), checkpoint portability across
a mode switch, and the knob's plumbing through the operator surface.

Runs on the conftest 8-device virtual CPU mesh
(--xla_force_host_platform_device_count=8)."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # bench.py lives at the repo root

from bench import estimate_weight_update_hbm  # noqa: E402
# canonical home since ISSUE 13 (bench re-exports for compatibility)
from kubeflow_tpu.obs.collectives import collective_counts  # noqa: E402
from kubeflow_tpu.api.trainingjob import ShardingSpec  # noqa: E402
from kubeflow_tpu.parallel.mesh import (build_mesh, replica_axes,  # noqa: E402
                                        replica_degree)
from kubeflow_tpu.runtime.trainstep import TrainStepBuilder  # noqa: E402

# clip LOW enough that global-norm clipping actively rescales every
# step: the regime where a shard-LOCAL norm (the bug class the explicit
# path must not have) would visibly diverge from the replicated path
OPT = lambda: optax.chain(optax.clip_by_global_norm(0.01),  # noqa: E731
                          optax.sgd(0.1, momentum=0.9))


def _linear_spec(din=16, dout=8):
    def init_fn(rng):
        params = {"w": jax.random.normal(rng, (din, dout)) * 3.0,
                  "b": jnp.zeros((dout,))}
        return params, {}

    def loss_fn(params, variables, batch, rng):
        y = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((y - batch["y"]) ** 2), {}

    rs = np.random.RandomState(0)
    batch = {"x": rs.randn(32, din).astype(np.float32),
             "y": rs.randn(32, dout).astype(np.float32)}
    return init_fn, loss_fn, batch


def _run(builder, init_fn, batch, steps=5):
    state = builder.init(init_fn, jax.random.PRNGKey(0))
    step = builder.build()
    placed = builder.place_batch(batch)
    losses = []
    for _ in range(steps):
        state, m = step(state, placed)
        losses.append(float(m["loss"]))
    return state, losses


class TestParity:
    def test_sharded_matches_replicated_losses(self):
        init_fn, loss_fn, batch = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        runs = {}
        for mode in ("replicated", "sharded"):
            b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                                 optimizer=OPT(), weight_update=mode)
            _, runs[mode] = _run(b, init_fn, batch, steps=5)
        np.testing.assert_allclose(runs["replicated"], runs["sharded"],
                                   rtol=0, atol=1e-5)

    def test_batch_stats_model_falls_back_to_gspmd_and_matches(self):
        """A model with mutable batch statistics (BatchNorm-style) must
        NOT take the explicit shard_map path — under it the stats would
        be per-replica where the replicated path computes them over the
        global batch. The strategy falls back to GSPMD and numerics
        match."""
        def init_fn(rng):
            params = {"w": jax.random.normal(rng, (16, 8))}
            return params, {"stat": jnp.zeros((8,))}

        def loss_fn(params, variables, batch, rng):
            y = batch["x"] @ params["w"]
            # batch-mean statistic, EMA'd into the mutable variables —
            # its value depends on WHICH batch the stat sees
            stat = 0.9 * variables["stat"] + 0.1 * jnp.mean(y, axis=0)
            loss = jnp.mean((y - batch["y"] + stat) ** 2)
            return loss, {"variables": {"stat": stat}}

        rs = np.random.RandomState(1)
        batch = {"x": rs.randn(32, 16).astype(np.float32),
                 "y": rs.randn(32, 8).astype(np.float32)}
        mesh = build_mesh(ShardingSpec(data=8))
        runs = {}
        for mode in ("replicated", "sharded"):
            b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                                 optimizer=OPT(), weight_update=mode)
            state = b.init(init_fn, jax.random.PRNGKey(0))
            if mode == "sharded":
                assert b.update_strategy(state.variables) == "zero2-gspmd"
                assert b.update_strategy() == "zero2-explicit"
            step = b.build()
            placed = b.place_batch(batch)
            losses = []
            for _ in range(3):
                state, m = step(state, placed)
                losses.append(float(m["loss"]))
            runs[mode] = losses
        np.testing.assert_allclose(runs["replicated"], runs["sharded"],
                                   rtol=0, atol=1e-5)

    def test_gspmd_strategy_parity_on_mixed_mesh(self):
        """Rules-sharded params on a dp x tp mesh take the GSPMD strategy
        (with_sharding_constraint) — numerics must match too."""
        from kubeflow_tpu.models import transformer as T
        spec = T.workload_spec(cfg=T.TransformerConfig.tiny(), seq_len=32)
        mesh = build_mesh(ShardingSpec(data=4, tensor=2))
        runs = {}
        for mode in ("replicated", "sharded"):
            b = TrainStepBuilder(
                mesh=mesh, loss_fn=spec.loss_fn, optimizer=OPT(),
                rules=spec.rules,
                param_logical_axes=spec.param_logical_axes,
                weight_update=mode)
            assert b.update_strategy() == \
                ("zero2-gspmd" if mode == "sharded" else "replicated")
            state = b.init(spec.init_fn, jax.random.PRNGKey(0))
            step = b.build()
            batch = b.place_batch(spec.batch_fn(jax.random.PRNGKey(1), 8))
            losses = []
            for _ in range(3):
                state, m = step(state, batch)
                losses.append(float(m["loss"]))
            runs[mode] = losses
        np.testing.assert_allclose(runs["replicated"], runs["sharded"],
                                   rtol=0, atol=1e-5)


class TestCompiledCollectives:
    def test_sharded_step_reduce_scatters_no_full_allreduce(self):
        init_fn, loss_fn, batch = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT(),
                             weight_update="sharded")
        assert b.update_strategy() == "zero2-explicit"
        state = b.init(init_fn, jax.random.PRNGKey(0))
        placed = b.place_batch(batch)
        hlo = b.build().lower(state, placed).compile().as_text()
        counts = collective_counts(hlo)
        assert counts["reduce_scatter"] > 0, counts
        assert counts["all_gather"] > 0, counts
        # the only all-reduces left are scalars (loss mean, global norms)
        assert counts["all_reduce_nonscalar"] == 0, counts

    def test_replicated_step_has_no_reduce_scatter(self):
        init_fn, loss_fn, batch = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT())
        state = b.init(init_fn, jax.random.PRNGKey(0))
        placed = b.place_batch(batch)
        hlo = b.build().lower(state, placed).compile().as_text()
        assert collective_counts(hlo)["reduce_scatter"] == 0

    def test_optimizer_state_is_sharded_over_replicas(self):
        """The point of the exercise: each replica materializes 1/N of
        the momentum buffer instead of all of it."""
        init_fn, loss_fn, batch = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT(),
                             weight_update="sharded")
        state = b.init(init_fn, jax.random.PRNGKey(0))
        mom = [l for l in jax.tree.leaves(state.opt_state)
               if getattr(l, "shape", None) == (16, 8)]
        assert mom, "momentum buffer not found"
        shard_shapes = {s.data.shape for s in mom[0].addressable_shards}
        assert shard_shapes == {(2, 8)}, shard_shapes   # 16/8 rows each


@pytest.mark.slow
class TestCheckpointModeSwitch:
    def test_roundtrip_across_mode_switch(self, tmp_path):
        """Save under the sharded update, restore into a replicated
        builder (and continue): steps 3-4 must match an uninterrupted
        replicated run — the checkpoint is layout-free."""
        pytest.importorskip("orbax.checkpoint")
        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        init_fn, loss_fn, batch = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))

        ref = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT())
        _, ref_losses = _run(ref, init_fn, batch, steps=4)

        b1 = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT(),
                              weight_update="sharded")
        state = b1.init(init_fn, jax.random.PRNGKey(0))
        step1 = b1.build()
        placed = b1.place_batch(batch)
        for _ in range(2):
            state, _ = step1(state, placed)
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(2, state, force=True)
        mgr.wait()

        b2 = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT())
        template = b2.init(init_fn, jax.random.PRNGKey(0))
        restored = mgr.restore(template)
        mgr.close()
        assert int(restored.step) == 2
        step2 = b2.build()
        losses = []
        for _ in range(2):
            restored, m = step2(restored, b2.place_batch(batch))
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(losses, ref_losses[2:], rtol=0,
                                   atol=1e-5)


class TestPlumbing:
    def test_invalid_mode_rejected_at_builder(self):
        init_fn, loss_fn, _ = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))
        with pytest.raises(ValueError, match="weight_update"):
            TrainStepBuilder(mesh=mesh, loss_fn=loss_fn, optimizer=OPT(),
                             weight_update="zero9")

    def test_replica_axes_and_degree(self):
        mesh = build_mesh(ShardingSpec(data=4, fsdp=2))
        assert replica_axes(mesh) == ("data", "fsdp")
        assert replica_degree(mesh) == 8
        mesh1 = build_mesh(ShardingSpec(data=1, tensor=8))
        assert replica_axes(mesh1) == ()
        assert replica_degree(mesh1) == 1

    def test_weight_update_spec_per_leaf_rules(self):
        from jax.sharding import PartitionSpec as P
        from kubeflow_tpu.parallel.sharding_rules import weight_update_spec
        mesh = build_mesh(ShardingSpec(data=8))
        axes = ("data",)
        # leading dividable dim gets the axis
        assert weight_update_spec(P(), (16, 8), mesh, axes) == \
            P("data", None)
        # first dim odd → second dim wins
        assert weight_update_spec(P(), (3, 16), mesh, axes) == \
            P(None, "data")
        # nothing dividable → None (caller keeps the param sharding)
        assert weight_update_spec(P(), (3, 5), mesh, axes) is None
        assert weight_update_spec(P(), (), mesh, axes) is None
        # an axis already consumed by the param sharding is skipped
        assert weight_update_spec(P("data"), (16, 8), mesh, axes) is None

    def test_compat_legacy_shard_map_matches_modern(self, monkeypatch):
        """The compat shim's legacy branch (jax.experimental.shard_map +
        check_rep) is load-bearing for trainstep/ring_attention/pipeline
        on older jax — exercise it by forcing the flag and asserting a
        sharded-update train step matches the modern branch exactly."""
        from kubeflow_tpu.parallel import compat
        init_fn, loss_fn, batch = _linear_spec()
        mesh = build_mesh(ShardingSpec(data=8))

        def one_step():
            b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                                 optimizer=OPT(), weight_update="sharded")
            assert b.update_strategy() == "zero2-explicit"
            _, losses = _run(b, init_fn, batch, steps=2)
            return losses

        modern = one_step()
        monkeypatch.setattr(compat, "_FORCE_LEGACY", True)
        legacy = one_step()
        np.testing.assert_allclose(modern, legacy, rtol=0, atol=0)

    def test_hbm_estimate_is_one_over_n(self):
        est = estimate_weight_update_hbm(100, 100, 8)
        # f32 reads g+p+state, writes p+state: 4*(3P+2S)
        assert est["full_bytes_per_chip"] == 4 * (3 * 100 + 2 * 100)
        assert est["sharded_bytes_per_chip"] == \
            -(-est["full_bytes_per_chip"] // 8)
        assert est["replicas"] == 8

    def test_spec_field_renders_worker_env(self):
        """spec.weightUpdate → KFTPU_WEIGHT_UPDATE on every replica pod
        (the operator_knob contract tests/test_lint.py enforces)."""
        from kubeflow_tpu.api.trainingjob import TrainingJob
        from kubeflow_tpu.cluster import FakeCluster
        from kubeflow_tpu.controllers.runtime import Manager
        from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
        manifest = {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "wu-job", "namespace": "kubeflow"},
            "spec": {
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "sharding": {"data": -1},
                "weightUpdate": "sharded",
            },
        }
        job = TrainingJob.from_manifest(manifest)
        assert job.weight_update == "sharded"
        assert job.to_manifest()["spec"]["weightUpdate"] == "sharded"
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(manifest)
        mgr.run_pending()
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert pods
        for pod in pods:
            envs = {e["name"]: e.get("value")
                    for c in pod["spec"]["containers"]
                    for e in c.get("env", [])}
            assert envs.get("KFTPU_WEIGHT_UPDATE") == "sharded"

    def test_bad_spec_value_rejected_at_admission(self):
        from kubeflow_tpu.api.trainingjob import TrainingJob
        manifest = {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "wu-bad", "namespace": "default"},
            "spec": {
                "replicaSpecs": {"TPU": {"tpuTopology": "v5e-8",
                                         "template": {}}},
                "sharding": {"data": -1},
                "weightUpdate": "sideways",
            },
        }
        with pytest.raises(ValueError, match="weight_update"):
            TrainingJob.from_manifest(manifest)
