"""Continuous (in-flight) batching scenarios (ISSUE 18): the batcher's
admission semantics, the Retry-After shed hint, and the admission-time
queue-gauge contract. All jax-free: a duck-typed servable with
scriptable blocking stands in for the model, so the tier runs in the
control-plane smoke lane."""

import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.serving.batcher import MicroBatcher, QueueFullError

pytestmark = pytest.mark.serving_batch


class _BlockingServable:
    """Echo servable whose predict blocks until released — freezes the
    dispatch loop mid-flight so tests can observe queue state while the
    device is 'busy'."""

    name = "blk"

    def __init__(self, hold: bool = False):
        self._gate = threading.Event()
        if not hold:
            self._gate.set()
        self.calls = []          # list of row-counts per dispatch

    def release(self):
        self._gate.set()

    def hold(self):
        self._gate.clear()

    def predict(self, batch):
        self._gate.wait(timeout=30.0)
        self.calls.append(batch.shape[0])
        return batch


def _items(n, rows=1):
    return [np.full((rows, 2), float(i), np.float32) for i in range(n)]


def test_batching_mode_is_validated():
    with pytest.raises(ValueError, match="batching"):
        MicroBatcher(_BlockingServable(), batching="sliding")


def test_continuous_is_the_default_mode():
    b = MicroBatcher(_BlockingServable())
    try:
        assert b.batching == "continuous"
    finally:
        b.shutdown()


def test_continuous_backlog_skips_the_window_wait():
    """Under load the batch forms from whatever is queued the moment
    the device frees — the window knob (max_latency_ms, here a huge
    5 s) is IGNORED for backlogged work; only the small idle-device
    coalescing bound (max_wait_ms) ever holds a request, and only an
    idle-start one (the PR 11 knee this mode kills)."""
    s = _BlockingServable(hold=True)
    b = MicroBatcher(s, max_batch=8, max_latency_ms=5_000.0,
                     max_wait_ms=50.0, batching="continuous")
    try:
        head = b.submit(np.zeros((1, 2), np.float32))
        deadline = time.monotonic() + 5.0
        # head-of-line admitted (device 'busy' inside predict)...
        while b.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        # ...then a backlog queues behind it
        futs = [b.submit(x) for x in _items(4)]
        t0 = time.perf_counter()
        s.release()
        head.result(timeout=10.0)
        for f in futs:
            f.result(timeout=10.0)
        elapsed = time.perf_counter() - t0
        # window mode would hold each partial batch to the 5 s edge;
        # continuous drains the whole backlog in well under a second
        assert elapsed < 2.0, f"backlog waited a window edge ({elapsed:.1f}s)"
    finally:
        b.shutdown()


def test_continuous_greedy_refill_batches_the_backlog():
    """Requests queued while the device was busy ride ONE dispatch
    (greedy refill to max_batch), not N serial singletons."""
    s = _BlockingServable(hold=True)
    b = MicroBatcher(s, max_batch=8, max_latency_ms=1.0,
                     batching="continuous")
    try:
        first = b.submit(np.zeros((1, 2), np.float32))
        deadline = time.monotonic() + 5.0
        # wait until the loop has admitted the first item (it left the
        # queue gauges) and is blocked inside predict
        while b.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        futs = [b.submit(x) for x in _items(4)]
        s.release()
        first.result(timeout=10.0)
        for f in futs:
            f.result(timeout=10.0)
        # first dispatch carried the lone head-of-line request; the 4
        # backlogged rows must coalesce into the (one) next dispatch
        assert s.calls[0] == 1
        assert s.calls[1] == 4, f"backlog fragmented: {s.calls}"
    finally:
        b.shutdown()


def test_gauges_drop_at_admission_not_at_dispatch_end():
    """The satellite contract: an admitted request is device backlog,
    not queue backlog — queue_depth/oldest_wait_s must stop counting
    it the moment it is pulled into a forming cohort, even while its
    dispatch is still in flight (the autoscaler would double-count
    otherwise)."""
    s = _BlockingServable(hold=True)
    b = MicroBatcher(s, max_batch=2, max_latency_ms=1.0,
                     batching="continuous")
    try:
        f0 = b.submit(np.zeros((1, 2), np.float32))
        deadline = time.monotonic() + 5.0
        while b.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        # the in-flight request left the gauges at admission
        assert b.queue_depth() == 0
        assert b.oldest_wait_s() == 0.0
        # new arrivals behind the busy device DO count
        f1 = b.submit(np.zeros((1, 2), np.float32))
        f2 = b.submit(np.zeros((1, 2), np.float32))
        assert b.queue_depth() == 2
        assert b.oldest_wait_s() >= 0.0
        s.release()
        for f in (f0, f1, f2):
            f.result(timeout=10.0)
        deadline = time.monotonic() + 5.0
        while b.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert b.queue_depth() == 0
    finally:
        b.shutdown()


def test_queue_full_carries_retry_after_hint():
    s = _BlockingServable(hold=True)
    b = MicroBatcher(s, max_batch=1, max_latency_ms=1.0, max_pending=2,
                     batching="continuous")
    try:
        # head-of-line admitted (blocks in predict), then fill the queue
        b.submit(np.zeros((1, 2), np.float32))
        deadline = time.monotonic() + 5.0
        while b.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        b.submit(np.zeros((1, 2), np.float32))
        b.submit(np.zeros((1, 2), np.float32))
        with pytest.raises(QueueFullError) as ei:
            b.submit(np.zeros((1, 2), np.float32))
        assert 1.0 <= ei.value.retry_after_s <= 30.0
        assert 1.0 <= b.retry_after_s() <= 30.0
    finally:
        s.release()
        b.shutdown()


def test_retry_hint_tracks_drain_rate():
    b = MicroBatcher(_BlockingServable(), max_latency_ms=1.0)
    try:
        # cold batcher (no measured rate): conservative 1 s floor
        assert b._retry_hint(100) == 1.0
        b._drain_rate = 10.0          # 10 req/s measured
        assert b._retry_hint(5) == 1.0         # clamp floor
        assert b._retry_hint(50) == 5.0        # depth / rate
        assert b._retry_hint(100000) == 30.0   # clamp ceiling
    finally:
        b.shutdown()


def test_drain_rate_ewma_updates_after_dispatch():
    s = _BlockingServable()
    b = MicroBatcher(s, max_latency_ms=1.0, batching="continuous")
    try:
        b.predict(np.zeros((1, 2), np.float32), timeout=10.0)
        assert b._drain_rate > 0.0
    finally:
        b.shutdown()


def test_single_request_determinism_across_modes():
    """A lone request's result must be identical whichever scheduler
    formed the (one-item) cohort — batch determinism for
    single-request traffic."""
    class _Echo:
        name = "echo"

        def predict(self, batch):
            return batch * 2.0

    x = np.arange(6, dtype=np.float32).reshape(3, 2)
    outs = {}
    for mode in MicroBatcher.BATCHING_MODES:
        b = MicroBatcher(_Echo(), max_batch=8, max_latency_ms=1.0,
                         batching=mode)
        try:
            outs[mode] = b.predict(x, timeout=10.0)
        finally:
            b.shutdown()
    np.testing.assert_array_equal(outs["continuous"], outs["window"])
    np.testing.assert_array_equal(outs["continuous"], x * 2.0)


def test_queue_stage_sealed_at_one_cohort_instant():
    """Ledger exactness: every cohort member's ``queue`` stage ends at
    the shared seal instant (enqueue → admission-to-cohort), so the
    per-request ledger partitions wall-clock with no unattributed gap
    between pull time and dispatch start."""
    class _Ctx:
        def __init__(self):
            self.stages = []

        def stage(self, name, start, end, **kw):
            self.stages.append((name, start, end))

        def note(self, **kw):
            pass

        def device(self, *a, **kw):
            pass

    s = _BlockingServable(hold=True)
    b = MicroBatcher(s, max_batch=8, max_latency_ms=1.0,
                     batching="continuous")
    try:
        b.submit(np.zeros((1, 2), np.float32))
        deadline = time.monotonic() + 5.0
        while b.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        ctxs = [_Ctx(), _Ctx()]
        futs = [b.submit(np.zeros((1, 2), np.float32), ctx=c)
                for c in ctxs]
        s.release()
        for f in futs:
            f.result(timeout=10.0)
        ends = []
        for c in ctxs:
            queue_stages = [st for st in c.stages if st[0] == "queue"]
            assert len(queue_stages) == 1
            ends.append(queue_stages[0][2])
        # both co-riders sealed at the SAME instant
        assert ends[0] == ends[1]
    finally:
        b.shutdown()


def test_window_mode_still_honors_the_window():
    """The PR 11 baseline stays selectable: in window mode a partial
    batch holds for the latency window (the A/B's fixed-window arm)."""
    s = _BlockingServable()
    b = MicroBatcher(s, max_batch=8, max_latency_ms=150.0,
                     batching="window")
    try:
        t0 = time.perf_counter()
        b.predict(np.zeros((1, 2), np.float32), timeout=10.0)
        elapsed = time.perf_counter() - t0
        assert elapsed >= 0.10, (
            f"window mode dispatched a partial batch early ({elapsed:.3f}s)")
    finally:
        b.shutdown()


def test_continuous_drain_flushes_and_fails_stragglers_fast():
    """Graceful drain under continuous admission: the queued cohort
    flushes through the device, and anything still queued past the
    deadline fails FAST with BatcherClosedError — zero hangs."""
    s = _BlockingServable()
    b = MicroBatcher(s, max_batch=8, max_latency_ms=1.0,
                     batching="continuous")
    try:
        futs = [b.submit(x) for x in _items(3)]
        report = b.drain(timeout_s=5.0)
        for f in futs:
            f.result(timeout=1.0)  # flushed, not dropped
        assert report["failed"] == 0
        from kubeflow_tpu.serving.batcher import BatcherClosedError
        with pytest.raises(BatcherClosedError):
            b.submit(np.zeros((1, 2), np.float32))
    finally:
        b.shutdown()
