"""Data pipeline tests: native C++ core vs pure-Python reference.

The native core's contract is "identical record order per seed" with the
Python implementation — the executable-spec pattern (SURVEY.md §4's fake
backend tier applied to the input pipeline)."""

import numpy as np
import pytest

from kubeflow_tpu.data import (NativeRecordPipeline, PyRecordPipeline,
                               RecordPipeline, epoch_order, native_available)


RECORD = 64


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    """3 shard files, 50 records each, record i = byte pattern of i."""
    root = tmp_path_factory.mktemp("shards")
    paths = []
    idx = 0
    for s in range(3):
        p = root / f"shard-{s}.bin"
        chunks = []
        for _ in range(50):
            rec = np.full((RECORD,), idx % 251, np.uint8)
            rec[:8] = np.frombuffer(np.int64(idx).tobytes(), np.uint8)
            chunks.append(rec)
            idx += 1
        p.write_bytes(b"".join(c.tobytes() for c in chunks))
        paths.append(str(p))
    return paths


def record_ids(batches):
    out = []
    for b in batches:
        for row in b:
            out.append(int(np.frombuffer(row[:8].tobytes(), np.int64)[0]))
    return out


class TestEpochOrder:
    def test_is_permutation_and_seed_dependent(self):
        o1 = epoch_order(100, seed=7)
        o2 = epoch_order(100, seed=7)
        o3 = epoch_order(100, seed=8)
        assert sorted(o1.tolist()) == list(range(100))
        assert o1.tolist() == o2.tolist()
        assert o1.tolist() != o3.tolist()


class TestPyPipeline:
    def test_reads_all_records_shuffled(self, shards):
        with PyRecordPipeline(shards, RECORD, batch_records=10,
                              seed=3) as pipe:
            assert pipe.total_records == 150
            assert pipe.num_batches == 15
            batches = list(pipe)
        ids = record_ids(batches)
        assert sorted(ids) == list(range(150))
        assert ids != list(range(150))  # actually shuffled
        assert ids == epoch_order(150, 3).tolist()  # in delivery order

    def test_drop_remainder_false_keeps_tail(self, shards):
        with PyRecordPipeline(shards, RECORD, batch_records=40, seed=0,
                              drop_remainder=False) as pipe:
            batches = list(pipe)
        assert [len(b) for b in batches] == [40, 40, 40, 30]

    def test_reset_reshuffles(self, shards):
        with PyRecordPipeline(shards, RECORD, batch_records=150,
                              seed=1) as pipe:
            first = record_ids(list(pipe))
            pipe.reset(seed=2)
            second = record_ids(list(pipe))
        assert sorted(first) == sorted(second)
        assert first != second

    def test_bad_args_rejected(self, shards):
        with pytest.raises(ValueError):
            PyRecordPipeline(shards, 0, 10)
        with pytest.raises(ValueError):
            PyRecordPipeline([], RECORD, 10)


@pytest.mark.skipif(not native_available(),
                    reason="native toolchain unavailable")
class TestNativePipeline:
    def test_matches_python_reference_exactly(self, shards):
        with PyRecordPipeline(shards, RECORD, batch_records=16,
                              seed=11) as py:
            py_ids = record_ids(list(py))
        with NativeRecordPipeline(shards, RECORD, batch_records=16,
                                  seed=11, num_threads=4) as native:
            native_ids = record_ids(list(native))
        assert native_ids == py_ids

    def test_full_epoch_and_reset(self, shards):
        with NativeRecordPipeline(shards, RECORD, batch_records=10,
                                  seed=5, num_threads=3) as pipe:
            assert pipe.total_records == 150
            ids1 = record_ids(list(pipe))
            assert sorted(ids1) == list(range(150))
            pipe.reset(seed=6)
            ids2 = record_ids(list(pipe))
            assert sorted(ids2) == list(range(150))
            assert ids1 != ids2

    def test_byte_payload_integrity(self, shards):
        with NativeRecordPipeline(shards, RECORD, batch_records=25,
                                  seed=9) as pipe:
            for batch in pipe:
                for row in batch:
                    rid = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
                    assert (row[8:] == rid % 251).all()

    def test_concurrency_stress_no_deadlock(self, shards):
        # regression: the slot ring needs a distinct CLAIMED state — with
        # only free/ready, a round-(b+depth) producer could steal the slot
        # a round-b producer had claimed but not yet published, wedging the
        # in-order consumer forever (reproduced with 2 threads, depth 4)
        for trial in range(10):
            for threads in (2, 3, 4):
                with NativeRecordPipeline(
                        shards, RECORD, batch_records=8, seed=trial,
                        queue_depth=4, num_threads=threads) as pipe:
                    total = sum(b.shape[0] for b in pipe)
                assert total == 144  # 18 full batches of 8 (drop remainder)

    def test_missing_file_fails_create(self, tmp_path):
        with pytest.raises(RuntimeError, match="dp_create failed"):
            NativeRecordPipeline([str(tmp_path / "nope.bin")], RECORD, 4)

    def test_factory_prefers_native(self, shards):
        pipe = RecordPipeline(shards, RECORD, 10)
        assert isinstance(pipe, NativeRecordPipeline)
        pipe.close()
