"""Pipelines subsystem: ScheduledWorkflow cron controller, run
persistence, and the pipeline REST API.

Reference parity targets (VERDICT r1 item 6):
pipeline-scheduledworkflow.libsonnet (cron + run history),
pipeline-apiserver.libsonnet (runs recorded and listable over HTTP),
pipeline-persistenceagent.libsonnet (workflow → run DB).
"""

import json
import urllib.request

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.pipelines import (PersistenceAgent, RunStore,
                                    ScheduledWorkflowReconciler,
                                    next_fire_time, parse_cron)
from kubeflow_tpu.pipelines.api_server import PipelineAPIServer
from kubeflow_tpu.workflows.engine import WorkflowReconciler


class FakeClock:
    def __init__(self, t=1_700_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def swf_manifest(name="sched", trigger=None, wf_steps=None, **spec_extra):
    container = {"image": "busybox", "command": ["true"]}
    wf_spec = {
        "entrypoint": "main",
        "templates": [{"name": "main", "container": container}],
    }
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "ScheduledWorkflow",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "trigger": trigger or {"periodicSchedule": {"intervalSecond": 60}},
            "workflow": {"spec": wf_spec},
            **spec_extra,
        },
    }


class TestCron:
    def test_parse_basic(self):
        minutes, hours, dom, months, dow = parse_cron("0 * * * *")
        assert minutes == frozenset({0})
        assert hours == frozenset(range(24))
        assert dow == frozenset(range(7))

    def test_parse_steps_ranges_lists(self):
        minutes, hours, *_ = parse_cron("*/15 9-17 * * 1,3,5")
        assert minutes == frozenset({0, 15, 30, 45})
        assert hours == frozenset(range(9, 18))

    def test_sunday_is_0_and_7(self):
        *_, dow7 = parse_cron("0 0 * * 7")
        *_, dow0 = parse_cron("0 0 * * 0")
        assert dow7 == dow0 == frozenset({0})

    def test_invalid_rejected(self):
        for bad in ("* * * *", "61 * * * *", "* 24 * * *", "*/0 * * * *"):
            with pytest.raises(ValueError):
                parse_cron(bad)

    def test_next_fire_hourly(self):
        # 2023-11-14 22:13:20 UTC → next hourly fire at 23:00:00
        t = next_fire_time("0 * * * *", 1_700_000_000.0)
        assert t == 1_700_002_800.0

    def test_next_fire_strictly_after(self):
        t0 = next_fire_time("* * * * *", 1_700_000_000.0)
        assert t0 > 1_700_000_000.0
        assert next_fire_time("* * * * *", t0) == t0 + 60

    def test_dom_dow_either_matches_when_both_restricted(self):
        # kube-cron: dom=1 OR Sunday, whichever comes first
        t = next_fire_time("0 0 1 * 0", 1_700_000_000.0)  # Tue Nov 14 2023
        import time as _time
        tm = _time.gmtime(t)
        assert tm.tm_mday == 1 or (tm.tm_wday + 1) % 7 == 0


@pytest.fixture
def env():
    cluster = FakeCluster()
    cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
    clock = FakeClock()
    mgr = Manager(cluster)
    mgr.add(ScheduledWorkflowReconciler(clock=clock))
    mgr.add(WorkflowReconciler(clock=clock))
    return cluster, mgr, clock


def drive(cluster, mgr, rounds=3):
    for _ in range(rounds):
        # make timed requeues due NOW: requeue_after delays are held against
        # real time.monotonic, which FakeClock does not advance
        for c in mgr.controllers:
            c._delayed = [(0.0, k) for _, k in c._delayed]
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


class TestScheduledWorkflow:
    def test_fires_on_tick_and_records_run(self, env):
        cluster, mgr, clock = env
        cluster.create(swf_manifest())
        mgr.run_pending()
        # not due yet: next fire anchored at creation + 60
        assert cluster.list("argoproj.io/v1alpha1", "Workflow",
                            "kubeflow") == []
        clock.advance(61)
        drive(cluster, mgr)
        wfs = cluster.list("argoproj.io/v1alpha1", "Workflow", "kubeflow")
        assert len(wfs) == 1
        assert k8s.name_of(wfs[0]) == "sched-1"
        # pod runs to completion → run history records Succeeded
        pod = cluster.list("v1", "Pod", "kubeflow")[0]
        cluster.set_pod_phase("kubeflow", k8s.name_of(pod), "Succeeded")
        drive(cluster, mgr)
        swf = cluster.get("kubeflow.org/v1beta1", "ScheduledWorkflow",
                          "kubeflow", "sched")
        runs = swf["status"]["runs"]
        assert runs[0]["name"] == "sched-1"
        assert runs[0]["phase"] == "Succeeded"

    def test_cron_trigger(self, env):
        cluster, mgr, clock = env
        clock.t = 1_700_000_000.0  # 22:13:20 UTC
        cluster.create(swf_manifest(
            trigger={"cronSchedule": {"cron": "0 * * * *"}}))
        mgr.run_pending()
        swf = cluster.get("kubeflow.org/v1beta1", "ScheduledWorkflow",
                          "kubeflow", "sched")
        assert swf["status"]["nextTriggeredTime"] == 1_700_002_800.0
        clock.t = 1_700_002_801.0
        drive(cluster, mgr)
        assert len(cluster.list("argoproj.io/v1alpha1", "Workflow",
                                "kubeflow")) == 1

    def test_max_concurrency_holds_trigger(self, env):
        cluster, mgr, clock = env
        cluster.create(swf_manifest(maxConcurrency=1))
        mgr.run_pending()  # anchor the schedule before advancing
        clock.advance(61)
        drive(cluster, mgr)
        assert len(cluster.list("argoproj.io/v1alpha1", "Workflow",
                                "kubeflow")) == 1
        # second fire due but first run still active → held
        clock.advance(61)
        drive(cluster, mgr)
        wfs = cluster.list("argoproj.io/v1alpha1", "Workflow", "kubeflow")
        assert len(wfs) == 1
        # finish the run → next reconcile triggers the held run
        pod = cluster.list("v1", "Pod", "kubeflow")[0]
        cluster.set_pod_phase("kubeflow", k8s.name_of(pod), "Succeeded")
        drive(cluster, mgr)
        wfs = cluster.list("argoproj.io/v1alpha1", "Workflow", "kubeflow")
        assert len(wfs) == 2

    def test_disabled_never_fires(self, env):
        cluster, mgr, clock = env
        cluster.create(swf_manifest(enabled=False))
        clock.advance(3600)
        drive(cluster, mgr)
        assert cluster.list("argoproj.io/v1alpha1", "Workflow",
                            "kubeflow") == []

    def test_history_trimmed(self, env):
        cluster, mgr, clock = env
        cluster.create(swf_manifest(maxHistory=2, maxConcurrency=5))
        mgr.run_pending()  # anchor the schedule before advancing
        for _ in range(4):
            clock.advance(61)
            drive(cluster, mgr)
            for pod in cluster.list("v1", "Pod", "kubeflow"):
                if pod.get("status", {}).get("phase") == "Running":
                    cluster.set_pod_phase("kubeflow", k8s.name_of(pod),
                                          "Succeeded")
            drive(cluster, mgr)
        swf = cluster.get("kubeflow.org/v1beta1", "ScheduledWorkflow",
                          "kubeflow", "sched")
        runs = swf["status"]["runs"]
        assert len(runs) == 2  # trimmed to maxHistory
        assert {r["name"] for r in runs} == {"sched-3", "sched-4"}

    def test_delete_cascades_to_workflows(self, env):
        cluster, mgr, clock = env
        cluster.create(swf_manifest())
        mgr.run_pending()  # anchor the schedule before advancing
        clock.advance(61)
        drive(cluster, mgr)
        assert len(cluster.list("argoproj.io/v1alpha1", "Workflow",
                                "kubeflow")) == 1
        cluster.delete("kubeflow.org/v1beta1", "ScheduledWorkflow",
                       "kubeflow", "sched")
        assert cluster.list("argoproj.io/v1alpha1", "Workflow",
                            "kubeflow") == []


class TestDurableStore:
    """r2 verdict #8: run history survives an apiserver restart when the
    store is file-backed (the PVC-mounted sqlite that replaces the
    reference's mysql pod)."""

    def test_runs_survive_apiserver_restart(self, tmp_path):
        import json as _json
        import urllib.request
        from kubeflow_tpu.pipelines.api_server import PipelineAPIServer

        db = str(tmp_path / "runs.db")
        cluster = FakeCluster()
        cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})

        def get(port, path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return _json.loads(r.read())

        def post(port, path, payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=_json.dumps(payload).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return _json.loads(r.read())

        wf_spec = {"entrypoint": "main", "templates": [
            {"name": "main",
             "container": {"image": "busybox", "command": ["true"]}}]}

        server = PipelineAPIServer(cluster, RunStore(db))
        port = server.start()
        post(port, "/apis/v1beta1/pipelines",
             {"name": "p1", "workflow": {"spec": wf_spec}})
        post(port, "/apis/v1beta1/runs",
             {"name": "r1", "pipeline": "p1"})
        # persist the run record the way the agent does
        wf = cluster.get("argoproj.io/v1alpha1", "Workflow", "kubeflow", "r1")
        wf["status"] = {"phase": "Succeeded"}
        server.store.upsert_run(wf, clock=lambda: 123.0)
        assert [r["name"] for r in
                get(port, "/apis/v1beta1/runs")["runs"]] == ["r1"]
        server.stop()
        server.store.close()

        # new process analog: fresh server + fresh RunStore on the same file
        server2 = PipelineAPIServer(cluster, RunStore(db))
        port2 = server2.start()
        try:
            runs = get(port2, "/apis/v1beta1/runs")["runs"]
            assert [r["name"] for r in runs] == ["r1"]
            assert runs[0]["phase"] == "Succeeded"
            pipelines = get(port2, "/apis/v1beta1/pipelines")["pipelines"]
            assert [p["pipeline_id"] for p in pipelines] == ["p1"]
        finally:
            server2.stop()
            server2.store.close()

    def test_storage_manifests(self):
        from kubeflow_tpu.manifests import build_component
        objs = build_component("pipeline-db")
        assert objs[0]["kind"] == "PersistentVolumeClaim"
        kinds = [o["kind"] for o in build_component("minio")]
        assert kinds == ["PersistentVolumeClaim", "Secret", "Deployment",
                         "Service"]
        kinds = [o["kind"] for o in build_component("pipeline-viewercrd")]
        assert "CustomResourceDefinition" in kinds
        # the apiserver + agent mount the shared DB volume
        api = build_component("pipeline-apiserver")
        for dep in (o for o in api if o["kind"] == "Deployment"):
            vols = dep["spec"]["template"]["spec"]["volumes"]
            assert vols[0]["persistentVolumeClaim"]["claimName"] == \
                "ml-pipeline-db"


class TestRunStore:
    def test_upsert_and_terminal_sticky(self):
        store = RunStore()
        clock = FakeClock()
        wf = {"apiVersion": "argoproj.io/v1alpha1", "kind": "Workflow",
              "metadata": {"name": "r1", "namespace": "kubeflow"},
              "status": {"phase": "Running"}}
        store.upsert_run(wf, clock=clock)
        clock.advance(10)
        wf["status"] = {"phase": "Succeeded", "nodes": {"main": {
            "phase": "Succeeded"}}}
        store.upsert_run(wf, clock=clock)
        run = store.get_run("kubeflow/r1")
        assert run["phase"] == "Succeeded"
        assert run["finished_at"] == clock.t
        finished = run["finished_at"]
        clock.advance(10)
        store.upsert_run(wf, clock=clock)  # re-observe: time must not move
        assert store.get_run("kubeflow/r1")["finished_at"] == finished

    def test_list_filters(self):
        store = RunStore()
        for i, phase in enumerate(["Succeeded", "Failed", "Running"]):
            store.upsert_run({
                "apiVersion": "argoproj.io/v1alpha1", "kind": "Workflow",
                "metadata": {"name": f"r{i}", "namespace": "kubeflow",
                             "labels": {
                                 "scheduledworkflows.kubeflow.org/name":
                                     "sched" if i < 2 else ""}},
                "status": {"phase": phase}})
        assert len(store.list_runs(namespace="kubeflow")) == 3
        assert len(store.list_runs(phase="Failed")) == 1
        assert len(store.list_runs(schedule="sched")) == 2

    def test_persistence_agent_survives_workflow_deletion(self, env):
        cluster, mgr, clock = env
        store = RunStore()
        mgr.add(PersistenceAgent(store, clock=clock))
        cluster.create(swf_manifest())
        mgr.run_pending()  # anchor the schedule before advancing
        clock.advance(61)
        drive(cluster, mgr)
        pod = cluster.list("v1", "Pod", "kubeflow")[0]
        cluster.set_pod_phase("kubeflow", k8s.name_of(pod), "Succeeded")
        drive(cluster, mgr)
        cluster.delete("kubeflow.org/v1beta1", "ScheduledWorkflow",
                       "kubeflow", "sched")
        mgr.run_pending()
        run = store.get_run("kubeflow/sched-1")
        assert run is not None and run["phase"] == "Succeeded"


class TestPipelineAPI:
    @pytest.fixture
    def api(self):
        cluster = FakeCluster()
        clock = FakeClock()
        mgr = Manager(cluster)
        mgr.add(ScheduledWorkflowReconciler(clock=clock))
        mgr.add(WorkflowReconciler(clock=clock))
        server = PipelineAPIServer(cluster)
        mgr.add(PersistenceAgent(server.store, clock=clock))
        port = server.start()
        yield cluster, mgr, clock, server, f"http://127.0.0.1:{port}"
        server.stop()

    def _req(self, url, payload=None, method=None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            url, data, {"Content-Type": "application/json"}, method=method)
        try:
            resp = urllib.request.urlopen(req)
            return json.loads(resp.read()), resp.status
        except urllib.error.HTTPError as e:
            return json.loads(e.read()), e.code

    def test_pipeline_upload_run_lifecycle(self, api):
        cluster, mgr, clock, server, base = api
        wf_spec = {"entrypoint": "main", "templates": [
            {"name": "main", "container": {"image": "busybox"}}]}
        out, code = self._req(f"{base}/apis/v1beta1/pipelines",
                              {"name": "bench", "workflow": wf_spec})
        assert code == 200
        out, code = self._req(f"{base}/apis/v1beta1/pipelines")
        assert [p["pipeline_id"] for p in out["pipelines"]] == ["bench"]

        out, code = self._req(f"{base}/apis/v1beta1/runs",
                              {"name": "bench-run-1", "pipeline": "bench"})
        assert code == 200 and out["run_id"] == "kubeflow/bench-run-1"
        drive(cluster, mgr)
        pod = cluster.list("v1", "Pod", "kubeflow")[0]
        cluster.set_pod_phase("kubeflow", k8s.name_of(pod), "Succeeded")
        drive(cluster, mgr)
        out, code = self._req(
            f"{base}/apis/v1beta1/runs/kubeflow/bench-run-1")
        assert code == 200 and out["phase"] == "Succeeded"
        out, _ = self._req(f"{base}/apis/v1beta1/runs?phase=Succeeded")
        assert len(out["runs"]) == 1

    def test_job_lifecycle_over_http(self, api):
        cluster, mgr, clock, server, base = api
        wf_spec = {"entrypoint": "main", "templates": [
            {"name": "main", "container": {"image": "busybox"}}]}
        out, code = self._req(f"{base}/apis/v1beta1/jobs", {
            "name": "nightly", "workflow": wf_spec,
            "trigger": {"periodicSchedule": {"intervalSecond": 60}}})
        assert code == 200
        mgr.run_pending()  # anchor the schedule before advancing
        clock.advance(61)
        drive(cluster, mgr)
        assert len(cluster.list("argoproj.io/v1alpha1", "Workflow",
                                "kubeflow")) == 1
        out, _ = self._req(f"{base}/apis/v1beta1/jobs")
        assert out["jobs"][0]["name"] == "nightly"
        out, code = self._req(
            f"{base}/apis/v1beta1/jobs/kubeflow/nightly:disable", {})
        assert code == 200 and out["enabled"] is False
        swf = cluster.get("kubeflow.org/v1beta1", "ScheduledWorkflow",
                          "kubeflow", "nightly")
        assert swf["spec"]["enabled"] is False
        out, code = self._req(f"{base}/apis/v1beta1/jobs/kubeflow/nightly",
                              method="DELETE")
        assert code == 200
        assert cluster.list("kubeflow.org/v1beta1", "ScheduledWorkflow",
                            "kubeflow") == []

    def test_run_with_inline_workflow_and_params(self, api):
        cluster, mgr, clock, server, base = api
        wf_spec = {"entrypoint": "main", "templates": [
            {"name": "main", "container": {
                "image": "busybox",
                "args": ["$(workflow.parameters.msg)"]}}]}
        out, code = self._req(f"{base}/apis/v1beta1/runs", {
            "name": "inline", "workflow": wf_spec,
            "parameters": [{"name": "msg", "value": "hello"}]})
        assert code == 200
        mgr.run_pending()
        pod = cluster.list("v1", "Pod", "kubeflow")[0]
        assert pod["spec"]["containers"][0]["args"] == ["hello"]

    def test_errors(self, api):
        _, _, _, _, base = api
        out, code = self._req(f"{base}/apis/v1beta1/runs",
                              {"name": "x", "pipeline": "ghost"})
        assert code == 404
        out, code = self._req(f"{base}/apis/v1beta1/runs", {"name": "x"})
        assert code == 400
        out, code = self._req(f"{base}/apis/v1beta1/pipelines/none")
        assert code == 404
