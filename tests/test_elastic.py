"""Elastic gang resizing (ISSUE 8): shrink-to-survive / shrink-to-admit
/ grow-to-fill / defrag resize plans, the operator's binding-shape
adoption, and the cross-replica-degree checkpoint reshape.

Tiers, mirroring the scheduler suite's layering:
- pure-core: SchedulingPolicy bounds, elastic shape enumeration,
  binding_matches envelope semantics, plan() resize decisions over a
  bare inventory;
- control-plane: SliceScheduler + the TPUJob operator over FakeCluster
  (capacity loss → degraded re-bind at fewer chips/pods → grow back,
  the resize-history annotation, the dashboard surface);
- compute: checkpoint save at replica degree N, restore at degree M for
  BOTH weight-update modes with optimizer-state reshape parity ≤ 1e-5,
  run-metadata validation, and resume-from-k data-order correctness;
- soak (slow): the real-training shrink→grow drill (scheduler/soak.py
  ElasticSoak), the bench.py --mode sched acceptance bar.
"""

import json

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.topology import parse_topology
from kubeflow_tpu.api.trainingjob import SchedulingPolicy, TrainingJob
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.scheduler import health
from kubeflow_tpu.scheduler.core import SliceScheduler, plan
from kubeflow_tpu.scheduler.inventory import (Placement, PoolState,
                                              SliceInventory)
from kubeflow_tpu.scheduler.queue import (JobRequest, SchedulerConfig,
                                          binding_matches, binding_of,
                                          elastic_topologies,
                                          resize_history)

pytestmark = pytest.mark.elastic


def req(name, topo="v5e-8", priority=0, preemptible=False, seq=0,
        num_slices=1, queue="default", namespace="default",
        min_chips=None, max_chips=None, grow_ok=True):
    return JobRequest(namespace=namespace, name=name, queue=queue,
                      priority=priority, preemptible=preemptible,
                      topology=parse_topology(topo),
                      num_slices=num_slices, seq=seq,
                      min_chips=min_chips, max_chips=max_chips,
                      grow_ok=grow_ok)


def inventory(*pool_topos):
    return SliceInventory([
        PoolState(f"pool-{i}", parse_topology(t))
        for i, t in enumerate(pool_topos)])


def job_manifest(policy=None, topo="v5e-8", sharding=None):
    spec = {
        "replicaSpecs": {"TPU": {
            "tpuTopology": topo,
            "template": {"spec": {"containers": [{"name": "c"}]}}}},
    }
    if policy is not None:
        spec["schedulingPolicy"] = policy
    if sharding is not None:
        spec["sharding"] = sharding
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "j", "namespace": "ns"}, "spec": spec}


class TestElasticShapes:
    def test_chip_bounds_default_to_nominal(self):
        p = SchedulingPolicy(min_chips=4)
        assert p.chip_bounds(16) == (4, 16)
        assert SchedulingPolicy(max_chips=32).chip_bounds(16) == (16, 32)
        assert SchedulingPolicy().chip_bounds(16) == (16, 16)
        assert not SchedulingPolicy().elastic
        assert SchedulingPolicy(max_chips=32).elastic

    def test_elastic_topologies_walk_supported_sizes(self):
        r = req("a", "v5e-8", min_chips=2, max_chips=32)
        # supported v5e sizes inside [2, 32]: 4, 8, 16, 32 — largest
        # first, nominal included
        assert [t.name for t in elastic_topologies(r)] == \
            ["v5e-32", "v5e-16", "v5e-8", "v5e-4"]
        assert elastic_topologies(req("b", "v5e-8")) == []

    def test_elastic_topologies_scale_per_slice(self):
        r = req("a", "v5e-8", num_slices=2, min_chips=8, max_chips=16)
        # totals (chips x 2 slices) inside [8, 16]: per-slice 4 and 8
        assert [t.name for t in elastic_topologies(r)] == \
            ["v5e-8", "v5e-4"]

    def test_binding_matches_accepts_envelope_shapes_only(self):
        job = TrainingJob.from_manifest(job_manifest(
            {"minChips": 4, "maxChips": 16}))
        ok = Placement(topology="v5e-4", num_slices=1, slices=[])
        assert binding_matches(ok, job)
        assert binding_matches(
            Placement(topology="v5e-8", num_slices=1, slices=[]), job)
        # outside the envelope / wrong slice count / wrong generation
        assert not binding_matches(
            Placement(topology="v5e-32", num_slices=1, slices=[]), job)
        assert not binding_matches(
            Placement(topology="v5e-4", num_slices=2, slices=[]), job)
        assert not binding_matches(
            Placement(topology="v4-8", num_slices=1, slices=[]), job)
        # a fixed-shape job accepts exactly its spec shape
        fixed = TrainingJob.from_manifest(job_manifest({}))
        assert not binding_matches(ok, fixed)

    def test_admission_rejects_unresolvable_envelope_shapes(self):
        # tensor=8 resolves the nominal 8 chips (data=1) but not the
        # 4-chip shrink the envelope admits: rejected at apply, not
        # crash-looped at the scheduler-chosen shape (review fix)
        with pytest.raises(ValueError, match="cannot resolve"):
            TrainingJob.from_manifest(job_manifest(
                {"minChips": 4, "maxChips": 8},
                sharding={"data": -1, "tensor": 8}))
        # the same spec with a tight envelope is fine
        job = TrainingJob.from_manifest(job_manifest(
            {"minChips": 8, "maxChips": 8},
            sharding={"data": -1, "tensor": 8}))
        assert job.scheduling_policy.elastic

    def test_pre_placement_fingerprint_does_not_restart_fleet(self):
        # an annotation written by a pre-defrag operator (no "@rects")
        # must match the new-format fingerprint when the SHAPE part is
        # unchanged — an operator upgrade is not a resize (review fix)
        changed = TrainingJobReconciler._shape_changed
        assert not changed("TPU:v5e-8x1",
                           "TPU:v5e-8x1@pool-a:0.0.2x4")
        assert changed("TPU:v5e-8x1", "TPU:v5e-4x1@pool-a:0.0.1x4")
        assert changed("TPU:v5e-8x1@pool-a:0.0.2x4",
                       "TPU:v5e-8x1@pool-b:0.0.2x4")   # migration
        assert not changed("TPU:v5e-8x1@pool-a:0.0.2x4",
                           "TPU:v5e-8x1@pool-a:0.0.2x4")

    def test_binding_matches_rejects_rects_disagreeing_with_topology(self):
        from kubeflow_tpu.scheduler.inventory import SliceRect
        job = TrainingJob.from_manifest(job_manifest(
            {"minChips": 4, "maxChips": 16}))
        lying = Placement(topology="v5e-4", num_slices=1,
                          slices=[SliceRect("p", 0, 0, 2, 4)])  # 8 chips
        assert not binding_matches(lying, job)


class TestResizePlans:
    def test_shrink_to_admit_replaces_preemption(self):
        # one v5e-16 pool fully held by a LOWER-priority elastic gang;
        # a higher-priority v5e-8 head arrives: the gang shrinks to
        # v5e-8 (keeping its checkpointed progress), the head binds,
        # and NOBODY is preempted to zero
        inv = inventory("v5e-16")
        low = req("low", "v5e-16", priority=0, preemptible=True,
                  min_chips=4, max_chips=16)
        p_low = inv.place_gang(low.topology, 1)
        inv.bind(low.key, p_low)
        head = req("head", "v5e-8", priority=5, seq=1)
        out = plan([head], [(low, p_low)], inv, SchedulerConfig())
        assert [(r.key, p.chips) for r, p, _ in out.resizes] == \
            [("default/low", 8)]
        assert [(r.key, p.chips) for r, p in out.binds] == \
            [("default/head", 8)]
        assert out.preempts == [] and out.waits == {}

    def test_shrink_prefers_lower_priority_victims(self):
        inv = inventory("v5e-16", "v5e-16")
        a = req("a", "v5e-16", priority=3, min_chips=4, max_chips=16)
        b = req("b", "v5e-16", priority=0, min_chips=4, max_chips=16,
                seq=1)
        pa = inv.place_gang(a.topology, 1); inv.bind(a.key, pa)
        pb = inv.place_gang(b.topology, 1); inv.bind(b.key, pb)
        head = req("head", "v5e-8", priority=5, seq=2)
        out = plan([head], [(a, pa), (b, pb)], inv, SchedulerConfig())
        assert [r.key for r, _p, _w in out.resizes] == ["default/b"]

    def test_self_shrink_survives_lost_host(self):
        # v5e-8 pool with one of two hosts down: no nominal rectangle
        # exists anywhere, so the elastic job binds DEGRADED at v5e-4
        # on the surviving host's 1x4 strip instead of starving
        inv = inventory("v5e-8")
        inv.down_cells = set(health.host_cells(
            "pool-0", parse_topology("v5e-8"), 1))
        inv.carve_down()
        j = req("job", "v5e-8", min_chips=4, max_chips=8)
        out = plan([j], [], inv, SchedulerConfig())
        assert [(r.key, p.chips) for r, p in out.binds] == \
            [("default/job", 4)]
        cells = {c for rect in out.binds[0][1].slices
                 for c in rect.cells()}
        assert cells.isdisjoint(inv.down_cells)

    def test_fixed_job_still_waits_on_lost_host(self):
        inv = inventory("v5e-8")
        inv.down_cells = set(health.host_cells(
            "pool-0", parse_topology("v5e-8"), 1))
        inv.carve_down()
        out = plan([req("job", "v5e-8")], [], inv, SchedulerConfig())
        assert out.binds == [] and "default/job" in out.waits

    def test_grow_to_fill_when_queue_empty(self):
        inv = inventory("v5e-32")
        g = req("g", "v5e-8", min_chips=4, max_chips=32)
        p = Placement(topology="v5e-4", num_slices=1,
                      slices=inv.place_gang(parse_topology("v5e-4"),
                                            1).slices)
        inv.bind(g.key, p)
        out = plan([], [(g, p)], inv, SchedulerConfig())
        assert [(r.key, p2.topology) for r, p2, _w in out.resizes] == \
            [("default/g", "v5e-32")]

    def test_grow_is_one_per_pass_and_respects_cooldown(self):
        inv = inventory("v5e-32")
        gangs = []
        for i in range(2):
            r = req(f"g{i}", "v5e-4", seq=i, min_chips=4, max_chips=8)
            p = inv.place_gang(r.topology, 1)
            inv.bind(r.key, p)
            gangs.append((r, p))
        out = plan([], gangs, inv, SchedulerConfig())
        assert len(out.resizes) == 1   # incremental: one restart per pass
        # inside the cooldown nothing grows at all
        cold = [(req(f"g{i}", "v5e-4", seq=i, min_chips=4, max_chips=8,
                     grow_ok=False), p) for i, (_r, p) in enumerate(gangs)]
        inv2 = inventory("v5e-32")
        for r, p in cold:
            inv2.bind(r.key, p)
        assert plan([], cold, inv2, SchedulerConfig()).resizes == []

    def test_grow_respects_quota(self):
        cfg = SchedulerConfig.from_dict({"queues": {"default": {
            "quotaChips": {"*": 8}}}})
        inv = inventory("v5e-32")
        g = req("g", "v5e-8", min_chips=4, max_chips=32)
        p = inv.place_gang(parse_topology("v5e-8"), 1)
        p = Placement(topology="v5e-8", num_slices=1, slices=p.slices)
        inv.bind(g.key, p)
        assert plan([], [(g, p)], inv, cfg).resizes == []

    def test_no_grow_behind_blocked_head(self):
        inv = inventory("v5e-16")
        g = req("g", "v5e-8", min_chips=4, max_chips=16)
        p = Placement(topology="v5e-8", num_slices=1,
                      slices=inv.place_gang(parse_topology("v5e-8"),
                                            1).slices)
        inv.bind(g.key, p)
        # a FIXED v5e-16 head cannot fit (g holds half the pool): the
        # idle chips are the head's reservation, never grow fodder
        out = plan([req("head", "v5e-16", priority=5, seq=1)],
                   [(g, p)], inv, SchedulerConfig())
        grow = [r for r in out.resizes if r[2].startswith("grow")]
        assert grow == []

    def test_defrag_migration_enlarges_largest_free_rect(self):
        # gang parked mid-pool (hand-made binding): re-placing it to a
        # corner strictly enlarges the largest free rectangle
        from kubeflow_tpu.scheduler.inventory import SliceRect
        inv = inventory("v5e-32")   # 4x8
        g = req("g", "v5e-8", min_chips=8, max_chips=8)
        p = Placement(topology="v5e-8", num_slices=1,
                      slices=[SliceRect("pool-0", 1, 2, 2, 4)])
        inv.bind(g.key, p)
        out = plan([], [(g, p)], inv, SchedulerConfig())
        assert [(r.key, w) for r, _p, w in out.resizes] == \
            [("default/g", "defrag: migrating to enlarge the largest "
                           "free rectangle")]
        moved = out.resizes[0][1]
        assert moved.chips == 8 and moved.slices != p.slices

    def test_defrag_leaves_optimal_placement_alone(self):
        inv = inventory("v5e-32")
        g = req("g", "v5e-8", min_chips=8, max_chips=8)
        p = inv.place_gang(parse_topology("v5e-8"), 1)   # corner cut
        inv.bind(g.key, p)
        assert plan([], [(g, p)], inv, SchedulerConfig()).resizes == []

    def test_elastic_off_keeps_fixed_shape_contract(self):
        cfg = SchedulerConfig(elastic=False)
        inv = inventory("v5e-16")
        low = req("low", "v5e-16", priority=0, preemptible=True,
                  min_chips=4, max_chips=16)
        p_low = inv.place_gang(low.topology, 1)
        inv.bind(low.key, p_low)
        out = plan([req("head", "v5e-8", priority=5, seq=1)],
                   [(low, p_low)], inv, cfg)
        # bounds ignored: preemption (not shrink) reclaims the pool
        assert out.resizes == []
        assert [r.key for r in out.preempts] == ["default/low"]

    def test_same_pass_bind_then_shrink_folds_into_one_bind(self):
        # an elastic gang bound THIS pass and immediately shrunk by a
        # later, higher-priority head must come out as ONE bind at the
        # final shape — never a bind plus a resize of a pod-less gang
        inv = inventory("v5e-16")
        a = req("a", "v5e-16", priority=1, min_chips=4, max_chips=16)
        head = req("head", "v5e-8", priority=5, seq=1)
        out = plan([a, head], [], inv, SchedulerConfig())
        assert out.resizes == []
        by_key = {r.key: p for r, p in out.binds}
        assert by_key["default/a"].chips == 8
        assert by_key["default/head"].chips == 8


def elastic_job(name, ckpt="", min_chips=4, max_chips=8, ns="kubeflow"):
    spec = {
        "replicaSpecs": {"TPU": {
            "tpuTopology": "v5e-8",
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}},
        "schedulingPolicy": {"queue": "research", "priority": 0,
                             "minChips": min_chips,
                             "maxChips": max_chips},
        "runPolicy": {"backoffLimit": 5},
    }
    if ckpt:
        spec["checkpointDir"] = ckpt
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


def drive(cluster, mgr, ticks=5):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


def get_job(cluster, name):
    return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                       name)


@pytest.fixture
def env():
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8", pool="pool-a")
    mgr = Manager(cluster)
    mgr.add(SliceScheduler(SchedulerConfig(grow_cooldown_s=0.0)))
    mgr.add(TrainingJobReconciler("TPUJob"))
    yield cluster, mgr
    for c in mgr.controllers:
        c.stop()


class TestControlPlane:
    def _delete_node(self, cluster, name="pool-a-v5e-8-1"):
        cluster.delete("v1", "Node", "", name)

    def test_capacity_loss_shrinks_gang_and_pods(self, env):
        cluster, mgr = env
        cluster.create(elastic_job("el", ckpt="/ckpt"))
        drive(cluster, mgr)
        assert binding_of(get_job(cluster, "el")).chips == 8
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2
        self._delete_node(cluster)
        drive(cluster, mgr, ticks=8)
        job = get_job(cluster, "el")
        placement = binding_of(job)
        assert placement.topology == "v5e-4" and placement.chips == 4
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert [k8s.name_of(p) for p in pods] == ["el-worker-0-0"]
        # the graceful resize path set the resume pointer
        assert job["spec"].get("resumeFrom") == "/ckpt"
        hist = resize_history(job)
        assert hist and hist[-1]["toChips"] == 4 \
            and hist[-1]["fromChips"] == 8

    def test_capacity_return_grows_gang_back(self, env):
        import copy
        cluster, mgr = env
        saved = copy.deepcopy(cluster.get("v1", "Node", "",
                                          "pool-a-v5e-8-1"))
        cluster.create(elastic_job("el", ckpt="/ckpt"))
        drive(cluster, mgr)
        self._delete_node(cluster)
        drive(cluster, mgr, ticks=8)
        assert binding_of(get_job(cluster, "el")).chips == 4
        for stale in ("uid", "resourceVersion", "creationTimestamp"):
            saved["metadata"].pop(stale, None)
        cluster.create(saved)
        drive(cluster, mgr, ticks=8)
        job = get_job(cluster, "el")
        assert binding_of(job).chips == 8
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2
        assert [h["toChips"] for h in resize_history(job)] == [4, 8]

    def test_fixed_job_strands_on_capacity_loss(self, env):
        # the pre-elastic contract, kept for jobs without bounds: a
        # lost host with no same-size rectangle leaves the job Queued
        cluster, mgr = env
        manifest = elastic_job("fixed")
        del manifest["spec"]["schedulingPolicy"]["minChips"]
        del manifest["spec"]["schedulingPolicy"]["maxChips"]
        cluster.create(manifest)
        drive(cluster, mgr)
        self._delete_node(cluster)
        drive(cluster, mgr, ticks=8)
        job = get_job(cluster, "fixed")
        assert binding_of(job) is None
        assert k8s.condition_true(job, "Queued")

    def test_grow_cooldown_blocks_immediate_regrow(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8", pool="pool-a")
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(SchedulerConfig(grow_cooldown_s=3600.0)))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(elastic_job("el"))
        drive(cluster, mgr)
        cluster.delete("v1", "Node", "", "pool-a-v5e-8-1")
        drive(cluster, mgr, ticks=8)
        node = {"apiVersion": "v1", "kind": "Node",
                "metadata": {"name": "pool-a-v5e-8-1",
                             "labels": {"kubeflow.org/pool": "pool-a",
                                        "cloud.google.com/gke-tpu-topology":
                                            "v5e-8"}},
                "status": {"conditions": [{"type": "Ready",
                                           "status": "True"}]}}
        cluster.create(node)
        drive(cluster, mgr, ticks=8)
        # shrink happened (urgent); the re-grow waits out the cooldown
        assert binding_of(get_job(cluster, "el")).chips == 4
        for c in mgr.controllers:
            c.stop()

    def test_resize_emits_trace_event_on_timeline(self, env, tmp_path,
                                                  monkeypatch):
        from kubeflow_tpu.obs.trace import SPAN_PATH_ENV, load_spans
        span_path = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv(SPAN_PATH_ENV, span_path)
        cluster, mgr = env
        cluster.create(elastic_job("el"))
        drive(cluster, mgr)
        self._delete_node(cluster)
        drive(cluster, mgr, ticks=8)
        names = [s.get("name") for s in load_spans(span_path)]
        assert "resized" in names

    def test_dashboard_reports_elastic_surface(self, env):
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        cluster, mgr = env
        cluster.create(elastic_job("el"))
        drive(cluster, mgr)
        self._delete_node(cluster)
        drive(cluster, mgr, ticks=8)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch("GET", "/api/sched/queues", b"")
        assert status == 200
        q = next(row for row in body if row["queue"] == "research")
        j = next(jj for jj in q["jobs"] if jj["name"] == "el")
        assert (j["minChips"], j["maxChips"]) == (4, 8)
        assert j["chips"] == 8 and j["currentChips"] == 4
        assert j["resizeHistory"][-1]["toChips"] == 4
        assert q["resizes"] == len(j["resizeHistory"])
        assert q["chipsBound"] == 4   # actual width, not nominal


@pytest.mark.compute
class TestCheckpointReshape:
    """Save at replica degree N, restore at degree M: the reshape must
    be LOSSLESS (≤1e-5; exactly 0 on the CPU mesh) for both weight-
    update modes — replicated state reshards trivially, ZeRO-2 sharded
    optimizer moments re-lay over the new replica axes."""

    def _builder(self, degree, mode):
        import jax
        import optax

        from kubeflow_tpu.api.trainingjob import ShardingSpec
        from kubeflow_tpu.parallel.mesh import build_mesh
        from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

        def init_fn(rng):
            import jax.numpy as jnp
            return {"w": jax.random.normal(rng, (16, 8)),
                    "b": jnp.zeros((8,))}, {}

        def loss_fn(params, variables, batch, rng):
            import jax.numpy as jnp
            y = batch["x"] @ params["w"] + params["b"]
            return jnp.mean((y - batch["y"]) ** 2), {}

        mesh = build_mesh(ShardingSpec(data=degree),
                          list(jax.devices())[:degree])
        b = TrainStepBuilder(mesh=mesh, loss_fn=loss_fn,
                             optimizer=optax.adam(1e-2),
                             weight_update=mode)
        return b, init_fn

    def _batch(self):
        import numpy as np
        rs = np.random.RandomState(0)
        return {"x": rs.randn(32, 16).astype(np.float32),
                "y": rs.randn(32, 8).astype(np.float32)}

    def _max_delta(self, a, b):
        import jax
        import numpy as np
        return max(jax.tree.leaves(jax.tree.map(
            lambda x, y: float(np.max(np.abs(
                np.asarray(x, np.float64) - np.asarray(y, np.float64)))),
            a, b)), default=0.0)

    @pytest.mark.parametrize("mode", ["replicated", "sharded"])
    @pytest.mark.parametrize("degrees", [(8, 4), (2, 8)])
    def test_cross_degree_restore_is_lossless(self, tmp_path, mode,
                                              degrees):
        import jax

        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        n, m = degrees
        builder_n, init_fn = self._builder(n, mode)
        state = builder_n.init(init_fn, jax.random.PRNGKey(0))
        step = builder_n.build()
        placed = builder_n.place_batch(self._batch())
        for _ in range(3):
            state, _metrics = step(state, placed)
        mgr = CheckpointManager(str(tmp_path), run_meta={
            "replicaDegree": n, "globalBatch": 32})
        mgr.save(3, state, force=True)
        mgr.wait()
        mgr.close()

        builder_m, init_fn_m = self._builder(m, mode)
        template = builder_m.init(init_fn_m, jax.random.PRNGKey(0))
        mgr2 = CheckpointManager(str(tmp_path))
        info = mgr2.check_elastic_resume(None, m, 32)
        assert info == {"resharded": True, "from": n, "to": m}
        restored = mgr2.restore(template)
        mgr2.close()
        assert int(restored.step) == 3
        assert self._max_delta(state.params, restored.params) <= 1e-5
        assert self._max_delta(state.opt_state, restored.opt_state) \
            <= 1e-5
        if mode == "sharded" and m > 1:
            # the moments really are distributed over the new mesh
            mu = restored.opt_state[0].mu["w"]
            assert "data" in str(mu.sharding.spec)
        # ...and the restored state steps on the new mesh
        step_m = builder_m.build()
        restored, metrics = step_m(restored, builder_m.place_batch(
            self._batch()))
        assert int(restored.step) == 4
        assert float(metrics["loss"]) == pytest.approx(
            float(metrics["loss"]))

    def test_run_meta_round_trips_and_guards_global_batch(self, tmp_path):
        import jax

        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        builder, init_fn = self._builder(4, "replicated")
        state = builder.init(init_fn, jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path), run_meta={
            "replicaDegree": 4, "globalBatch": 32})
        mgr.save(1, state, force=True)
        mgr.wait()
        assert mgr.run_meta_of(1) == {"replicaDegree": 4,
                                      "globalBatch": 32}
        # same degree: nothing to validate
        assert mgr.check_elastic_resume(None, 4, 32) == {}
        # degree change + changed global batch = contract breach
        with pytest.raises(ValueError, match="global batch"):
            mgr.check_elastic_resume(None, 2, 64)
        # degree change + non-dividing batch
        with pytest.raises(ValueError, match="divide"):
            mgr.check_elastic_resume(None, 3, 32)
        # the breach is validated against the step the restore walk
        # actually picks, and NEVER absorbed by the newest-first
        # fallback (review fix): restore(expect_run=...) raises even
        # though a template restore without the check would succeed
        builder2, init_fn2 = self._builder(2, "replicated")
        template = builder2.init(init_fn2, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="global batch"):
            mgr.restore(template, expect_run=(2, 64))
        mgr.close()

    def test_pre_elastic_checkpoints_restore_without_meta(self, tmp_path):
        import jax

        from kubeflow_tpu.runtime.checkpoint import CheckpointManager
        builder, init_fn = self._builder(4, "replicated")
        state = builder.init(init_fn, jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path))   # no run_meta: old writer
        mgr.save(1, state, force=True)
        mgr.wait()
        assert mgr.run_meta_of(1) == {}
        assert mgr.check_elastic_resume(None, 8, 32) == {}   # degrades
        mgr.close()


@pytest.mark.compute
class TestElasticResume:
    """train()-level resume across replica degrees: the resumed run
    must pick the data stream up at step k (no replay, no skip) with
    the global batch fixed, and track an undisturbed full-width run."""

    def _ctx(self, devices):
        import jax

        from kubeflow_tpu.api.trainingjob import ShardingSpec
        from kubeflow_tpu.parallel.mesh import build_mesh
        from kubeflow_tpu.runtime.bootstrap import WorkerContext
        return WorkerContext(
            contract=None, sharding=ShardingSpec(),
            mesh=build_mesh(ShardingSpec(),
                            list(jax.devices())[:devices]),
            process_id=0, num_processes=1)

    def test_resume_from_k_at_smaller_degree(self, tmp_path):
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.runtime.worker import train
        clean_dir = str(tmp_path / "clean")
        el_dir = str(tmp_path / "elastic")
        kw = dict(workload="transformer", global_batch=8, sync_every=1,
                  checkpoint_every=2, seed=0, handle_sigterm=False,
                  workload_kwargs={})
        train(steps=6, checkpoint_dir=clean_dir, ctx=self._ctx(8), **kw)
        train(steps=3, checkpoint_dir=el_dir, ctx=self._ctx(8), **kw)
        # resume at HALF the replica degree: the second segment must
        # execute exactly steps 3..6 (result.steps counts executed)
        result = train(steps=6, checkpoint_dir=el_dir, ctx=self._ctx(4),
                       **kw)
        assert result.steps == 3
        a, b = final_params(clean_dir), final_params(el_dir)
        delta = max(jax.tree.leaves(jax.tree.map(
            lambda x, y: float(np.max(np.abs(
                np.asarray(x) - np.asarray(y)))), a, b)), default=0.0)
        # cross-degree reduction order only — NOT a data-order or
        # reshape error, which would blow far past this bound
        assert delta <= 1e-3

    def test_changed_global_batch_refuses_elastic_resume(self, tmp_path):
        from kubeflow_tpu.runtime.worker import train
        d = str(tmp_path / "ck")
        train(workload="transformer", steps=2, global_batch=8,
              sync_every=1, checkpoint_every=1, seed=0,
              handle_sigterm=False, checkpoint_dir=d, ctx=self._ctx(8),
              workload_kwargs={})
        with pytest.raises(ValueError, match="global batch"):
            train(workload="transformer", steps=4, global_batch=16,
                  sync_every=1, checkpoint_every=1, seed=0,
                  handle_sigterm=False, checkpoint_dir=d,
                  ctx=self._ctx(4), workload_kwargs={})


@pytest.mark.slow
@pytest.mark.compute
class TestElasticSoak:
    def test_shrink_grow_soak_succeeds_with_lossless_roundtrip(
            self, tmp_path):
        from kubeflow_tpu.scheduler.soak import ElasticSoak
        soak = ElasticSoak(workdir=str(tmp_path))
        report = soak.run()
        assert report["outcome"] == "succeeded", report
        assert report["chips_seen"] == [8, 4, 8]
        assert report["roundtrip_delta_at_shrink"] <= 1e-5
        assert report["roundtrip_delta_final"] <= 1e-5
        hist = json.loads(report["resize_history"])
        assert [h["toChips"] for h in hist] == [4, 8]
