"""Mixture-of-experts / expert-parallelism tests (8-device CPU mesh).

The reference has no EP anywhere (SURVEY.md §2.5 row 5); these tests pin
down the native implementation: routing math, capacity semantics, aux-loss
plumbing, and a real train step with the expert axis sharded over the mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.api.trainingjob import ShardingSpec
from kubeflow_tpu.models import transformer as T
from kubeflow_tpu.models.moe import MoEMLP, load_balancing_loss
from kubeflow_tpu.parallel.mesh import build_mesh
from kubeflow_tpu.runtime.trainstep import TrainStepBuilder

pytestmark = pytest.mark.compute  # JAX trace/compile tests: excluded from smoke tier


def tiny_moe_cfg(**kw):
    base = dict(vocab_size=64, num_layers=2, embed_dim=32, num_heads=2,
                head_dim=16, mlp_dim=64, max_seq_len=32, num_experts=4)
    base.update(kw)
    return T.TransformerConfig(**base)


@pytest.mark.slow
class TestRouting:
    def test_top1_router_gets_task_gradient(self):
        """Switch semantics: with top_k=1 the combine weight is the raw
        router probability, so the task loss backprops into the router."""
        layer = MoEMLP(num_experts=4, mlp_dim=16, top_k=1,
                       dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 12))
        params = layer.init(jax.random.PRNGKey(1), x)

        def task_loss(p):
            out, _ = layer.apply(p, x, mutable=["losses"])
            return jnp.mean(out ** 2)

        g = jax.grad(task_loss)(params)["params"]["router"]
        assert float(jnp.max(jnp.abs(g))) > 0

    def test_top_k_exceeding_num_experts_rejected(self):
        layer = MoEMLP(num_experts=2, mlp_dim=16, top_k=3)
        with pytest.raises(ValueError, match="top_k"):
            layer.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 8)))

    def test_load_balancing_loss_uniform_is_minimal(self):
        B, S, E = 2, 16, 4
        uniform = jnp.full((B, S, E), 1.0 / E)
        idx = jnp.tile(jnp.arange(S) % E, (B, 1))
        lb_uniform = load_balancing_loss(uniform, idx)
        # skewed: all mass and all assignments on expert 0
        skew = jnp.zeros((B, S, E)).at[..., 0].set(1.0)
        idx0 = jnp.zeros((B, S), jnp.int32)
        lb_skew = load_balancing_loss(skew, idx0)
        assert float(lb_uniform) == pytest.approx(1.0, abs=1e-5)
        assert float(lb_skew) == pytest.approx(E, abs=1e-4)
        assert float(lb_skew) > float(lb_uniform)

    def test_moe_layer_shapes_and_aux(self):
        layer = MoEMLP(num_experts=4, mlp_dim=64, top_k=2)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(1), x)
        y, mods = layer.apply(variables, x, mutable=["losses"])
        assert y.shape == x.shape
        aux = jax.tree.leaves(mods["losses"])
        assert len(aux) == 1 and aux[0].shape == ()
        assert float(aux[0]) > 0

    def test_expert_params_have_leading_expert_dim(self):
        layer = MoEMLP(num_experts=4, mlp_dim=64)
        x = jnp.zeros((1, 8, 32))
        variables = layer.init(jax.random.PRNGKey(0), x)
        assert variables["params"]["wi"].shape == (4, 32, 64)
        assert variables["params"]["wo"].shape == (4, 64, 32)

    def test_zero_capacity_overflow_drops_tokens(self):
        # capacity factor so tiny every expert takes ~1 token; output must
        # stay finite and dropped tokens contribute zero (not NaN)
        layer = MoEMLP(num_experts=2, mlp_dim=16, top_k=1,
                       capacity_factor=0.01)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 8), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(1), x)
        y = layer.apply(variables, x, mutable=["losses"])[0]
        assert np.isfinite(np.asarray(y, jnp.float32)).all()
        # with capacity 1 per expert, at most 2 token rows are nonzero
        nonzero_rows = int(jnp.sum(jnp.any(jnp.abs(y) > 0, axis=-1)))
        assert nonzero_rows <= 2

    def test_combine_weights_renormalized(self):
        # top-2 gating with ample capacity: per-token combine weights sum
        # to 1, so the layer is a convex mix of expert outputs
        layer = MoEMLP(num_experts=4, mlp_dim=16, top_k=2,
                       capacity_factor=4.0)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8), jnp.float32)
        variables = layer.init(jax.random.PRNGKey(1), x)

        # identity experts: wi = [E, M, H] zeros→gelu(0)=0 makes output 0;
        # instead probe via the dispatch/combine tensors through a linear
        # expert: set wo so expert e outputs constant e... simpler: check
        # output invariance when all experts share identical weights
        p = variables["params"]
        wi0 = p["wi"][0]
        wo0 = p["wo"][0]
        shared = {"params": {**p,
                             "wi": jnp.stack([wi0] * 4),
                             "wo": jnp.stack([wo0] * 4)}}
        y_shared = layer.apply(shared, x, mutable=["losses"])[0]
        dense = jnp.einsum("bsm,mh->bsh", x.astype(jnp.bfloat16),
                           wi0.astype(jnp.bfloat16))
        import flax.linen as nn
        dense = jnp.einsum("bsh,hm->bsm", nn.gelu(dense),
                           wo0.astype(jnp.bfloat16))
        np.testing.assert_allclose(np.asarray(y_shared, jnp.float32),
                                   np.asarray(dense, jnp.float32),
                                   atol=0.15, rtol=0.15)


@pytest.mark.slow
class TestMoETransformer:
    def test_forward_and_loss(self):
        cfg = tiny_moe_cfg()
        model = T.TransformerLM(cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        variables = jax.jit(model.init)(jax.random.PRNGKey(0), tokens)
        loss_fn = T.make_loss_fn(model)
        loss, metrics = loss_fn(variables["params"], {},
                                {"tokens": tokens}, None)
        assert jnp.isfinite(loss)
        assert "moe_aux_loss" in metrics
        assert float(metrics["moe_aux_loss"]) > 0

    def test_logical_axes_cover_expert_params(self):
        cfg = tiny_moe_cfg()
        model = T.TransformerLM(cfg)
        abstract = jax.eval_shape(
            lambda rng: T.init_fn(model, 16)(rng)[0], jax.random.PRNGKey(0))
        axes = T.logical_axes(abstract)
        layer0 = axes["layer0"]["moe"]
        assert layer0["wi"] == ("expert", "embed", "mlp")
        assert layer0["wo"] == ("expert", "mlp", "embed")
        assert layer0["router"] == ("embed", None)

    def test_train_step_with_expert_axis_sharding(self):
        # dp=2 x expert=2 x tensor=2 over the 8-device mesh: the EP path
        # end-to-end through the real TrainStepBuilder
        sharding = ShardingSpec(data=2, fsdp=1, expert=2, tensor=2)
        mesh = build_mesh(sharding, jax.devices()[:8])
        cfg = tiny_moe_cfg()
        spec = T.workload_spec(cfg=cfg, seq_len=32)
        builder = TrainStepBuilder(
            mesh=mesh, loss_fn=spec.loss_fn,
            optimizer=optax.adamw(1e-2), rules=spec.rules,
            param_logical_axes=spec.param_logical_axes)
        state = builder.init(spec.init_fn, jax.random.PRNGKey(0))

        # expert weights actually sharded over the expert mesh axis
        wi = state.params["layer0"]["moe"]["wi"]
        specs = wi.sharding.spec
        assert "expert" in str(specs), specs

        step_fn = builder.build()
        batch = builder.place_batch(spec.batch_fn(jax.random.PRNGKey(1), 8))
        losses = []
        for _ in range(5):
            state, metrics = step_fn(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses))