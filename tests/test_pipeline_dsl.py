"""Pipeline DSL: author in Python, compile to a Workflow, run it through
the real engine — the kfp.dsl/compiler role over workflows/engine.py.

E2E tier (SURVEY.md §4): the compiled manifest is reconciled by the real
WorkflowReconciler on the in-memory apiserver, including a launch step
that creates a TPUJob the real training-job operator runs to completion.
"""

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.cluster import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.pipelines import Pipeline
from kubeflow_tpu.workflows.engine import (WORKFLOW_API_VERSION,
                                           WorkflowReconciler)


def tpu_job(name: str, steps: str = "5") -> dict:
    return {
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "replicaSpecs": {"TPU": {
                "tpuTopology": "v5e-8",
                "template": {"spec": {"containers": [{
                    "name": "worker", "image": "worker:v1",
                    "command": ["python", "-m",
                                "kubeflow_tpu.runtime.worker",
                                "--steps", steps]}]}},
            }},
            "runPolicy": {"backoffLimit": 1},
        },
    }


class TestCompile:
    def test_dag_shape(self):
        p = Pipeline("demo", parameters={"steps": "100"},
                     labels={"team": "ml"})
        a = p.container("prep", image="busybox", command=["sh", "-c", "ok"])
        b = p.launch("train", manifest=tpu_job("t"), after=[a])
        p.container("report", image="busybox",
                    args=["--steps=$(workflow.parameters.steps)"],
                    env={"RUN": "$(workflow.name)"}, after=[b])
        wf = p.compile()
        assert wf["apiVersion"] == WORKFLOW_API_VERSION
        assert wf["metadata"]["labels"] == {"team": "ml"}
        assert wf["spec"]["entrypoint"] == "main"
        tmpl = {t["name"]: t for t in wf["spec"]["templates"]}
        assert set(tmpl) == {"main", "prep", "train", "report"}
        tasks = {t["name"]: t for t in tmpl["main"]["dag"]["tasks"]}
        assert "dependencies" not in tasks["prep"]
        assert tasks["train"]["dependencies"] == ["prep"]
        assert tasks["report"]["dependencies"] == ["train"]
        assert tmpl["train"]["resource"]["action"] == "create"
        assert wf["spec"]["arguments"]["parameters"] == [
            {"name": "steps", "value": "100"}]

    def test_compile_is_pure(self):
        p = Pipeline("demo")
        p.container("a", image="busybox")
        w1, w2 = p.compile(), p.compile()
        assert w1 == w2 and w1 is not w2
        # outputs never alias internal state: mutating one compile()'s
        # result (or the launch manifest) must not leak into the next
        w1["spec"]["templates"][1]["container"]["image"] = "debug"
        assert p.compile()["spec"]["templates"][1]["container"][
            "image"] == "busybox"

    def test_launch_manifest_snapshot(self):
        m = tpu_job("j")
        p = Pipeline("demo")
        p.launch("train", manifest=m)
        m["spec"]["runPolicy"]["backoffLimit"] = 99  # caller mutates after
        tmpl = p.compile()["spec"]["templates"][1]
        assert tmpl["resource"]["manifest"]["spec"]["runPolicy"][
            "backoffLimit"] == 1

    def test_authoring_errors(self):
        p = Pipeline("demo")
        with pytest.raises(ValueError, match="no steps"):
            p.compile()
        p.container("a", image="busybox")
        with pytest.raises(ValueError, match="duplicate"):
            p.container("a", image="busybox")
        with pytest.raises(ValueError, match="unknown"):
            p.container("b", image="busybox", after=["nope"])
        with pytest.raises(ValueError, match="reserved"):
            p.container("main", image="busybox")
        with pytest.raises(ValueError, match="manifest"):
            p.launch("l", manifest={"kind": "TPUJob"})
        with pytest.raises(ValueError, match="apiVersion"):
            # no apiVersion → nothing would ever reconcile it
            p.launch("l", manifest={
                "kind": "TPUJob", "metadata": {"name": "j"}})
        with pytest.raises(ValueError, match="invalid"):
            Pipeline("Bad_Name")
        # combined pod name '{pipeline}-{step}' must fit a DNS label
        long = Pipeline("p" * 40)
        with pytest.raises(ValueError, match="invalid"):
            long.container("s" * 40, image="busybox")

    def test_submit_overrides(self):
        cluster = FakeCluster()
        p = Pipeline("demo", parameters={"steps": "100"})
        p.container("a", image="busybox")
        with pytest.raises(ValueError, match="unknown parameters"):
            p.submit(cluster, nope="1")
        p.submit(cluster, steps="7")
        wf = cluster.get(WORKFLOW_API_VERSION, "Workflow", "kubeflow",
                         "demo")
        assert wf["spec"]["arguments"]["parameters"][0]["value"] == "7"


class TestEndToEnd:
    @pytest.fixture
    def env(self):
        cluster = FakeCluster()
        cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        mgr.add(WorkflowReconciler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        return cluster, mgr

    def drain(self, cluster, mgr, rounds=8):
        for _ in range(rounds):
            mgr.run_pending()
            cluster.tick()
            for pod in cluster.list("v1", "Pod", "kubeflow"):
                if pod.get("status", {}).get("phase") == "Running":
                    cluster.set_pod_phase(k8s.namespace_of(pod, "kubeflow"),
                                          k8s.name_of(pod), "Succeeded")
            mgr.run_pending()

    def test_pipeline_orchestrates_training_job(self, env):
        """The authored DAG runs end-to-end: prep pod → TPUJob (real gang
        reconciler) → report pod with parameters substituted."""
        cluster, mgr = env
        p = Pipeline("train-pipe", parameters={"steps": "9"})
        prep = p.container("prep", image="busybox",
                           command=["sh", "-c", "prep"])
        train = p.launch(
            "train",
            manifest=tpu_job("pipe-job",
                             steps="$(workflow.parameters.steps)"),
            after=[prep])
        p.container("report", image="busybox",
                    args=["--run=$(workflow.name)"], after=[train])
        p.submit(cluster)
        self.drain(cluster, mgr)
        wf = cluster.get(WORKFLOW_API_VERSION, "Workflow", "kubeflow",
                         "train-pipe")
        assert wf["status"]["phase"] == "Succeeded", wf["status"]
        # the launched job went through the REAL operator with the
        # parameter substituted into the worker command
        job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                          "pipe-job")
        cmd = job["spec"]["replicaSpecs"]["TPU"]["template"]["spec"][
            "containers"][0]["command"]
        assert cmd[-1] == "9"
        assert k8s.condition_true(job, "Succeeded")
        # report pod saw the workflow name
        report = cluster.get("v1", "Pod", "kubeflow", "train-pipe-report")
        assert report["spec"]["containers"][0]["args"] == [
            "--run=train-pipe"]

    def test_parallel_fanout(self, env):
        cluster, mgr = env
        p = Pipeline("fanout")
        a = p.container("a", image="busybox")
        b1 = p.container("b1", image="busybox", after=[a])
        b2 = p.container("b2", image="busybox", after=[a])
        p.container("join", image="busybox", after=[b1, b2])
        p.submit(cluster)
        # after a completes, b1 and b2 launch together
        mgr.run_pending()
        cluster.tick()
        cluster.set_pod_phase("kubeflow", "fanout-a", "Succeeded")
        mgr.run_pending()
        pods = {k8s.name_of(x) for x in cluster.list("v1", "Pod", "kubeflow")}
        assert {"fanout-b1", "fanout-b2"} <= pods
        assert "fanout-join" not in pods
        self.drain(cluster, mgr)
        wf = cluster.get(WORKFLOW_API_VERSION, "Workflow", "kubeflow",
                         "fanout")
        assert wf["status"]["phase"] == "Succeeded"


class TestSchedule:
    def test_schedule_manifest_shapes(self):
        p = Pipeline("nightly")
        p.container("a", image="busybox")
        swf = p.schedule("0 2 * * *", max_concurrency=2, max_history=5)
        assert swf["kind"] == "ScheduledWorkflow"
        assert swf["spec"]["trigger"]["cronSchedule"]["cron"] == "0 2 * * *"
        assert swf["spec"]["maxConcurrency"] == 2
        assert swf["spec"]["workflow"]["spec"]["entrypoint"] == "main"
        periodic = p.schedule(interval_s=600)
        assert periodic["spec"]["trigger"]["periodicSchedule"][
            "intervalSecond"] == 600
        with pytest.raises(ValueError, match="exactly one"):
            p.schedule()
        with pytest.raises(ValueError, match="exactly one"):
            p.schedule("0 * * * *", interval_s=60)
        with pytest.raises(ValueError, match="exactly one"):
            p.schedule("")  # empty cron is not a schedule
        with pytest.raises(ValueError):
            p.schedule("not a cron")  # validated at author time
        with pytest.raises(ValueError, match=">= 1"):
            p.schedule(interval_s=0)  # would silently never fire
        with pytest.raises(ValueError, match=">= 1"):
            p.schedule(interval_s=-60)  # would fire every reconcile

    def test_schedule_rejects_fixed_launch_names(self):
        """A fixed launched-manifest name collides on the 2nd firing —
        caught at author time; $(workflow.name) makes it run-unique."""
        p = Pipeline("sched")
        p.launch("train", manifest=tpu_job("fixed-name"))
        with pytest.raises(ValueError, match="AlreadyExists"):
            p.schedule(interval_s=60)
        ok = Pipeline("sched")
        ok.launch("train", manifest=tpu_job("job-$(workflow.name)"))
        swf = ok.schedule(interval_s=60)
        assert swf["kind"] == "ScheduledWorkflow"

    def test_schedule_validates_instance_pod_names(self):
        # '{pipeline}-{index}-{step}' must fit a DNS label with headroom
        p = Pipeline("p" * 30)
        p.container("s" * 22, image="busybox")  # fits '{p}-{s}' one-shot
        with pytest.raises(ValueError, match="invalid"):
            p.schedule(interval_s=60)

    def test_scheduled_pipeline_fires_through_controller(self):
        """The DSL-authored schedule runs through the real
        ScheduledWorkflow reconciler: tick → Workflow instance → pods."""
        from test_pipelines import FakeClock, drive
        from kubeflow_tpu.pipelines import ScheduledWorkflowReconciler
        cluster = FakeCluster()
        cluster.add_node("cpu-0", {"cpu": 96, "memory": 2 ** 36})
        clock = FakeClock()
        mgr = Manager(cluster)
        mgr.add(ScheduledWorkflowReconciler(clock=clock))
        mgr.add(WorkflowReconciler(clock=clock))
        p = Pipeline("tick")
        p.container("a", image="busybox", command=["true"])
        cluster.create(p.schedule(interval_s=60))
        mgr.run_pending()
        clock.advance(61)
        drive(cluster, mgr)
        wfs = cluster.list(WORKFLOW_API_VERSION, "Workflow", "kubeflow")
        assert [k8s.name_of(w) for w in wfs] == ["tick-1"]
        assert cluster.list("v1", "Pod", "kubeflow")  # step pod launched
