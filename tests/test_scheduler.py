"""Gang-scheduler subsystem tests (ISSUE 4).

Three tiers, mirroring the chaos suite's layering:
- pure-core: inventory packing (contiguity, fragmentation scoring,
  determinism) and plan() policy (quota, cheapest-victim preemption,
  backfill no-starvation) with no cluster at all;
- control-plane: SliceScheduler + the TPUJob operator over FakeCluster
  (Queued phase, binding → pool-pinned pods, preemption teardown →
  resumeFrom → re-bind);
- soak (slow): the real-training preemption-parity drill
  (scheduler/soak.py), the bench.py --mode sched acceptance bar.
"""

import json
import random

import pytest

from kubeflow_tpu.api import k8s
from kubeflow_tpu.api.topology import parse_topology
from kubeflow_tpu.api.trainingjob import (BINDING_ANNOTATION, COND_QUEUED,
                                          PREEMPTED_COUNT_ANNOTATION,
                                          SCHED_STATE_ANNOTATION)
from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.runtime import Manager
from kubeflow_tpu.controllers.tpujob import TrainingJobReconciler
from kubeflow_tpu.scheduler.core import SliceScheduler, plan
from kubeflow_tpu.scheduler.inventory import (PoolState, SliceInventory,
                                              SliceRect)
from kubeflow_tpu.scheduler.queue import JobRequest, SchedulerConfig

pytestmark = pytest.mark.sched


def req(name, topo="v5e-8", priority=0, preemptible=False, seq=0,
        num_slices=1, queue="default", namespace="default"):
    return JobRequest(namespace=namespace, name=name, queue=queue,
                      priority=priority, preemptible=preemptible,
                      topology=parse_topology(topo),
                      num_slices=num_slices, seq=seq)


def inventory(*pool_topos):
    return SliceInventory([
        PoolState(f"pool-{i}", parse_topology(t))
        for i, t in enumerate(pool_topos)])


class TestInventory:
    def test_rect_is_contiguous_and_fits_pool(self):
        inv = inventory("v5e-32")   # 4x8 grid
        p = inv.place_gang(parse_topology("v5e-8"), 1)   # 2x4 rect
        assert p is not None and len(p.slices) == 1
        r = p.slices[0]
        assert {r.h, r.w} == {2, 4}
        assert r.x + r.h <= 4 and r.y + r.w <= 8

    def test_packing_fills_pool_exactly(self):
        # 4 x v5e-8 fill a v5e-32 with zero stranded chips
        inv = inventory("v5e-32")
        for i in range(4):
            p = inv.place_gang(parse_topology("v5e-8"), 1)
            assert p is not None, f"gang {i} did not fit"
            inv.bind(f"j{i}", p)
        assert inv.free_chips == 0
        assert inv.place_gang(parse_topology("v5e-4"), 1) is None

    def test_fragmentation_scoring_leaves_large_hole(self):
        # after a v5e-16 (4x4) lands in a v5e-32 (4x8), the remaining
        # free region must still be one contiguous 4x4 — a v5e-16 still
        # fits (corner placement, not a middle cut)
        inv = inventory("v5e-32")
        p = inv.place_gang(parse_topology("v5e-16"), 1)
        inv.bind("a", p)
        assert inv.place_gang(parse_topology("v5e-16"), 1) is not None

    def test_release_returns_chips(self):
        inv = inventory("v5e-32")
        p = inv.place_gang(parse_topology("v5e-8"), 1)
        inv.bind("a", p)
        assert inv.free_chips == 24
        assert inv.release("a") == 8
        assert inv.free_chips == 32

    def test_multislice_gang_places_each_slice_contiguously(self):
        inv = inventory("v5e-32")
        p = inv.place_gang(parse_topology("v5e-8"), 3)
        assert p is not None and len(p.slices) == 3
        # slices never overlap
        cells = [c for r in p.slices for c in r.cells()]
        assert len(cells) == len(set(cells)) == 24

    def test_packing_is_deterministic_under_a_seed(self):
        # the same seeded request sequence always produces the same
        # placements, byte for byte — no tiebreak depends on dict order
        def run(seed):
            rng = random.Random(seed)
            inv = inventory("v5e-32", "v5e-16", "v5e-32")
            out = []
            for i in range(12):
                topo = rng.choice(["v5e-4", "v5e-8", "v5e-16"])
                p = inv.place_gang(parse_topology(topo), 1)
                if p is None:
                    out.append((topo, None))
                    continue
                inv.bind(f"j{i}", p)
                if rng.random() < 0.3:
                    inv.release(f"j{i}")
                    out.append((topo, "released"))
                else:
                    out.append((topo, p.to_dict()))
            return out
        assert run(7) == run(7)
        assert run(11) == run(11)

    def test_binding_wire_round_trip(self):
        inv = inventory("v5e-32")
        p = inv.place_gang(parse_topology("v5e-8"), 2)
        from kubeflow_tpu.scheduler.inventory import Placement
        assert Placement.from_dict(
            json.loads(json.dumps(p.to_dict()))).to_dict() == p.to_dict()

    def test_from_nodes_carves_not_ready_hosts(self):
        # a NotReady host's EXACT cells leave the placeable inventory
        # (the old behavior truncated bottom rows regardless of which
        # host died); the pool keeps its full grid geometry
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="full")
        cluster.add_tpu_slice_nodes("v5e-8", pool="half")
        # drain one of half's two hosts
        node = cluster.get("v1", "Node", "", "half-v5e-8-1")
        node["status"]["conditions"] = [{"type": "Ready",
                                         "status": "False"}]
        cluster.update(node)
        inv = SliceInventory.from_nodes(cluster.list("v1", "Node"))
        assert inv.pools["full"].total_chips == 32
        assert inv.pools["half"].total_chips == 8   # geometry intact
        down = {c for c in inv.down_cells if c[0] == "half"}
        assert down == set(inv.cells_by_node["half-v5e-8-1"])
        assert len(down) == 4                       # one host's chips
        inv.carve_down()
        assert inv.pools["half"].free_chips == 4    # placeable half
        # a full v5e-8 gang no longer fits the half pool, the intact
        # pool still takes it
        p = inv.place_gang(parse_topology("v5e-8"), 1)
        assert p is not None and p.slices[0].pool == "full"

    def test_from_nodes_carves_quarantined_hosts(self):
        # the quarantine annotation (scheduler/health.py wire contract)
        # carves a host exactly like NotReady — runtime failure
        # evidence feeds placement
        from kubeflow_tpu.scheduler import health as H
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="big")
        cluster.patch("v1", "Node", "", "big-v5e-32-2", {
            "metadata": {"annotations": {
                "kubeflow.org/quarantine": H.quarantine_record(
                    "test", 5.0, 0.0, 60.0)}}})
        inv = SliceInventory.from_nodes(cluster.list("v1", "Node"))
        assert inv.down_cells == set(inv.cells_by_node["big-v5e-32-2"])
        inv.carve_down()
        assert inv.pools["big"].free_chips == 28
        # bindings over the quarantined host read invalid -> replan
        from kubeflow_tpu.scheduler.inventory import Placement
        hit = Placement(topology="v5e-16", num_slices=1,
                        slices=[SliceRect("big", 0, 0, 4, 4)])
        clear = Placement(topology="v5e-16", num_slices=1,
                          slices=[SliceRect("big", 0, 4, 4, 4)])
        assert not inv.valid_binding(hit)    # covers host 2's cells
        assert inv.valid_binding(clear)


class TestPlanPolicy:
    def test_priority_order_binds_high_first(self):
        inv = inventory("v5e-8")
        decisions = plan([req("low", seq=0), req("high", priority=5,
                                                 seq=1)],
                         [], inv, SchedulerConfig())
        assert [r.name for r, _ in decisions.binds] == ["high"]
        assert "default/low" in decisions.waits

    def test_quota_enforced_per_queue_namespace(self):
        cfg = SchedulerConfig.from_dict({"queues": {"default": {
            "quotaChips": {"team-a": 8}}}})
        inv = inventory("v5e-32")
        decisions = plan(
            [req("a1", namespace="team-a", seq=0),
             req("a2", namespace="team-a", seq=1),
             req("b1", namespace="team-b", seq=2)],
            [], inv, cfg)
        bound = {r.key for r, _ in decisions.binds}
        assert bound == {"team-a/a1", "team-b/b1"}
        assert "quota" in decisions.waits["team-a/a2"]

    def test_quota_counts_bound_not_queued(self):
        cfg = SchedulerConfig.from_dict({"queues": {"default": {
            "quotaChips": {"*": 8}}}})
        inv = inventory("v5e-32")
        first = plan([req("a1", seq=0)], [], inv, cfg)
        assert len(first.binds) == 1
        # with a1 bound, a2 is over quota; once a1 finishes (released +
        # absent from bound), a2 binds
        inv2 = inventory("v5e-32")
        blocked = plan([req("a2", seq=1)], first.binds, inv2, cfg)
        assert not blocked.binds
        inv3 = inventory("v5e-32")
        after = plan([req("a2", seq=1)], [], inv3, cfg)
        assert len(after.binds) == 1

    def test_preemption_picks_the_cheapest_victim(self):
        # bound: a cheap 8-chip preemptible and an expensive 16-chip
        # preemptible; an arriving 8-chip high-priority job must evict
        # ONLY the 8-chip victim
        inv = inventory("v5e-32")
        small = req("small", "v5e-8", priority=0, preemptible=True, seq=0)
        big = req("big", "v5e-16", priority=0, preemptible=True, seq=1)
        b1 = inv.place_gang(small.topology, 1)
        inv.bind(small.key, b1)
        b2 = inv.place_gang(big.topology, 1)
        inv.bind(big.key, b2)
        # fill the rest so the head cannot fit without a preemption
        filler = req("filler", "v5e-8", preemptible=False, seq=2)
        b3 = inv.place_gang(filler.topology, 1)
        inv.bind(filler.key, b3)
        decisions = plan(
            [req("urgent", "v5e-8", priority=10, seq=3)],
            [(small, b1), (big, b2), (filler, b3)], inv,
            SchedulerConfig())
        assert [v.name for v in decisions.preempts] == ["small"]
        assert [r.name for r, _ in decisions.binds] == ["urgent"]

    def test_preemption_spares_victims_that_never_blocked_the_head(self):
        # head needs a full v5e-32 (only pool-0 can hold it); a cheap
        # 4-chip job on pool-1 is released FIRST by the greedy
        # cheapest-order walk but contributes nothing — the prune must
        # re-bind it so only the pool-0 job eats the SIGTERM
        from kubeflow_tpu.scheduler.inventory import Placement
        inv = inventory("v5e-32", "v5e-16")
        innocent = req("innocent", "v5e-4", preemptible=True, seq=0)
        # pin the innocent job onto pool-1 explicitly
        bi = Placement(topology="v5e-4", num_slices=1,
                       slices=[SliceRect("pool-1", 0, 0, 2, 2)])
        inv.bind(innocent.key, bi)
        blocker = req("blocker", "v5e-32", preemptible=True, seq=1)
        bb = inv.place_gang(blocker.topology, 1)
        inv.bind(blocker.key, bb)
        decisions = plan(
            [req("urgent", "v5e-32", priority=10, seq=2)],
            [(innocent, bi), (blocker, bb)], inv, SchedulerConfig())
        assert [v.name for v in decisions.preempts] == ["blocker"]
        assert [r.name for r, _ in decisions.binds] == ["urgent"]

    def test_preemption_never_touches_equal_or_higher_priority(self):
        inv = inventory("v5e-8")
        peer = req("peer", priority=5, preemptible=True, seq=0)
        b = inv.place_gang(peer.topology, 1)
        inv.bind(peer.key, b)
        decisions = plan([req("urgent", priority=5, seq=1)],
                         [(peer, b)], inv, SchedulerConfig())
        assert decisions.preempts == []
        assert not decisions.binds

    def test_non_preemptible_victims_are_untouchable(self):
        inv = inventory("v5e-8")
        solid = req("solid", priority=0, preemptible=False, seq=0)
        b = inv.place_gang(solid.topology, 1)
        inv.bind(solid.key, b)
        decisions = plan([req("urgent", priority=10, seq=1)],
                         [(solid, b)], inv, SchedulerConfig())
        assert decisions.preempts == []

    def test_backfill_binds_small_jobs_behind_blocked_head(self):
        # head needs the whole v5e-32; half is occupied -> blocked; a
        # v5e-8 backfill job must still bind (outside the reservation a
        # v5e-32 head claims the WHOLE pool... so use two pools: head
        # reserves pool geometry, backfill rides the second pool)
        inv = inventory("v5e-32", "v5e-16")
        runner = req("runner", "v5e-16", seq=0)
        b = inv.place_gang(runner.topology, 1)   # lands in pool-0 corner
        inv.bind(runner.key, b)
        decisions = plan(
            [req("head", "v5e-32", priority=5, seq=1),
             req("small", "v5e-8", priority=0, seq=2)],
            [(runner, b)], inv, SchedulerConfig(preemption=False))
        assert "default/head" in decisions.waits
        assert [r.name for r, _ in decisions.binds] == ["small"]
        # backfill landed clear of the head's reserved pool-0 region
        assert all(r.pool != "pool-0"
                   for _, p in decisions.binds for r in p.slices)

    def test_backfill_never_starves_the_head(self):
        # the no-starvation invariant, run to completion: a stream of
        # small jobs keeps arriving; as soon as the blockers finish the
        # head MUST bind even though small jobs are still queued
        inv = inventory("v5e-32")
        blocker = req("blocker", "v5e-16", preemptible=False, seq=0)
        b = inv.place_gang(blocker.topology, 1)
        inv.bind(blocker.key, b)
        cfg = SchedulerConfig(preemption=False)
        head = req("head", "v5e-32", priority=5, seq=1)
        smalls = [req(f"small-{i}", "v5e-4", seq=2 + i)
                  for i in range(6)]
        decisions = plan([head, *smalls], [(blocker, b)], inv, cfg)
        # head blocked; NO small job may take pool-0 cells the head
        # reserved (= the whole pool) -> none bind
        assert decisions.binds == []
        # blocker finishes: the head binds immediately, smalls still wait
        inv2 = inventory("v5e-32")
        decisions2 = plan([head, *smalls], [], inv2, cfg)
        assert [r.name for r, _ in decisions2.binds] == ["head"]

    def test_fifo_config_ignores_priority(self):
        inv = inventory("v5e-8")
        from kubeflow_tpu.scheduler.sim import policy_config
        cfg = policy_config("fifo")
        decisions = plan([req("first", seq=0),
                          req("vip", priority=99, seq=1)], [], inv, cfg)
        assert [r.name for r, _ in decisions.binds] == ["first"]


def tpujob(name, topo="v5e-8", priority=0, preemptible=True, ckpt="",
           policy=True, ns="kubeflow"):
    spec = {
        "replicaSpecs": {"TPU": {
            "tpuTopology": topo,
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}},
        "runPolicy": {"backoffLimit": 2},
    }
    if policy:
        spec["schedulingPolicy"] = {"queue": "research",
                                    "priority": priority,
                                    "preemptible": preemptible}
    if ckpt:
        spec["checkpointDir"] = ckpt
    return {"apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


@pytest.fixture
def env():
    cluster = FakeCluster()
    cluster.add_tpu_slice_nodes("v5e-8")
    mgr = Manager(cluster)
    mgr.add(SliceScheduler())
    mgr.add(TrainingJobReconciler("TPUJob"))
    yield cluster, mgr
    for c in mgr.controllers:
        c.stop()


def drive(cluster, mgr, ticks=4):
    for _ in range(ticks):
        mgr.run_pending()
        cluster.tick()
    mgr.run_pending()


def get_job(cluster, name):
    return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                       name)


class TestControlPlane:
    def test_unbound_job_sits_queued_with_no_pods(self):
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        mgr = Manager(cluster)
        # operator only — no scheduler running: the job must WAIT, the
        # pre-scheduler behavior (create immediately) would deadlock a
        # contended cluster on partial gangs
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("waiting"))
        drive(cluster, mgr)
        assert cluster.list("v1", "Pod", "kubeflow") == []
        job = get_job(cluster, "waiting")
        assert k8s.condition_true(job, COND_QUEUED)
        for c in mgr.controllers:
            c.stop()

    def test_legacy_job_without_policy_creates_immediately(self, env):
        cluster, mgr = env
        cluster.create(tpujob("legacy", policy=False))
        mgr.run_pending()
        assert len(cluster.list("v1", "Pod", "kubeflow")) == 2

    def test_bound_job_pods_pinned_to_pool(self, env):
        cluster, mgr = env
        cluster.create(tpujob("pinned"))
        drive(cluster, mgr)
        job = get_job(cluster, "pinned")
        binding = json.loads(
            k8s.annotations_of(job)[BINDING_ANNOTATION])
        assert binding["topology"] == "v5e-8"
        pod = cluster.get("v1", "Pod", "kubeflow", "pinned-worker-0-0")
        sel = pod["spec"]["nodeSelector"]
        assert sel["kubeflow.org/pool"] == binding["slices"][0]["pool"]
        rect = json.loads(k8s.annotations_of(pod)[
            "scheduling.kubeflow.org/slice"])
        assert SliceRect.from_dict(rect).chips == 8
        envm = {e["name"]: e["value"]
                for e in pod["spec"]["containers"][0]["env"]}
        assert envm["KFTPU_SCHED_QUEUE"] == "research"
        assert envm["KFTPU_SCHED_PREEMPTIBLE"] == "1"
        assert pod["status"]["phase"] == "Running"

    def test_sub_slice_binds_on_larger_pool(self):
        # a v5e-8 gang carved out of a v5e-32 pool: the exact-topology
        # node pin must give way to the pool pin or the pods would wait
        # forever for v5e-8-labeled nodes
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="big")
        mgr = Manager(cluster)
        mgr.add(SliceScheduler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("carved"))
        drive(cluster, mgr)
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert len(pods) == 2
        assert all(p["status"]["phase"] == "Running" for p in pods)
        assert all(p["spec"]["nodeSelector"]["kubeflow.org/pool"] == "big"
                   for p in pods)
        for c in mgr.controllers:
            c.stop()

    def test_second_job_queues_instead_of_half_creating(self, env):
        # THE motivating scenario: two jobs on a one-slice cluster; the
        # seed behavior started both and deadlocked on partial gangs
        cluster, mgr = env
        cluster.create(tpujob("first"))
        cluster.create(tpujob("second"))
        drive(cluster, mgr)
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert {k8s.name_of(p) for p in pods} == \
            {"first-worker-0-0", "first-worker-0-1"}
        second = get_job(cluster, "second")
        assert k8s.condition_true(second, COND_QUEUED)
        assert k8s.annotations_of(second)[
            SCHED_STATE_ANNOTATION] == "queued"
        # first succeeds -> second binds
        cluster.set_pod_phase("kubeflow", "first-worker-0-0", "Succeeded")
        drive(cluster, mgr, ticks=6)
        pods = cluster.list("v1", "Pod", "kubeflow")
        assert {k8s.name_of(p) for p in pods} >= \
            {"second-worker-0-0", "second-worker-0-1"}

    def test_preemption_requeues_victim_with_resume(self, env):
        cluster, mgr = env
        cluster.create(tpujob("victim", priority=0, preemptible=True,
                              ckpt="/ckpt/victim"))
        drive(cluster, mgr)
        cluster.create(tpujob("winner", priority=10, preemptible=False))
        drive(cluster, mgr, ticks=6)
        victim = get_job(cluster, "victim")
        anns = k8s.annotations_of(victim)
        assert not anns.get(BINDING_ANNOTATION)
        assert anns[SCHED_STATE_ANNOTATION] == "preempted"
        assert anns[PREEMPTED_COUNT_ANNOTATION] == "1"
        assert victim["spec"]["resumeFrom"] == "/ckpt/victim"
        assert k8s.condition_true(victim, COND_QUEUED)
        # preemption is a requeue, never a failure: no backoff burned
        assert "kubeflow.org/gang-restart-count" not in anns
        winner_pods = [k8s.name_of(p)
                       for p in cluster.list("v1", "Pod", "kubeflow")]
        assert sorted(winner_pods) == ["winner-worker-0-0",
                                       "winner-worker-0-1"]

    def test_preempted_jobs_resume_env_survives_rebind(self, env):
        # the checkpoint contract across the whole cycle: preempt ->
        # re-bind -> the recreated gang carries KFTPU_RESUME_FROM
        cluster, mgr = env
        cluster.create(tpujob("victim", ckpt="/ckpt/victim"))
        drive(cluster, mgr)
        cluster.create(tpujob("winner", priority=10, preemptible=False))
        drive(cluster, mgr, ticks=6)
        cluster.set_pod_phase("kubeflow", "winner-worker-0-0",
                              "Succeeded")
        drive(cluster, mgr, ticks=8)
        pod = cluster.get("v1", "Pod", "kubeflow", "victim-worker-0-0")
        envm = {e["name"]: e["value"]
                for e in pod["spec"]["containers"][0]["env"]}
        assert envm["KFTPU_RESUME_FROM"] == "/ckpt/victim"
        assert envm["KFTPU_CHECKPOINT_DIR"] == "/ckpt/victim"
        victim = get_job(cluster, "victim")
        assert k8s.annotations_of(victim).get(BINDING_ANNOTATION)
        assert k8s.get_condition(victim, COND_QUEUED)["status"] == "False"

    def test_deployed_scheduler_reads_configmap_quotas(self):
        # the tpu-scheduler manifest's ConfigMap is LIVE policy: a
        # default-constructed SliceScheduler (the deployment path) must
        # enforce the quotas it renders, not a silent built-in default
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="big")
        from kubeflow_tpu.manifests.training import tpu_scheduler
        for obj in tpu_scheduler(queues={"research": {
                "quotaChips": {"kubeflow": 8}}}):
            cluster.create(obj)
        mgr = Manager(cluster)
        mgr.add(SliceScheduler())    # no explicit config
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("a1"))
        cluster.create(tpujob("a2"))
        drive(cluster, mgr)
        a1 = get_job(cluster, "a1")
        a2 = get_job(cluster, "a2")
        assert k8s.annotations_of(a1).get(BINDING_ANNOTATION)
        assert not k8s.annotations_of(a2).get(BINDING_ANNOTATION)
        assert "quota" in k8s.annotations_of(a2)[
            "scheduling.kubeflow.org/reason"]
        for c in mgr.controllers:
            c.stop()

    def test_conflicting_bindings_requeue_not_crash(self, env):
        # two overlapping (well-formed) bindings — scheduler-replica
        # overlap during a rollout, or a hand-edited annotation — must
        # requeue the later job, not abort every future pass
        cluster, mgr = env
        cluster.create(tpujob("one"))
        drive(cluster, mgr)
        one = get_job(cluster, "one")
        stolen = k8s.annotations_of(one)[BINDING_ANNOTATION]
        manifest = tpujob("two")
        manifest["metadata"]["annotations"] = {BINDING_ANNOTATION: stolen}
        cluster.create(manifest)
        drive(cluster, mgr)   # must not raise / give up
        two = get_job(cluster, "two")
        assert not k8s.annotations_of(two).get(BINDING_ANNOTATION)
        assert k8s.condition_true(two, COND_QUEUED)
        # the original owner keeps its gang
        assert k8s.annotations_of(
            get_job(cluster, "one")).get(BINDING_ANNOTATION) == stolen

    def test_scheduler_pass_is_idempotent_no_write_storm(self, env):
        # a steady-state pass must not rewrite annotations: unchanged
        # patches would MODIFIED-storm the watch and spin the manager
        cluster, mgr = env
        cluster.create(tpujob("steady"))
        cluster.create(tpujob("waiting"))
        drive(cluster, mgr)
        rv_before = {
            k8s.name_of(j): j["metadata"]["resourceVersion"]
            for j in cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  "kubeflow")}
        sched = SliceScheduler()
        sched.reconcile(cluster, ("kubeflow", "steady"))
        rv_after = {
            k8s.name_of(j): j["metadata"]["resourceVersion"]
            for j in cluster.list("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  "kubeflow")}
        assert rv_before == rv_after

    def test_dashboard_reports_queue_state(self, env):
        from kubeflow_tpu.webapps.dashboard import build_dashboard_app
        cluster, mgr = env
        cluster.create(tpujob("running"))
        cluster.create(tpujob("parked", priority=0))
        drive(cluster, mgr)
        app = build_dashboard_app(cluster)
        status, body = app.dispatch("GET", "/api/sched/queues", b"")
        assert status == 200
        q = next(row for row in body if row["queue"] == "research")
        assert q["bound"] == 1 and q["queued"] == 1
        assert q["chipsBound"] == 8 and q["chipsQueued"] == 8
        states = {j["name"]: j["state"] for j in q["jobs"]}
        assert states == {"running": "bound", "parked": "queued"}


class TestNodeFlap:
    """Node Ready-condition flaps must not thrash bindings: writes
    happen on STATE CHANGE only (write-on-change), a flap on a host no
    binding covers writes nothing at all, and the replan after a real
    transition is deterministic."""

    def _set_ready(self, cluster, node_name, ready: bool):
        node = cluster.get("v1", "Node", "", node_name)
        node["status"]["conditions"] = [
            {"type": "Ready", "status": "True" if ready else "False"}]
        cluster.update(node)

    def _job_rvs(self, cluster):
        return {k8s.name_of(j): j["metadata"]["resourceVersion"]
                for j in cluster.list("tpu.kubeflow.org/v1alpha1",
                                      "TPUJob", "kubeflow")}

    def test_flap_on_uncovered_host_writes_nothing(self):
        # the v5e-8 gang carved out of the v5e-32 pool sits on hosts
        # 0+2 (rows 0-1, cols 0-3); host 7 (row 3, cols 4-7) flapping
        # must not touch the binding — the OLD bottom-row truncation
        # would have invalidated it (wrong host!) and thrashed the gang
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-32", pool="big")
        mgr = Manager(cluster)
        sched = SliceScheduler()
        mgr.add(sched)
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(tpujob("steady"))
        drive(cluster, mgr)
        binding_before = k8s.annotations_of(
            get_job(cluster, "steady"))[BINDING_ANNOTATION]
        rv_before = self._job_rvs(cluster)
        for ready in (False, True, False, True):
            self._set_ready(cluster, "big-v5e-32-7", ready)
            sched.reconcile(cluster, ("", "#cluster-pass"))
        assert self._job_rvs(cluster) == rv_before
        assert k8s.annotations_of(get_job(cluster, "steady"))[
            BINDING_ANNOTATION] == binding_before
        for c in mgr.controllers:
            c.stop()

    def test_covered_host_flap_write_on_change_holds(self, env):
        # a flap UNDER the binding is a real state change: the binding
        # drops (the gang cannot run on a dead host) and deterministically
        # re-places on recovery — but repeated passes in the SAME state
        # must write nothing (no write storm, no thrash loop)
        cluster, mgr = env
        cluster.create(tpujob("flappy"))
        drive(cluster, mgr)
        original = k8s.annotations_of(
            get_job(cluster, "flappy"))[BINDING_ANNOTATION]
        sched = next(c.reconciler for c in mgr.controllers
                     if isinstance(c.reconciler, SliceScheduler))
        self._set_ready(cluster, "tpu-pool-v5e-8-0", False)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        job = get_job(cluster, "flappy")
        assert not k8s.annotations_of(job).get(BINDING_ANNOTATION)
        # steady NotReady: repeated passes are write-idempotent
        rvs = self._job_rvs(cluster)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        sched.reconcile(cluster, ("", "#cluster-pass"))
        assert self._job_rvs(cluster) == rvs
        # recovery: exactly the same placement comes back (deterministic
        # packing), then steady Ready passes are write-idempotent again
        self._set_ready(cluster, "tpu-pool-v5e-8-0", True)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        assert k8s.annotations_of(get_job(cluster, "flappy"))[
            BINDING_ANNOTATION] == original
        rvs = self._job_rvs(cluster)
        sched.reconcile(cluster, ("", "#cluster-pass"))
        assert self._job_rvs(cluster) == rvs

    def test_chronic_flapper_quarantines_itself(self):
        # every Ready→NotReady transition folds a not-ready health
        # event; a chronically flapping host crosses the threshold and
        # is pulled from placement even while it reads Ready
        from kubeflow_tpu.scheduler import health as H
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8", pool="flappy")
        sched = SliceScheduler(SchedulerConfig(
            health=H.HealthConfig(quarantine_threshold=2.5)))
        sched.reconcile(cluster, ("", "#cluster-pass"))
        for _ in range(3):
            self._set_ready(cluster, "flappy-v5e-8-1", False)
            sched.reconcile(cluster, ("", "#cluster-pass"))
            self._set_ready(cluster, "flappy-v5e-8-1", True)
            sched.reconcile(cluster, ("", "#cluster-pass"))
        node = cluster.get("v1", "Node", "", "flappy-v5e-8-1")
        assert H.is_quarantined(node)
        assert H.health_of(node)["events"] == 3


class TestSimulation:
    def test_policies_dominate_fifo_on_seeded_contention(self):
        from kubeflow_tpu.scheduler.sim import compare_policies
        table = compare_policies([0, 1, 2], n_jobs=16,
                                 pools=("v5e-32",))
        fifo, pre = table["fifo"], table["preempt"]
        assert pre["chip_utilization"] > fifo["chip_utilization"]
        assert pre["queue_wait_p50"] < fifo["queue_wait_p50"]
        assert table["backfill"]["queue_wait_mean"] <= \
            fifo["queue_wait_mean"]
        # every job finishes under every policy (no starvation)
        assert all(row["unfinished"] == 0 for row in table.values())

    def test_simulation_is_seed_deterministic(self):
        from kubeflow_tpu.scheduler.sim import make_workload, simulate
        runs = [simulate(make_workload(3, n_jobs=10),
                         pools=("v5e-32",), policy="preempt")
                for _ in range(2)]
        assert runs[0] == runs[1]

    def test_preemption_respects_checkpoint_cadence(self):
        from kubeflow_tpu.scheduler.sim import make_workload, simulate
        row = simulate(make_workload(5, n_jobs=16), pools=("v5e-16",),
                       policy="preempt", checkpoint_every=4)
        if row["preemptions"]:
            # each preemption loses at most checkpoint_every-1 ticks
            assert row["recomputed_ticks"] <= \
                row["preemptions"] * 3


@pytest.mark.slow
@pytest.mark.compute
class TestPreemptionSoak:
    def test_preempted_job_matches_uncontended_params(self, tmp_path):
        import jax
        import numpy as np

        from kubeflow_tpu.cluster.chaos import final_params
        from kubeflow_tpu.scheduler.soak import PreemptionSoak

        soak = PreemptionSoak(workdir=str(tmp_path), total_steps=6,
                              checkpoint_every=2, preempt_at=4)
        report = soak.run()
        assert report["outcome"] == "succeeded", report
        assert report["victim_preempted_count"] == 1
        # the resume step is the forced checkpoint at preemption — the
        # re-bound gang continued, it did not replay from step 0
        assert report["victim_resume_step"] == 4
        preempted = final_params(report["checkpoint_dir"])
        clean = soak.uncontended_params()
        delta = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(np.max(np.abs(
                np.asarray(a) - np.asarray(b)))),
            preempted, clean)), default=0.0)
        assert delta <= 1e-5, f"params diverged by {delta}"
