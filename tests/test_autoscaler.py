"""Serving-autoscaler scenarios (ISSUE 18): the pure hysteresis policy
(fast up on pressure, slow down on sustained idle, cooldown flap
guard, min/max bounds, unpollable-blocks-down), the manifest-facing
AutoscalerConfig (loud on typos), and the ServingFleetReconciler
against a FakeCluster with fake poller + actuator. All jax-free."""

import pytest

from kubeflow_tpu.cluster.fake import FakeCluster
from kubeflow_tpu.controllers.autoscaler import (SERVING_FLEET_API_VERSION,
                                                 SERVING_FLEET_KIND,
                                                 AutoscalerConfig,
                                                 AutoscalerPolicy,
                                                 ReplicaSignals,
                                                 ServingFleetReconciler)

pytestmark = pytest.mark.serving_batch


def _cfg(**kw):
    base = dict(min_replicas=1, max_replicas=4, burn_up_threshold=2.0,
                queue_up_threshold=4.0, oldest_wait_up_s=0.5,
                idle_down_s=10.0, cooldown_s=5.0, poll_interval_s=1.0)
    base.update(kw)
    return AutoscalerConfig(**base)


def _idle(name="r0"):
    return ReplicaSignals(name=name)


def _busy(name="r0", **kw):
    sig = dict(queue_depth=10, oldest_wait_s=1.0, inflight=2,
               burn_fast=0.0)
    sig.update(kw)
    return ReplicaSignals(name=name, **sig)


# ---------------------------------------------------------------- policy


def test_scale_up_fast_on_burn_rate():
    p = AutoscalerPolicy(_cfg())
    d = p.decide([ReplicaSignals(name="r0", burn_fast=3.0)], 1, now=100.0)
    assert d.direction == "up"
    assert "burn" in d.reason


def test_scale_up_fast_on_queue_depth_per_replica():
    p = AutoscalerPolicy(_cfg())
    # 10 queued across 2 replicas = 5/replica ≥ 4 threshold
    d = p.decide([ReplicaSignals(name="a", queue_depth=6),
                  ReplicaSignals(name="b", queue_depth=4)], 2, now=1.0)
    assert d.direction == "up"
    assert "queue" in d.reason


def test_scale_up_fast_on_oldest_wait():
    p = AutoscalerPolicy(_cfg())
    d = p.decide([ReplicaSignals(name="r0", oldest_wait_s=0.9)], 1,
                 now=1.0)
    assert d.direction == "up"
    assert "oldest wait" in d.reason


def test_scale_up_blocked_at_max_replicas():
    p = AutoscalerPolicy(_cfg(max_replicas=2))
    d = p.decide([_busy("a"), _busy("b")], 2, now=1.0)
    assert d.direction is None
    assert "maxReplicas" in d.reason


def test_scale_up_blocked_inside_cooldown():
    p = AutoscalerPolicy(_cfg(cooldown_s=60.0))
    assert p.decide([_busy()], 1, now=0.0).direction == "up"
    d = p.decide([_busy()], 2, now=30.0)
    assert d.direction is None
    assert "cooldown" in d.reason
    # cooldown expired: pressure may scale again
    assert p.decide([_busy()], 2, now=61.0).direction == "up"


def test_scale_down_requires_sustained_idle():
    p = AutoscalerPolicy(_cfg(idle_down_s=10.0))
    assert p.decide([_idle("a"), _idle("b")], 2, now=0.0).direction is None
    # still inside the idle window: hold
    assert p.decide([_idle("a"), _idle("b")], 2, now=5.0).direction is None
    # sustained past idleDownSeconds: drain one
    assert p.decide([_idle("a"), _idle("b")], 2, now=11.0).direction == "down"


def test_momentary_lull_resets_the_idle_window():
    p = AutoscalerPolicy(_cfg(idle_down_s=10.0))
    p.decide([_idle("a"), _idle("b")], 2, now=0.0)
    # a burst interrupts the lull (not enough for scale-up pressure)
    p.decide([ReplicaSignals(name="a", inflight=1), _idle("b")], 2,
             now=8.0)
    # 11s after the FIRST idle poll, but the window restarted at t=9
    p.decide([_idle("a"), _idle("b")], 2, now=9.0)
    assert p.decide([_idle("a"), _idle("b")], 2, now=11.0).direction is None
    assert p.decide([_idle("a"), _idle("b")], 2, now=20.0).direction == "down"


def test_scale_down_blocked_at_min_replicas():
    p = AutoscalerPolicy(_cfg(min_replicas=1, idle_down_s=1.0))
    p.decide([_idle()], 1, now=0.0)
    d = p.decide([_idle()], 1, now=5.0)
    assert d.direction is None
    assert "minReplicas" in d.reason


def test_unpollable_replica_blocks_scale_down():
    """Missing data must read as unknown load, never as idle capacity
    to shed."""
    p = AutoscalerPolicy(_cfg(idle_down_s=1.0))
    p.decide([_idle("a"), None], 2, now=0.0)
    assert p.decide([_idle("a"), None], 2, now=5.0).direction is None


def test_one_lull_drains_one_replica_not_the_fleet():
    """After a scale-down the idle window restarts: the same long lull
    must not cascade a second drain right after the first."""
    p = AutoscalerPolicy(_cfg(idle_down_s=10.0, cooldown_s=0.0,
                              min_replicas=1))
    idle3 = [_idle("a"), _idle("b"), _idle("c")]
    p.decide(idle3, 3, now=0.0)
    assert p.decide(idle3, 3, now=11.0).direction == "down"
    # immediately after: a fresh full idle window is required
    assert p.decide(idle3[:2], 2, now=12.0).direction is None
    assert p.decide(idle3[:2], 2, now=22.0).direction == "down"


def test_cooldown_guards_down_then_up_flap():
    p = AutoscalerPolicy(_cfg(idle_down_s=1.0, cooldown_s=60.0))
    p.decide([_idle("a"), _idle("b")], 2, now=0.0)
    assert p.decide([_idle("a"), _idle("b")], 2, now=2.0).direction == "down"
    # pressure right behind the drain: the cooldown holds it
    d = p.decide([_busy("a")], 1, now=10.0)
    assert d.direction is None
    assert "cooldown" in d.reason


def test_draining_replica_is_not_pressure():
    p = AutoscalerPolicy(_cfg())
    d = p.decide([_idle("a"), _busy("b", draining=True)], 2, now=1.0)
    assert d.direction is None


# ---------------------------------------------------------------- config


def test_config_round_trips_through_manifest_keys():
    cfg = _cfg(min_replicas=2, max_replicas=8)
    again = AutoscalerConfig.from_dict(cfg.to_dict())
    assert again == cfg
    assert set(cfg.to_dict()) == set(AutoscalerConfig.KEYS)


def test_config_rejects_unknown_keys_loudly():
    with pytest.raises(ValueError, match="maxReplica"):
        AutoscalerConfig.from_dict({"maxReplica": 3})  # typo'd knob


def test_signals_aggregate_over_models():
    snap = {"draining": False,
            "models": [
                {"queueDepth": 3, "inFlight": 1, "oldestWaitSeconds": 0.2,
                 "burnRates": {"60s": {"latency": 0.5}}},
                {"queueDepth": 2, "inFlight": 0, "oldestWaitSeconds": 0.7,
                 "burnRates": {"60s": {"availability": 2.5}}},
            ]}
    sig = ReplicaSignals.from_snapshot("r0", snap)
    assert sig.queue_depth == 5
    assert sig.inflight == 1
    assert sig.oldest_wait_s == 0.7
    assert sig.burn_fast == 2.5
    assert not sig.draining


# ------------------------------------------------------------ reconciler


class _FakeActuator:
    def __init__(self):
        self.ups = 0
        self.downs = []

    def scale_up(self):
        self.ups += 1
        return {"name": f"scaled-{self.ups}",
                "url": f"http://127.0.0.1:{9000 + self.ups}",
                "startKind": "warm"}

    def scale_down(self, name):
        self.downs.append(name)


def _fleet_obj(autoscaler=None, endpoints=("http://127.0.0.1:8500",)):
    return {"apiVersion": SERVING_FLEET_API_VERSION,
            "kind": SERVING_FLEET_KIND,
            "metadata": {"name": "fleet", "namespace": "serving"},
            "spec": {"model": "resnet18", "endpoints": list(endpoints),
                     "autoscaler": autoscaler or
                     {"minReplicas": 1, "maxReplicas": 3,
                      "idleDownSeconds": 10.0, "cooldownSeconds": 0.0,
                      "pollIntervalSeconds": 0.5}}}


def _mk(cluster, signals_by_name, t):
    """Reconciler with a fake poller (name → ReplicaSignals) and a
    settable clock."""
    rec = ServingFleetReconciler(
        actuator=_FakeActuator(),
        poller=lambda name, url, **kw: signals_by_name.get(name),
        clock=lambda: t[0])
    return rec


def test_reconciler_scales_up_on_pressure_and_publishes_status():
    fc = FakeCluster()
    fc.create(_fleet_obj())
    t = [0.0]
    signals = {"fleet-0": _busy("fleet-0")}
    rec = _mk(fc, signals, t)
    res = rec.reconcile(fc, ("serving", "fleet"))
    assert res.requeue_after == 0.5
    obj = fc.get(SERVING_FLEET_API_VERSION, SERVING_FLEET_KIND,
                 "serving", "fleet")
    st = obj["status"]
    names = [r["name"] for r in st["replicas"]]
    assert names == ["fleet-0", "scaled-1"]
    assert st["observedReplicas"] == 2
    assert st["lastScale"]["direction"] == "up"
    assert rec.actuator.ups == 1


def test_reconciler_scales_down_after_sustained_idle():
    fc = FakeCluster()
    fc.create(_fleet_obj(endpoints=("http://a", "http://b")))
    t = [0.0]
    signals = {"fleet-0": _idle("fleet-0"), "fleet-1": _idle("fleet-1")}
    rec = _mk(fc, signals, t)
    rec.reconcile(fc, ("serving", "fleet"))      # idle window opens
    t[0] = 11.0
    rec.reconcile(fc, ("serving", "fleet"))      # sustained → drain
    obj = fc.get(SERVING_FLEET_API_VERSION, SERVING_FLEET_KIND,
                 "serving", "fleet")
    assert [r["name"] for r in obj["status"]["replicas"]] == ["fleet-0"]
    assert rec.actuator.downs == ["fleet-1"]     # LIFO victim
    assert obj["status"]["lastScale"]["direction"] == "down"


def test_reconciler_respects_cooldown_between_events():
    fc = FakeCluster()
    fc.create(_fleet_obj(autoscaler={"minReplicas": 1, "maxReplicas": 3,
                                     "cooldownSeconds": 60.0}))
    t = [0.0]
    signals = {"fleet-0": _busy("fleet-0"), "scaled-1": _busy("scaled-1")}
    rec = _mk(fc, signals, t)
    rec.reconcile(fc, ("serving", "fleet"))
    t[0] = 10.0                                   # still pressured, in cooldown
    rec.reconcile(fc, ("serving", "fleet"))
    obj = fc.get(SERVING_FLEET_API_VERSION, SERVING_FLEET_KIND,
                 "serving", "fleet")
    assert obj["status"]["observedReplicas"] == 2  # no second event
    assert rec.actuator.ups == 1


def test_reconciler_bad_config_raises_loudly():
    fc = FakeCluster()
    fc.create(_fleet_obj(autoscaler={"maxReplica": 3}))
    rec = ServingFleetReconciler(poller=lambda *a, **k: None)
    with pytest.raises(ValueError, match="maxReplica"):
        rec.reconcile(fc, ("serving", "fleet"))


def test_reconciler_forgets_deleted_fleet():
    fc = FakeCluster()
    fc.create(_fleet_obj())
    t = [0.0]
    rec = _mk(fc, {"fleet-0": _busy("fleet-0")}, t)
    rec.reconcile(fc, ("serving", "fleet"))
    assert ("serving", "fleet") in rec._policies
    fc.delete(SERVING_FLEET_API_VERSION, SERVING_FLEET_KIND,
              "serving", "fleet")
    res = rec.reconcile(fc, ("serving", "fleet"))
    assert ("serving", "fleet") not in rec._policies
    assert not res.requeue_after  # gone: no periodic requeue


def test_reconciler_without_actuator_is_declarative_only():
    """No actuator: the reconciler publishes desiredReplicas (the
    HPA-writes-the-scale-subresource shape) but touches nothing."""
    fc = FakeCluster()
    fc.create(_fleet_obj())
    t = [0.0]
    rec = ServingFleetReconciler(
        poller=lambda name, url, **kw: _busy(name), clock=lambda: t[0])
    rec.reconcile(fc, ("serving", "fleet"))
    obj = fc.get(SERVING_FLEET_API_VERSION, SERVING_FLEET_KIND,
                 "serving", "fleet")
    assert obj["status"]["observedReplicas"] == 1   # unchanged
    assert obj["status"]["desiredReplicas"] == 2    # the ask is published


def test_reconciler_registered_with_controller_manager():
    from kubeflow_tpu.controllers.__main__ import (CONTROLLER_FACTORIES,
                                                   _register_defaults)
    _register_defaults()
    assert CONTROLLER_FACTORIES["autoscaler"] is ServingFleetReconciler


def test_live_fetch_signals_reads_verbose_healthz():
    """fetch_signals against a real in-process replica (ChaosServable —
    no jax): queued work shows up as queue_depth/oldest_wait."""
    from kubeflow_tpu.cluster.chaos import ServingReplicaHarness
    from kubeflow_tpu.controllers.autoscaler import fetch_signals
    h = ServingReplicaHarness("sig0", model="m", predict_s=0.01)
    try:
        url = h.start()
        sig = fetch_signals("sig0", url, timeout_s=2.0)
        assert sig is not None
        assert sig.name == "sig0"
        assert not sig.draining
        assert sig.queue_depth == 0
    finally:
        h.stop()
    # a dead replica polls as None, never raises
    assert fetch_signals("sig0", url, timeout_s=0.5) is None
