"""Two-process jax.distributed exercise (r2 verdict #5).

Renders the KFTPU_* contract exactly as the TPUJob operator does
(render_contracts), spawns two real OS processes, and asserts the
DISTRIBUTED branch of bootstrap.initialize runs: coordinator rendezvous,
8 global devices from 2×4 local, a cross-process reduction, and the full
worker train loop with cross-process gradient all-reduce."""

from __future__ import annotations

import concurrent.futures
import json
import os
import socket
import subprocess
import sys

import pytest

from kubeflow_tpu.api.topology import parse_topology, render_contracts


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_children(job_name: str, child_basename: str, timeout: float,
                  topology: str = "v5e-8",
                  num_slices: int = 1) -> list[dict]:
    """Spawn one child per contract host and collect their JSON lines.

    Pipes are drained CONCURRENTLY (a chatty child blocking on a full
    stderr pipe while its peer waits at a collective is a mutual
    deadlock), and every child is killed on any failure/timeout so a
    broken run can't leak processes into the rest of the session."""
    port = _free_port()
    contracts = render_contracts(job_name, "default",
                                 parse_topology(topology),
                                 num_slices=num_slices)
    assert len(contracts) == 2  # 2 processes either way (hosts x slices)
    child = os.path.join(os.path.dirname(__file__), child_basename)

    procs = []
    try:
        for contract in contracts:
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # the child pins its own devices
            env.update(contract.to_env())
            # pod DNS doesn't resolve here; point at the local coordinator
            env["KFTPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
            env["PYTHONPATH"] = REPO
            procs.append(subprocess.Popen(
                [sys.executable, child], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        with concurrent.futures.ThreadPoolExecutor(len(procs)) as pool:
            futures = [pool.submit(p.communicate, timeout=timeout)
                       for p in procs]
            results = [f.result(timeout=timeout + 30) for f in futures]
    except BaseException:
        for p in procs:
            p.kill()
        raise
    outs = []
    for p, (out, err) in zip(procs, results):
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert {o["process_id"] for o in outs} == {0, 1}
    for o in outs:
        assert o["num_processes"] == 2
    return outs


@pytest.mark.slow
def test_two_process_psum():
    outs = _run_children("dj", "_distributed_child.py", timeout=240)
    for o in outs:
        assert o["global_devices"] == 8
        assert o["local_devices"] == 4
        # sum over the 8-element global arange — identical on every process
        assert o["sum"] == sum(range(8))
        assert o["mesh"]["data"] == 8


@pytest.mark.slow
def test_two_process_full_train_loop():
    """The whole worker loop — sharded init, global batch placement, jitted
    step with cross-process gradient reduction — over two real processes.
    Both processes must observe the IDENTICAL loss trajectory (the gradient
    all-reduce makes the replicated state bit-identical)."""
    outs = _run_children("mptrain", "_distributed_train_child.py",
                         timeout=280)
    for o in outs:
        assert o["steps"] == 3
    assert outs[0]["loss"] == outs[1]["loss"]
    assert outs[0]["grad_norm"] == outs[1]["grad_norm"]


@pytest.mark.slow
def test_two_slice_dcn_train_loop():
    """MULTI-SLICE: two v5e-4 slices (one host each) — the processes sit on
    opposite sides of the modeled DCN boundary, so the data axis spans
    slices (DCN-major mesh order) and the gradient all-reduce crosses it.
    Same bit-identical-trajectory bar as the single-slice test."""
    outs = _run_children("dcn", "_distributed_train_child.py", timeout=280,
                         topology="v5e-4", num_slices=2)
    for o in outs:
        assert o["steps"] == 3
    assert outs[0]["loss"] == outs[1]["loss"]
    assert outs[0]["grad_norm"] == outs[1]["grad_norm"]
