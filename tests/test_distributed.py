"""Two-process jax.distributed exercise (r2 verdict #5).

Renders the KFTPU_* contract exactly as the TPUJob operator does
(render_contracts), spawns two real OS processes, and asserts the
DISTRIBUTED branch of bootstrap.initialize runs: coordinator rendezvous,
8 global devices from 2×4 local, and a cross-process reduction producing
the same global sum on both processes."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from kubeflow_tpu.api.topology import parse_topology, render_contracts

CHILD = os.path.join(os.path.dirname(__file__), "_distributed_child.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_psum():
    port = _free_port()
    contracts = render_contracts("dj", "default", parse_topology("v5e-8"))
    assert len(contracts) == 2  # v5e-8 = 2 hosts -> 2 processes

    procs = []
    for contract in contracts:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the child pins its own device count
        env.update(contract.to_env())
        # pod DNS doesn't resolve here; point at the local coordinator
        env["KFTPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["PYTHONPATH"] = REPO
        procs.append(subprocess.Popen(
            [sys.executable, CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_id = {o["process_id"]: o for o in outs}
    assert set(by_id) == {0, 1}
    for o in outs:
        assert o["num_processes"] == 2
        assert o["global_devices"] == 8
        assert o["local_devices"] == 4
        # sum over the 8-element global arange — identical on every process
        assert o["sum"] == sum(range(8))
        assert o["mesh"]["data"] == 8
